"""Benchmark: embeddings/sec/chip (+ MFU) for the flagship training step.

Measures the flagship workload: the precision-policy flagship trunk
(``googlenet_mxu`` — s2d stem + fused inception 1x1s — under the "mxu"
mixed-precision policy: bf16 compute / fp32 params / single-pass bf16
MXU gemms, models.precision) + L2 normalize + mined N-pair loss (shipped
def.prototxt mining config, policy-precision gemms) + analytic backward
+ Caffe-SGD update + in-graph Recall@{1,5,10} metrics, batch 120 (60 ids
x 2 imgs, def.prototxt:21-27), as ONE jitted graph on the current
accelerator.  The prototxt-parity recipes stay measured alongside: the
``googlenet_fp32_parity`` batch row (fp32 everything) and the plain-
trunk ``120`` row (the pre-policy bf16 headline), plus the reported
``policy_fp32_loss_delta`` (same trunk/params under both recipes).

The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
compares against a documented estimate of the Caffe+MPI original on its
contemporary GPU: ~400 embeddings/sec/GPU (GoogLeNet fwd+bwd at ~75 ms per
batch-32 on a Maxwell Titan X scaled to batch 120, plus the loss layer's
per-step host mining loop and CPU-buffer MPI round trips). North-star
target is >= 4x (BASELINE.json).

Timing discipline: the tunneled axon backend neither blocks in
``block_until_ready`` nor re-executes identical dispatches (it memoizes
them), so every measurement here chains DISTINCT computations (solver
state threading, or per-step input perturbation inside one lax.scan),
synchronizes by fetching a scalar to the host, and subtracts the
measured dispatch+fetch latency floor (``_fetch_floor``).

Robustness contract (this script must ALWAYS print one JSON line):
the top-level process imports no jax — every measurement runs in a child
subprocess under a wall-clock timeout, with escalating fallbacks:

    1. backend probe (which platform actually initializes?)
    2. full flagship bench on that platform
    3. --smoke bench (tiny MLP, 5 steps) on that platform
    4. --smoke bench on CPU
    5. an explicit error record (value 0.0) — never a silent rc=1

Children print per-phase progress to stderr and the result JSON to
stdout; the persistent compilation cache (.jax_cache/) makes reruns and
driver retries cheap.  MFU comes from XLA's own per-step FLOPs estimate
(compiled.cost_analysis()) against the chip's peak; extra engine
measurements (dense vs Pallas-blockwise loss at pool 4096) ride in the
"extras" field of the same single line.

Wedge containment (2026-08-01: the blockwise_flagship_radix compile
wedged the tunnel mid-extras, which would have discarded the already-
measured headline): the full child spills its partial record to
/tmp/bench_spill.json after the headline and after every extras row,
marking which row is in flight; if the child dies, the parent salvages
the spill as a "salvaged": true full record and quarantines the
in-flight row in bench_cache/quarantine.json (committed) so later runs
skip it instead of re-wedging the tunnel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_EMBEDDINGS_PER_SEC = 400.0
# Geometry env overrides exist so the full-child orchestration (spill /
# salvage / quarantine) can be driven end-to-end on CPU at toy scale;
# driver runs never set them, so recorded artifacts use the reference
# geometry (batch 120 @ 224, def.prototxt:21-27).
BATCH = int(os.environ.get("BENCH_BATCH", 120))
IMAGE = int(os.environ.get("BENCH_IMAGE", 224))
REPO = os.path.dirname(os.path.abspath(__file__))
# Persistent XLA compilation cache, COMMITTED under bench_cache/ so
# tunnel windows spend their minutes measuring instead of recompiling:
# any process (bench children, CLI runs with --compile-cache, the
# Solver.warmup AOT path) that compiled a program saves every later
# process the compile (docs/PIPELINE.md).
CACHE_DIR = os.path.join(REPO, "bench_cache", "xla_cache")
# Committed last-known-good hardware payload (refreshed on every
# successful full TPU run).  When the tunnel is down the degraded record
# carries this payload with "stale": true instead of zeroing the round
# (round-3 lesson: BENCH_r03.json came back rc=124 / parsed null).
LAST_GOOD_PATH = os.path.join(REPO, "bench_cache", "last_good.json")
METRIC = "googlenet_npair_train_embeddings_per_sec_per_chip"
UNIT = "embeddings/sec/chip"
# Partial-record spill: written by the full child after the headline and
# after every extras row so a mid-extras tunnel wedge cannot discard
# what was already measured (parent salvages it on child death).  The
# parent pins a pid-scoped path into the child's environment so
# concurrent bench runs on one machine cannot clobber or cross-salvage
# each other's spills.
SPILL_PATH = os.environ.get(
    "BENCH_SPILL_PATH", f"/tmp/bench_spill.{os.getuid()}.json"
)
# Rows observed in flight when a child wedged the tunnel.  Committed so
# the driver's fresh round-end run skips them too — one lost row beats a
# voided round.  Clear an entry manually to re-try the row.
QUARANTINE_PATH = os.path.join(REPO, "bench_cache", "quarantine.json")

# Peak-FLOP/s table, cost analysis, and the MFU computation live in
# npairloss_tpu/obs/perf/costs.py (mfu_from_timing) — one home, shared
# with the CLI `time`/`prof` subcommands (utils.profiling re-exports).

# Every final parent record also lands here as one JSONL row with the
# obs envelope (run_id/step/wall_time/phase) — the bench trajectory as a
# structured sink the BENCH_*.json stdout line is a derived view of.
TELEMETRY_LOG = os.path.join(REPO, "bench_cache", "bench_history.jsonl")


def _log(msg: str) -> None:
    print(f"[bench t={time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.time()


def _load_obs_sinks():
    """File-path import of the stdlib-only sinks module.  The parent must
    NOT import the npairloss_tpu package — its ``__init__`` pulls jax,
    and a hung backend import would defeat this file's no-jax-in-parent
    robustness contract (same trick as cli.cmd_bench in reverse)."""
    import importlib.util

    path = os.path.join(REPO, "npairloss_tpu", "obs", "sinks.py")
    spec = importlib.util.spec_from_file_location("_npair_obs_sinks", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _emit(rec) -> int:
    """Publish the parent's ONE JSON line (the historical stdout/BENCH_*
    format — kept byte-compatible as the derived view) and append the
    same payload to the committed JSONL history sink."""
    print(json.dumps(rec))
    try:
        sinks = _load_obs_sinks()
        sink = sinks.JsonlSink(TELEMETRY_LOG)
        # Envelope stamped LAST so it always wins over record keys (the
        # same contract RunTelemetry.log pins) — a future bench key named
        # "step"/"wall_time" must not corrupt the history rows.
        row = dict(rec)
        row.update(
            run_id=f"bench-{int(_T0)}-{os.getpid()}",
            step=0,
            wall_time=time.time(),
            phase="bench",
        )
        sink.log(row)
        sink.close()
    except Exception as e:  # the sink must never cost the bench line
        _log(f"bench history sink append failed (non-fatal): {e}")
    return 0


# ---------------------------------------------------------------------------
# Child: actual measurement (runs under a parent-enforced timeout)
# ---------------------------------------------------------------------------


def _child_setup(platform: str):
    import jax

    if platform == "cpu":
        # The axon TPU plugin ignores JAX_PLATFORMS from the shell env —
        # forcing CPU must go through jax.config before backend init.
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    try:
        # One home for the cache knobs (pipeline.enable_compile_cache) —
        # the same helper the CLI's --compile-cache uses, so bench and
        # training runs share bench_cache/xla_cache/ entries.
        from npairloss_tpu.pipeline import enable_compile_cache

        enable_compile_cache(CACHE_DIR)
    except Exception as e:  # cache is an optimization, never a requirement
        _log(f"compilation cache unavailable: {e}")
    _log("importing backend...")
    dev = jax.devices()[0]
    _log(f"backend up: platform={dev.platform} kind={dev.device_kind}")
    return jax, dev


def _mfu_estimate(compiled, dt: float, steps: int, device_kind: str):
    """``{"step_flops", "mfu"}`` (values possibly None) via THE shared
    helper (obs.perf.costs.mfu_from_timing) — bench must never grow its
    own flops/peak arithmetic again."""
    from npairloss_tpu.utils.profiling import mfu_from_timing

    est = mfu_from_timing(compiled, seconds=dt, steps=steps,
                          device_kind=device_kind)
    if est["step_flops"] is None:
        _log("cost_analysis unavailable")
    return est


def child_probe(platform: str) -> int:
    """Print which backend initializes (and its device kind) as JSON.

    Everything is jitted: eager ops on the axon TPU backend are one
    tunnel round-trip EACH and can wedge the tunnel for minutes
    (environment gotcha, .claude/skills/verify).
    """
    import jax

    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x @ x

    import numpy as np

    y = f(jnp.ones((128, 128))).block_until_ready()
    # np.asarray is one device_get, not an eager indexing op.
    assert float(np.asarray(y)[0, 0]) == 128.0
    print(json.dumps({"platform": dev.platform, "kind": dev.device_kind}))
    return 0


def _fetch_floor(jax):
    """Dispatch+fetch latency floor of the backend, measured.

    On tunneled backends (axon) ``block_until_ready`` can return before
    device compute finishes and identical dispatches may be served from a
    memo cache — so every timing in this file (a) chains DISTINCT
    computations and (b) synchronizes by fetching a scalar to the host,
    then subtracts this floor (observed ~66 ms per round trip on the
    axon tunnel, microseconds locally).  One home for the discipline:
    ``utils.profiling.dispatch_floor`` (process-wide salted probes).
    """
    from npairloss_tpu.utils.profiling import dispatch_floor

    floor = dispatch_floor()
    _log(f"fetch floor: {floor * 1e3:.1f} ms")
    return floor


def _measure(step, args_list, warmup: int, steps: int, fetch, floor=0.0,
             repeats=2, deadline=None):
    """Time ``steps`` sequential calls per window; sync via ``fetch`` (a
    host device_get), subtract the dispatch/fetch ``floor``.  The
    ``step`` calls must be genuinely distinct computations (chained
    state or varying inputs) — see ``_fetch_floor`` for why.

    Returns the per-window seconds (min is the published number): tunnel
    latency jitters (the 08:04 UTC 2026-08-01 capture clocked dense_abs
    at 60.6 ms/step vs 9.1 in round 2 — a transient spike inside the
    single timed window), a spike can only inflate, and publishing every
    window keeps an anomalous min diagnosable in the artifact.  A window
    past ``deadline`` is skipped (budget guard for tail rows)."""
    for i in range(warmup):
        _log(f"warmup {i + 1}/{warmup}")
        out = step(*args_list)
        fetch(out)
    dts = []
    for r in range(repeats):
        if dts and deadline is not None and time.time() > deadline:
            _log("skipping further timing windows (soft budget)")
            break
        _log(f"timing {steps} steps (window {r + 1}/{repeats})...")
        t0 = time.perf_counter()
        out = None
        for _ in range(steps):
            out = step(*args_list)
        fetch(out)
        dts.append(max(time.perf_counter() - t0 - floor, 1e-9))
    return dts


def child_full(platform: str, steps: int, warmup: int,
               soft_budget: float = 900.0, rows: str = None) -> int:
    # --rows (ADVICE #2): a selective re-pass measures ONLY the named
    # rows ("headline", engine-extras names, batch_scaling keys) instead
    # of re-running the ~20-row sweep — a re-pass for wedge-lost tail
    # rows no longer spends ~70 min of tunnel re-measuring what the
    # first pass already captured.  The emitted record carries
    # rows_filter, and _save_last_good MERGES it into the existing
    # payload instead of replacing it.
    selected = None
    if rows:
        selected = {r.strip() for r in rows.split(",") if r.strip()}
        _log(f"selective re-measure (--rows): {sorted(selected)}")
    jax, dev = _child_setup(platform)
    import jax.numpy as jnp
    import numpy as np

    floor = _fetch_floor(jax)
    measure_headline = selected is None or "headline" in selected
    reused = None
    if not measure_headline:
        reused = (_load_last_good() or {}).get("payload") or None
        if not (reused and reused.get("value")):
            _log("--rows without 'headline' but no last-good payload to "
                 "reuse — measuring the headline anyway")
            measure_headline, reused = True, None

    if measure_headline:
        from npairloss_tpu.models import FLAGSHIP_POLICY, FLAGSHIP_TRUNK

        _log(f"building flagship solver ({FLAGSHIP_TRUNK} under the "
             f"{FLAGSHIP_POLICY!r} precision policy, batch {BATCH})")
        # The headline IS the precision-policy flagship (ISSUE 7): the
        # parity-preserving MXU trunk (s2d stem + fused 1x1s) under the
        # "mxu" policy — bf16 compute / fp32 params / single-pass bf16
        # MXU gemms through trunk AND loss engines.  The prototxt-parity
        # fp32 recipe stays measured as the googlenet_fp32_parity batch
        # row, and the policy-vs-fp32 loss delta is reported below.
        # Built via the SAME constructor child_warmup("headline") uses,
        # so the AOT-warmed program IS the measured program by
        # construction, not by keeping two call sites in lockstep.
        solver = _solver_for_spec(
            jnp, FLAGSHIP_TRUNK, {"policy": FLAGSHIP_POLICY}, {})
        from npairloss_tpu.utils.profiling import next_timing_salt

        rng = np.random.default_rng(0)
        images = rng.standard_normal(
            (BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
        labels = np.repeat(np.arange(BATCH // 2), 2).astype(np.int32)
        # Per-run input salt: the tunnel memo is keyed on argument VALUES
        # (even across processes — utils/profiling.py), and the seeded rng
        # would otherwise make a supervisor-retried run re-dispatch the
        # previous run's exact value sequence and time memo hits.
        x = jax.device_put(jnp.asarray(images + next_timing_salt() * 1e-6))
        lab = jax.device_put(jnp.asarray(labels))

        _log("compiling + warming up (first TPU compile can take minutes)...")
        # Successive solver.step calls chain through the optimizer state,
        # so each dispatch is a distinct computation (no memo-cache
        # hazard).
        dts = _measure(
            lambda a, b: solver.step(a, b),
            [x, lab],
            warmup,
            steps,
            lambda m: float(np.asarray(m["loss"])),
            floor,
        )
        dt = min(dts)
        emb_per_sec = BATCH * steps / dt
        _log(f"flagship: {emb_per_sec:.1f} emb/s "
             f"({dt / steps * 1e3:.1f} ms/step)")

        # MFU from XLA's own FLOPs estimate of the jitted train step.
        mfu = None
        step_flops = None
        try:
            compiled = solver._step_fn.lower(
                solver.state, x, lab
            ).compile()
            est = _mfu_estimate(compiled, dt, steps, dev.device_kind)
            step_flops, mfu = est["step_flops"], est["mfu"]
            if mfu is not None:
                _log(f"mfu={mfu:.3f} (step_flops={step_flops:.3e})")
        except Exception as e:
            _log(f"mfu estimate failed: {e}")

    # Extras must never cost the headline: the parent kills this child at
    # --full-timeout, so every extra row checks a soft deadline and
    # records itself as skipped instead of overrunning (the row count
    # grew round 4: sim-cache on/off + s2d + remat).
    deadline = _T0 + 0.75 * soft_budget
    record = {
        "metric": "googlenet_npair_train_embeddings_per_sec_per_chip",
        "unit": "embeddings/sec/chip",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        # Stamped up front so even a wedge-salvaged spill record carries
        # the floor the run was measured against.
        "fetch_floor_ms": round(floor * 1e3, 1),
        "mode": "full",
        # Geometry is stamped so a BENCH_BATCH/BENCH_IMAGE toy run can
        # never masquerade as a reference-geometry artifact (and
        # _save_last_good refuses non-reference geometry outright).
        "batch": BATCH,
        "image": IMAGE,
    }
    if measure_headline:
        # Which recipe this headline measures — the policy flagship's
        # identity travels with the number (bench_check gates a policy
        # headline against the measured googlenet_mxu bar).
        from npairloss_tpu.models import FLAGSHIP_POLICY, FLAGSHIP_TRUNK

        record["trunk"] = FLAGSHIP_TRUNK
        record["policy"] = FLAGSHIP_POLICY
        record.update(
            value=round(emb_per_sec, 2),
            vs_baseline=round(emb_per_sec / BASELINE_EMBEDDINGS_PER_SEC, 3),
            ms_per_step=round(dt / steps * 1e3, 2),
            ms_per_step_windows=[round(d / steps * 1e3, 2) for d in dts],
        )
        if mfu is not None:
            record["mfu"] = round(mfu, 4)
        if step_flops is not None:
            record["step_flops"] = step_flops
    else:
        # Headline carried over from last_good (flagged): a rows-only
        # record must still print the driver-contract keys, but its
        # headline is REUSED evidence, not a fresh measurement — the
        # merge in _save_last_good never lets it clobber a measured one.
        record.update(
            value=float(reused.get("value", 0.0)),
            vs_baseline=float(reused.get("vs_baseline", 0.0)),
            headline_reused=True,
        )
        for k in ("ms_per_step", "ms_per_step_windows", "mfu",
                  "step_flops"):
            if k in reused:
                record[k] = reused[k]
    if selected is not None:
        record["rows_filter"] = sorted(selected)
    # The headline is now wedge-proof: every extras row below re-spills
    # the record, so a mid-row tunnel wedge costs that row, not the run.
    extras = {}
    record["extras"] = extras

    def flush(inflight=None):
        _write_spill(record, inflight)

    flush()
    if measure_headline and not _quarantined("policy_loss_delta"):
        # The recorded price of the policy (ISSUE 7 acceptance: loss
        # delta vs fp32 parity bounded and reported): same trunk, same
        # trained params, one forward+loss under each recipe.  Device
        # work -> same inflight/quarantine containment as a row.
        flush("policy_loss_delta")
        try:
            record.update(_policy_loss_delta(jax, jnp, np, solver, x, lab))
            _log("policy vs fp32_parity loss delta: "
                 f"{record.get('policy_fp32_loss_delta')}")
        except Exception as e:
            _log(f"policy loss delta failed (non-fatal): {e}")
        flush()
    try:
        _engine_extras(jax, jnp, np, floor, deadline, extras, flush,
                       selected)
    except Exception as e:
        _log(f"engine extras failed: {e}")
    try:
        rows_out = {}
        extras["batch_scaling"] = rows_out
        _batch_scaling_extras(jax, jnp, np, dev, floor, deadline, rows_out,
                              flush, selected)
    except Exception as e:
        _log(f"batch scaling extras failed: {e}")
    # Floor drift diagnostic: a row whose ms_per_step disagrees wildly
    # with its sibling runs (dense_abs 60.6 vs 9.1, 08:04 UTC capture)
    # is explained — or not — by the tunnel's latency floor moving.
    # This probe dispatches device work, so it gets the same inflight
    # containment as a row — a wedge here must not demote a fully-
    # measured run to a headline-less salvage.
    if not _quarantined("fetch_floor_end"):
        flush("fetch_floor_end")
        try:
            record["fetch_floor_end_ms"] = round(_fetch_floor(jax) * 1e3, 1)
        except Exception:
            pass
    flush()
    if not extras.get("batch_scaling"):
        extras.pop("batch_scaling", None)
    if not extras:
        del record["extras"]
    print(json.dumps(record))
    return 0


def _policy_loss_delta(jax, jnp, np, solver, x, lab):
    """``|loss(mxu policy) - loss(fp32_parity)|`` on the SAME flagship
    trunk, SAME (post-measurement) params, SAME batch — the honest
    apples-to-apples price of the single-pass-bf16 recipe, reported in
    the headline record (and bounded by tests/test_precision_policy.py
    at test scale)."""
    from npairloss_tpu.models import FLAGSHIP_TRUNK, get_model
    from npairloss_tpu.train import Solver, SolverConfig

    s32 = Solver(
        get_model(FLAGSHIP_TRUNK, policy="fp32_parity"),
        solver.loss_cfg,
        SolverConfig(display=0, snapshot=0),
        input_shape=solver.input_shape,
        precision="fp32_parity",
    )
    s32.state = solver.state  # fp32 master params: shared verbatim

    def one_loss(s):
        def f(state, xx, ll):
            emb, _ = s.apply_model(
                state["params"], state["batch_stats"], xx, train=True)
            loss, _ = s.compute_loss(emb, ll)
            return loss

        return float(np.asarray(jax.jit(f)(s.state, x, lab)))

    l_pol = one_loss(solver)
    l_32 = one_loss(s32)
    return {
        "policy_loss": round(l_pol, 6),
        "fp32_parity_loss": round(l_32, 6),
        "policy_fp32_loss_delta": round(abs(l_pol - l_32), 6),
    }


# Engine-extras row names — the vocabulary --rows selects from (plus
# "headline" and the batch_scaling keys in _batch_scaling_extras).
ENGINE_ROWS = (
    "dense_abs", "blockwise_abs", "dense_flagship", "blockwise_flagship",
    "blockwise_flagship_nocache", "blockwise_flagship_radix",
    "blockwise_flagship_bf16matmul", "dense_flagship_bf16matmul",
    "ring_abs", "ring_flagship", "ring_flagship_nocache",
    "ring_flagship_bf16matmul", "serve_qps",
    "flat_qps_1m", "ivf_qps_1m", "ivf_fused_qps_1m",
    "ivf_probe_kernel_micro",
)


def _engine_extras(jax, jnp, np, floor, deadline=None, extras=None,
                   flush=None, selected=None):
    """Loss-engine comparison at a large self-pool: dense XLA graph vs the
    Pallas blockwise kernels (compiled by Mosaic when on TPU — this is the
    on-hardware validation of ops/pallas_npair.py) vs the ring engine on a
    1-device mesh, fwd+bwd each.

    Each engine is timed as ``steps`` loss+grad evaluations inside
    ONE jitted ``lax.scan`` (inputs perturbed per step so no two steps are
    identical), synced by a single host fetch — robust against the
    non-blocking/memoizing tunnel backend (see ``_fetch_floor``).
    """
    n, d = 4096, 512
    steps = 10
    if extras is None:
        extras = {}
    if flush is None:
        flush = lambda inflight=None: None  # noqa: E731
    extras.update({"pool": n, "steps": steps})
    try:
        # DCN-aware plan provenance (parallel.plan): what the engine
        # selector would choose for THIS pool on THIS box's topology —
        # the same "which engine and why" stamp the run manifests
        # carry, so a bench record is auditable against the selection
        # policy that was live when it was measured.
        from npairloss_tpu.parallel.plan import host_counts, plan_engine

        devs = jax.devices()
        extras["engine_plan"] = plan_engine(
            n_devices=len(devs), n_hosts=len(host_counts(devs)),
            shard_rows=max(n // len(devs), 1), emb_dim=d,
            device_kind=getattr(devs[0], "device_kind", ""),
        ).to_dict()
    except Exception as e:  # noqa: BLE001 — provenance, not measurement
        _log(f"extras: engine plan stamp unavailable ({e})")
    if selected is not None and not (set(ENGINE_ROWS) & selected):
        # A batch-only --rows re-pass: every engine row is unselected,
        # so skip the whole section BEFORE the n x d pool is built and
        # device_put through the tunnel — that transfer is exactly the
        # budget a selective re-pass exists to save.
        _log("extras: no engine row selected (--rows); section skipped")
        for name in ENGINE_ROWS:
            extras[name] = {"skipped": "not selected (--rows)"}
        flush()
        return

    from jax.sharding import PartitionSpec as P

    from npairloss_tpu import NPairLossConfig, REFERENCE_CONFIG
    from npairloss_tpu.ops.npair_loss import npair_loss
    from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss
    from npairloss_tpu.parallel._compat import shard_map
    from npairloss_tpu.parallel.mesh import data_parallel_mesh
    from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

    rng = np.random.default_rng(1)
    f = rng.standard_normal((n, d)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    feats = jax.device_put(jnp.asarray(f))
    labels = jax.device_put(
        jnp.asarray(np.repeat(np.arange(n // 2), 2).astype(np.int32))
    )
    # Absolute-mining config (single-pass thresholds) plus the flagship
    # RELATIVE config (streamed radix selection) on every engine.
    from npairloss_tpu.ops.npair_loss import MiningMethod, MiningRegion

    abs_cfg = NPairLossConfig(
        margin_diff=-0.05,
        ap_mining_method=MiningMethod.RAND,
        an_mining_method=MiningMethod.HARD,
        an_mining_region=MiningRegion.LOCAL,
    )

    def bench_one(name, loss_fn):
        """loss_fn(features, labels) -> scalar loss; timed fwd+bwd."""
        # One-source-of-truth guard: a row missing from ENGINE_ROWS
        # would dodge --rows selection AND silently skip the
        # sacrificial warmup, corrupting its own measurement.
        assert name in ENGINE_ROWS, f"{name} missing from ENGINE_ROWS"
        if selected is not None and name not in selected:
            extras[name] = {"skipped": "not selected (--rows)"}
            return None
        vg = jax.value_and_grad(loss_fn)

        @jax.jit
        def many(f_, l_, salt):
            # ``salt`` is a float32-exact per-CALL distinct argument (the
            # time_scan pattern, utils/profiling.py): the tunnel memo
            # keys on argument values, and folding a salt into the
            # 1.0 + eps multiplier would collapse below the float32 ulp
            # — it must arrive as its own argument.
            def body(acc, s):
                # Perturb the input per step: every scan iteration is a
                # distinct computation, and the gradient feeds the carry
                # so no step can be elided.
                loss, grad = vg(f_ * (1.0 + (s + salt) * 1e-6), l_)
                return acc + loss + grad[0, 0], loss

            acc, losses = jax.lax.scan(
                body, jnp.float32(0.0), jnp.arange(steps, dtype=jnp.float32)
            )
            return acc, losses[0]

        if deadline is not None and time.time() > deadline:
            _log(f"extras: skipping {name} (soft time budget reached)")
            extras[name] = {"skipped": "soft time budget reached"}
            return None
        q = _quarantined(name)
        if q:
            _log(f"extras: skipping {name} (quarantined: {q})")
            extras[name] = {"skipped": f"quarantined: {q}"}
            return None
        _log(f"extras: compiling {name}...")
        flush(name)
        try:
            result = _bench_one_timed(name, many)
            flush()
            return result
        except Exception as e:  # one engine failing must not void the rest
            _log(f"extras: {name} FAILED: {e}")
            extras[name] = {"error": str(e)[:300]}
            flush()
            return None

    def _bench_one_timed(name, many):
        from npairloss_tpu.utils.profiling import next_timing_salt

        # The loss comes from THIS salt-0 dispatch (losses[0] is the
        # unperturbed input) so the cross-engine parity deltas below
        # stay exact; salted dispatches are for timing only.
        acc, l0 = many(feats, labels, jnp.float32(0.0))
        float(np.asarray(acc))  # warm (compile + first run)
        loss = float(np.asarray(l0))
        # Second warm run: the first executable a process times otherwise
        # absorbs one-time backend setup (observed ~40 ms/step of phantom
        # cost on the first-timed program only).  Fresh salt argument:
        # the tunnel memo keys on argument VALUES, even across processes.
        acc, _ = many(feats, labels, jnp.float32(next_timing_salt()))
        float(np.asarray(acc))
        # Two timed windows, min taken (tunnel latency jitter is one-
        # sided — see _measure); each window is a fresh-salted dispatch.
        dts = []
        for _ in range(2):
            salt = jnp.float32(next_timing_salt())
            t0 = time.perf_counter()
            acc, _ = many(feats, labels, salt)
            float(np.asarray(acc))
            dts.append(max(time.perf_counter() - t0 - floor, 1e-9))
        dt = min(dts)
        extras[name] = {
            "emb_per_sec": round(n * steps / dt, 1),
            "ms_per_step": round(dt / steps * 1e3, 2),
            "ms_per_step_windows": [round(d / steps * 1e3, 2) for d in dts],
            "loss": round(loss, 6),
        }
        _log(f"extras: {name}: {extras[name]}")
        return loss

    # Sacrificial timed program: the first program timed in a section has
    # absorbed ~40 ms/step of one-time backend cost even after two warm
    # runs (BENCH_r02/r03 extras: dense_abs inflated vs dense_flagship).
    # Burn that on a throwaway tiny loss so the real rows are clean.
    def _sacrifice():
        sf, sl = feats[:256], labels[:256]
        vg = jax.value_and_grad(lambda x: npair_loss(x, sl, abs_cfg))

        @jax.jit
        def many(f_):
            def body(acc, s):
                loss, grad = vg(f_ * (1.0 + s * 1e-6))
                return acc + loss + grad[0, 0], loss
            acc, _ = jax.lax.scan(
                body, jnp.float32(0.0), jnp.arange(steps, dtype=jnp.float32)
            )
            return acc

        for i in range(3):
            float(np.asarray(many(sf * (1.0 + i * 1e-3))))

    # The sacrifice dispatches real device work, so it gets the same
    # inflight/quarantine containment as a row: if it ever wedges the
    # tunnel, later runs skip it (first timed row then absorbs the ~40
    # ms/step phantom cost — priced, not silent) instead of re-wedging.
    # (A --rows pass that measures no engine row already returned above.)
    q = _quarantined("warmup_sacrifice")
    if q:
        _log(f"extras: skipping sacrificial warmup (quarantined: {q})")
    else:
        flush("warmup_sacrifice")
        try:
            _sacrifice()
        except Exception as e:
            _log(f"extras: sacrificial warmup failed (continuing): {e}")
        flush()

    mesh = data_parallel_mesh(jax.devices()[:1])

    def ring_loss(cfg, sim_cache=None, matmul_precision=None):
        # top_ks=() keeps the comparison fair: dense/blockwise are timed
        # as loss+grad only, so the ring must not pay for streamed
        # retrieval-metric top-k maintenance the others skip.
        fn = shard_map(
            lambda f_, l_: ring_npair_loss_and_metrics(
                f_, l_, cfg, "dp", top_ks=(), sim_cache=sim_cache,
                matmul_precision=matmul_precision,
            )[0][None],
            mesh=mesh,
            in_specs=(P("dp"), P("dp")),
            out_specs=P("dp"),
        )
        return lambda f_, l_: fn(f_, l_).sum()

    def delta(key, a, b):
        if a is not None and b is not None:
            extras[key] = abs(a - b)

    l_dense = bench_one(
        "dense_abs", lambda f_, l_: npair_loss(f_, l_, abs_cfg)
    )
    l_block = bench_one(
        "blockwise_abs", lambda f_, l_: blockwise_npair_loss(f_, l_, abs_cfg)
    )
    delta("dense_blockwise_abs_delta", l_dense, l_block)
    l_dense_rel = bench_one(
        "dense_flagship",
        lambda f_, l_: npair_loss(f_, l_, REFERENCE_CONFIG),
    )
    l_block_rel = bench_one(
        "blockwise_flagship",
        lambda f_, l_: blockwise_npair_loss(f_, l_, REFERENCE_CONFIG),
    )
    delta("dense_blockwise_flagship_delta", l_dense_rel, l_block_rel)
    # The rows above run with sim_cache auto (ON at this pool: 67 MB);
    # the _nocache rows force the O(N x block) recompute path so the
    # cache's effect is a recorded delta, not a hypothesis (VERDICT r3).
    l_block_rel_nc = bench_one(
        "blockwise_flagship_nocache",
        lambda f_, l_: blockwise_npair_loss(
            f_, l_, REFERENCE_CONFIG, sim_cache=False),
    )
    delta("blockwise_cache_nocache_delta", l_block_rel, l_block_rel_nc)
    # pos_topk=0 forces the streamed radix path for the AP threshold —
    # the delta against blockwise_flagship records the sparse-positive
    # fast path's gain (round 4) as a driver artifact.
    l_block_rel_radix = bench_one(
        "blockwise_flagship_radix",
        lambda f_, l_: blockwise_npair_loss(
            f_, l_, REFERENCE_CONFIG, pos_topk=0),
    )
    delta("blockwise_postopk_radix_delta", l_block_rel, l_block_rel_radix)
    # matmul_precision="default": the opt-in single-pass bf16 MXU mode
    # (round 4) — records the throughput headroom users buy by giving
    # up oracle bit-parity.  The loss delta vs the HIGHEST rows is the
    # recorded price.
    l_block_rel_bf16 = bench_one(
        "blockwise_flagship_bf16matmul",
        lambda f_, l_: blockwise_npair_loss(
            f_, l_, REFERENCE_CONFIG, matmul_precision="default"),
    )
    delta("blockwise_bf16matmul_loss_delta", l_block_rel, l_block_rel_bf16)
    l_dense_rel_bf16 = bench_one(
        "dense_flagship_bf16matmul",
        lambda f_, l_: npair_loss(
            f_, l_, REFERENCE_CONFIG, matmul_precision="default"),
    )
    delta("dense_bf16matmul_loss_delta", l_dense_rel, l_dense_rel_bf16)
    # Ring engine on a 1-device mesh: same pool, same math — isolates the
    # ring machinery's overhead (multi-pass tile recompute + ppermute)
    # against dense at an identical problem size (VERDICT r2 item 7).
    l_ring = bench_one("ring_abs", ring_loss(abs_cfg))
    delta("dense_ring_abs_delta", l_dense, l_ring)
    l_ring_rel = bench_one("ring_flagship", ring_loss(REFERENCE_CONFIG))
    delta("dense_ring_flagship_delta", l_dense_rel, l_ring_rel)
    l_ring_rel_nc = bench_one(
        "ring_flagship_nocache",
        ring_loss(REFERENCE_CONFIG, sim_cache=False),
    )
    delta("ring_cache_nocache_delta", l_ring_rel, l_ring_rel_nc)
    # Ring at matmul_precision="default": completes the bf16-mode
    # coverage across all three engines (dense/blockwise rows above).
    l_ring_rel_bf16 = bench_one(
        "ring_flagship_bf16matmul",
        ring_loss(REFERENCE_CONFIG, matmul_precision="default"),
    )
    delta("ring_bf16matmul_loss_delta", l_ring_rel, l_ring_rel_bf16)

    # serve_qps: the online path (serve.QueryEngine) against the same
    # 4096 x 512 pool as a gallery — warmed-bucket query latency p50/p99
    # + QPS at each fixed padding bucket, plus the counted proof that
    # steady-state serving performed zero post-warmup compiles.  Every
    # timed dispatch queries DISTINCT rows of a fresh random pool so a
    # memoizing tunnel backend cannot serve a repeat (docs/DESIGN.md §6).
    def _serve_qps():
        from npairloss_tpu.serve import (
            EngineConfig,
            GalleryIndex,
            QueryEngine,
        )

        buckets = (8, 32)
        trials = 20
        idx = GalleryIndex.build(f, np.asarray(labels), normalize=False)
        engine = QueryEngine(
            idx, EngineConfig(top_k=10, buckets=buckets)
        )
        warm_s = engine.warmup()
        qpool = np.random.default_rng(7).standard_normal(
            (max(buckets) * trials, d)
        ).astype(np.float32)
        row = {"gallery": n, "top_k": 10, "warmup_s": round(warm_s, 2)}
        for bucket in buckets:
            lats = []
            for t in range(trials):
                q = qpool[t * bucket:(t + 1) * bucket]
                t0 = time.perf_counter()
                engine.query(q, normalize=True)
                # query() already materialized the answer (np.asarray)
                lats.append(
                    max(time.perf_counter() - t0 - floor, 1e-9) * 1e3
                )
            lats.sort()
            row[f"bucket_{bucket}"] = {
                "p50_ms": round(lats[len(lats) // 2], 2),
                "p99_ms": round(lats[min(int(len(lats) * 0.99),
                                         len(lats) - 1)], 2),
                "qps": round(bucket * trials / (sum(lats) / 1e3), 1),
            }
        row["compiles_after_warmup"] = \
            engine.compile_stats()["compiles_after_warmup"]
        extras["serve_qps"] = row
        _log(f"extras: serve_qps: {row}")

    name = "serve_qps"
    if selected is not None and name not in selected:
        extras[name] = {"skipped": "not selected (--rows)"}
    elif deadline is not None and time.time() > deadline:
        _log(f"extras: skipping {name} (soft time budget reached)")
        extras[name] = {"skipped": "soft time budget reached"}
    elif _quarantined(name):
        q = _quarantined(name)
        _log(f"extras: skipping {name} (quarantined: {q})")
        extras[name] = {"skipped": f"quarantined: {q}"}
    else:
        _log(f"extras: measuring {name}...")
        flush(name)
        try:
            _serve_qps()
        except Exception as e:  # the serve row must not void the rest
            _log(f"extras: {name} FAILED: {e}")
            extras[name] = {"error": str(e)[:300]}
        flush()

    # flat_qps_1m / ivf_qps_1m: production-gallery-scale serving
    # (ISSUE 11 / ROADMAP item 2).  A 1M x 128 synthetic gallery served
    # through the flat exact scan (the recall oracle — untenable at
    # this size, which is the point being measured) and through the IVF
    # probe path (serve/ivf.py: k-means clusters, probe-top-C, bf16
    # cluster-scan scoring).  The IVF row carries build time and
    # recall@1/@10 against the flat ground truth computed on IDENTICAL
    # queries — bench_check holds a HARD recall floor and a minimum
    # ivf-vs-flat speedup on it, not just the noise-aware p99 gate.
    # Rows are stamped with the measuring platform: gallery-scale rows
    # may be captured on CPU during tunnel outages, and that provenance
    # must ride the row, not the record headline.
    def _serve_scale_rows(want_flat, want_ivf, want_fused, want_micro):
        import gc

        from npairloss_tpu.ops.pallas_ivf import PROBE_IMPLS
        from npairloss_tpu.serve import (
            EngineConfig,
            GalleryIndex,
            QueryEngine,
        )
        from npairloss_tpu.serve.ivf import IVFIndex, topk_recall

        n1, d1, kc, probes = 1_000_000, 128, 1024, 32
        bucket, trials, top_k = 8, 12, 10
        platform = jax.devices()[0].platform
        # The cluster-scan matmul dtype: bf16 is the MXU-headroom mode
        # (the ring bf16 row's ~6.7x), but XLA *CPU* scalarizes bf16
        # (measured ~13x SLOWER than the Eigen f32 path) — an outage-
        # round CPU measurement must not pay an emulation tax the row
        # exists to disprove.  The recall-parity gates for bf16/int8
        # live in tests/test_ivf.py either way.
        scoring = "fp32" if platform == "cpu" else "bf16"
        # The fused Pallas probe row is the same per-platform story one
        # level up: off TPU the kernel runs in interpret mode — a
        # parity/debug harness ~1000x slower than the thing it
        # emulates — so a CPU outage round stamps the row skipped
        # rather than paying (and publishing) an emulation tax.  The
        # recall/1e-6-parity evidence for the kernel lives in
        # tests/test_pallas_ivf.py + the ci.sh interpret smoke either
        # way; the TPU-window recipe rides the bench record note.
        measure_fused = want_fused and platform == "tpu"
        if want_fused and not measure_fused:
            extras["ivf_fused_qps_1m"] = {
                "skipped": "fused probe kernel measures on TPU only "
                           "(interpret mode is a parity harness, not "
                           "a serving path)"}
            _log("extras: skipping ivf_fused_qps_1m (platform "
                 f"{platform}: interpret emulation is not a "
                 "measurement)")
        # Clustered synthetic gallery — the geometry a trained
        # metric-learning gallery actually has (4096 classes, tight
        # class clusters), and the structure IVF's probe-recall story
        # is ABOUT.  An isotropic-gaussian pool is the adversarial
        # no-structure case: true neighbors scatter uniformly over
        # clusters and no sublinear index can hold recall there.
        classes = 4096
        rng1 = np.random.default_rng(11)
        centers = rng1.standard_normal(
            (classes, d1), dtype=np.float32)
        centers /= np.linalg.norm(centers, axis=1, keepdims=True)
        plab = (np.arange(n1) % classes).astype(np.int32)
        pool = centers[plab] + 0.045 * rng1.standard_normal(
            (n1, d1), dtype=np.float32)
        pool /= np.linalg.norm(pool, axis=1, keepdims=True)
        sel_rows = rng1.choice(n1, bucket * trials, replace=False)
        qs = pool[sel_rows] + 0.045 * rng1.standard_normal(
            (bucket * trials, d1), dtype=np.float32)
        qs /= np.linalg.norm(qs, axis=1, keepdims=True)

        def timed(engine):
            lats, rows_out = [], []
            for t in range(trials):
                q = qs[t * bucket:(t + 1) * bucket]
                t0 = time.perf_counter()
                out = engine.query(q, normalize=False)
                lats.append(
                    max(time.perf_counter() - t0 - floor, 1e-9) * 1e3
                )
                rows_out.append(out["rows"][:, :top_k])
            lats.sort()
            return lats, np.concatenate(rows_out)

        def base_row(lats, warm_s, engine):
            return {
                "gallery": n1, "dim": d1, "top_k": top_k,
                "bucket": bucket, "platform": platform,
                "warmup_s": round(warm_s, 2),
                "p50_ms": round(lats[len(lats) // 2], 2),
                "p99_ms": round(lats[min(int(len(lats) * 0.99),
                                         len(lats) - 1)], 2),
                "qps": round(bucket * trials / (sum(lats) / 1e3), 1),
                "compiles_after_warmup":
                    engine.compile_stats()["compiles_after_warmup"],
            }

        # Which passes this selection actually needs: the flat oracle
        # feeds every recall number; the scan engine feeds its own row
        # AND the micro row's baseline clock; the dispatch-count-only
        # micro row never forces the oracle pass.
        need_oracle = want_flat or want_ivf or measure_fused
        need_index = want_ivf or measure_fused or want_micro
        flat_lats = flat_rows = None
        if need_oracle:
            _log(f"extras: building 1M x {d1} gallery "
                 "(flat oracle pass)...")
            idx_f = GalleryIndex.build(pool, plab, normalize=False)
            eng_f = QueryEngine(idx_f, EngineConfig(
                top_k=top_k, buckets=(bucket,), gallery_block=131072))
            warm_f = eng_f.warmup()
            flat_lats, flat_rows = timed(eng_f)
            if want_flat:
                extras["flat_qps_1m"] = base_row(flat_lats, warm_f,
                                                 eng_f)
                _log(f"extras: flat_qps_1m: {extras['flat_qps_1m']}")
            # Free the flat device residency before the IVF build
            # doubles it (the flat answers — the recall ground truth —
            # are host-side).
            del eng_f
            idx_f.emb = idx_f.labels = idx_f.valid = None
            gc.collect()
        if not need_index:
            return
        t0 = time.perf_counter()
        idx_i = IVFIndex.build_ivf(
            pool, plab, normalize=False, clusters=kc, iters=8,
            train_size=65536)
        build_s = time.perf_counter() - t0

        def ivf_row_extras(row, eng_rows):
            return {
                "clusters": kc, "probes": probes, "scoring": scoring,
                "cap": idx_i.layout.cap,
                "build_s": round(build_s, 1),
                "recall_at_1": round(
                    topk_recall(eng_rows, flat_rows, k=1), 4),
                "recall_at_10": round(
                    topk_recall(eng_rows, flat_rows, k=10), 4),
                "speedup_vs_flat_p50": round(
                    flat_lats[len(flat_lats) // 2]
                    / max(row["p50_ms"], 1e-9), 1),
            }

        eng_i = None
        if want_ivf or want_micro:
            eng_i = QueryEngine(idx_i, EngineConfig(
                top_k=top_k, buckets=(bucket,), probes=probes,
                scoring=scoring))
            warm_i = eng_i.warmup()
        if want_ivf:
            ivf_lats, ivf_rows = timed(eng_i)
            row = base_row(ivf_lats, warm_i, eng_i)
            row.update(ivf_row_extras(row, ivf_rows))
            extras["ivf_qps_1m"] = row
            _log(f"extras: ivf_qps_1m: {row}")
        eng_fu = None
        if measure_fused or (want_micro and platform == "tpu"):
            # SAME index object, probe_impl the only delta — the row
            # isolates the kernel, not a rebuild.
            eng_fu = QueryEngine(idx_i, EngineConfig(
                top_k=top_k, buckets=(bucket,), probes=probes,
                scoring=scoring, probe_impl="fused"))
            warm_fu = eng_fu.warmup()
        if measure_fused:
            fu_lats, fu_rows = timed(eng_fu)
            rowf = base_row(fu_lats, warm_fu, eng_fu)
            rowf.update(ivf_row_extras(rowf, fu_rows))
            rowf.update({
                "probe_impl": eng_fu.probe_impl,
                "dispatch_count":
                    PROBE_IMPLS["fused"]["dispatch_count"],
            })
            extras["ivf_fused_qps_1m"] = rowf
            _log(f"extras: ivf_fused_qps_1m: {rowf}")
        if want_micro:
            # Kernel-level micro: ONE steady-state probe dispatch per
            # impl (no host gather, no batcher), plus the registry's
            # declared pipeline dispatch counts — the 4 -> 2 claim,
            # stamped where bench_check can gate it jax-free.
            qm = jnp.asarray(qs[:bucket])

            def one_dispatch_ms(eng):
                args, _ = eng._topk_call(bucket)
                reps = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    jax.block_until_ready(eng._topk_fn(qm, *args))
                    reps.append(
                        max(time.perf_counter() - t0 - floor, 1e-9))
                reps.sort()
                return round(reps[len(reps) // 2] * 1e3, 2)

            mrow = {
                "gallery": n1, "dim": d1, "clusters": kc,
                "probes": probes, "bucket": bucket,
                "scoring": scoring, "platform": platform,
                "cap": idx_i.layout.cap,
                "scan_dispatches":
                    PROBE_IMPLS["scan"]["dispatch_count"],
                "fused_dispatches":
                    PROBE_IMPLS["fused"]["dispatch_count"],
                "scan_ms": one_dispatch_ms(eng_i),
            }
            if eng_fu is not None:
                mrow["fused_ms"] = one_dispatch_ms(eng_fu)
            else:
                mrow["fused_ms_note"] = (
                    "needs a TPU window — interpret emulation "
                    "excluded (see the record note's recipe)")
            extras["ivf_probe_kernel_micro"] = mrow
            _log(f"extras: ivf_probe_kernel_micro: {mrow}")

    scale_names = ("flat_qps_1m", "ivf_qps_1m", "ivf_fused_qps_1m",
                   "ivf_probe_kernel_micro")
    wants = {}
    for name in scale_names:
        if selected is not None and name not in selected:
            extras[name] = {"skipped": "not selected (--rows)"}
            wants[name] = False
        elif deadline is not None and time.time() > deadline:
            _log(f"extras: skipping {name} (soft time budget reached)")
            extras[name] = {"skipped": "soft time budget reached"}
            wants[name] = False
        elif _quarantined(name):
            q = _quarantined(name)
            _log(f"extras: skipping {name} (quarantined: {q})")
            extras[name] = {"skipped": f"quarantined: {q}"}
            wants[name] = False
        else:
            wants[name] = True
    # The IVF row's recall ground truth IS the flat oracle pass, so a
    # QUARANTINED flat row (it wedged a previous child) must also stand
    # the IVF row down — re-running the wedging code to feed the other
    # row defeats the quarantine.  A merely-deselected flat row still
    # permits the (unmeasured) oracle pass.
    for name in ("ivf_qps_1m", "ivf_fused_qps_1m"):
        if wants[name] and _quarantined("flat_qps_1m"):
            reason = _quarantined("flat_qps_1m")
            _log(f"extras: skipping {name} (flat oracle quarantined: "
                 f"{reason})")
            extras[name] = {
                "skipped": f"flat oracle quarantined: {reason}"}
            wants[name] = False
    if any(wants[name] for name in scale_names):
        flush("serve_scale_1m")
        try:
            _serve_scale_rows(wants["flat_qps_1m"], wants["ivf_qps_1m"],
                              wants["ivf_fused_qps_1m"],
                              wants["ivf_probe_kernel_micro"])
        except Exception as e:  # scale rows must not void the rest
            _log(f"extras: serve scale rows FAILED: {e}")
            for name in scale_names:
                # Never clobber a half-pass's MEASURED row (the flat
                # oracle may have landed minutes of work before the IVF
                # build raised): only still-pending rows get the marker.
                if wants[name] and not _row_measured(extras.get(name)):
                    extras[name] = {"error": str(e)[:300]}
        flush()
    return extras


# Batch-scaling sweep: (batch, model_name, row_key, model_kw, solver_kw).
# Ordered by importance: the soft deadline may skip later rows.  The
# parity-preserving MXU rewrites (s2d stem, fused inception 1x1s, both =
# "mxu") and the remat row answer PROFILE.md's open attribution questions
# with driver-captured numbers.  A ``"policy"`` key in model_kw routes
# the row through the named precision policy (models.precision) — the
# *_policy rows are the flagship recipe's 240/480/960 scaling curve
# (the 120 point is the headline itself), googlenet_fp32_parity keeps
# the prototxt-parity fp32 delta measured, and 120_pallas_stem times
# the fused-stem Pallas kernels (Mosaic-compiled on TPU).  The vit_b16
# rows time BASELINE.json config 5's trunk (real ViT-B/16) through the
# blockwise (stretch-path) engine; the 256 row probes the largest batch
# and runs LAST so an OOM cannot cost any other row.  The row_key
# column is the other half of the --rows/--warmup-rows vocabulary
# (with "headline" and ENGINE_ROWS).
BATCH_SCALING_SPECS = (
    (120, "googlenet", "120", {}, {}),
    (120, "googlenet_mxu", "120_mxu", {}, {}),
    (120, "googlenet", "googlenet_fp32_parity",
     {"policy": "fp32_parity"}, {}),
    (240, "googlenet", "240", {}, {}),
    (240, "flagship", "240_policy", {"policy": "mxu"}, {}),
    (480, "googlenet", "480", {}, {}),
    (480, "flagship", "480_policy", {"policy": "mxu"}, {}),
    (128, "vit_b16", "vit_b16_128", {}, {"engine": "blockwise"}),
    (120, "googlenet_s2d", "120_s2d", {}, {}),
    (120, "googlenet_fused", "120_fused", {}, {}),
    (120, "googlenet_pallas", "120_pallas_stem", {"policy": "mxu"}, {}),
    # Remat row: does relieving activation HBM pressure recover the
    # batch-480 MFU decay?  (~25% extra trunk FLOPs for O(block)
    # activation memory; numerically identical.)
    (480, "googlenet", "480_remat", {"remat": True}, {}),
    (960, "flagship", "960_policy", {"policy": "mxu"}, {}),
    (256, "vit_b16", "vit_b16_256", {}, {"engine": "blockwise"}),
)


def known_row_names():
    """The full --rows vocabulary; a name outside it is a typo."""
    return {"headline"} | set(ENGINE_ROWS) | {
        spec[2] for spec in BATCH_SCALING_SPECS
    }


def _batch_scaling_extras(jax, jnp, np, dev, floor, deadline=None,
                          rows=None, flush=None, selected=None):
    """Flagship solver throughput at batch 120/240/480 — does a bigger
    per-chip batch lift emb/s/chip (VERDICT r2 item 4)?  Plus the
    space-to-depth stem variant at batch 120: parity-preserving rewrite
    of the K=147/C_in=3 conv1 (models/layers.conv1_kernel_to_s2d), the
    claimed ~28%-of-FLOPs MXU-underutilization fix (VERDICT r3 item 4) —
    recording it here makes the s2d MFU a driver artifact."""
    if rows is None:
        rows = {}
    if flush is None:
        flush = lambda inflight=None: None  # noqa: E731
    for batch, model_name, key, model_kw, solver_kw in BATCH_SCALING_SPECS:
        if selected is not None and key not in selected:
            rows[key] = {"skipped": "not selected (--rows)"}
            continue
        if deadline is not None and time.time() > deadline:
            _log(f"batch scaling: skipping {key} (soft time budget reached)")
            rows[key] = {"skipped": "soft time budget reached"}
            continue
        q = _quarantined(key)
        if q:
            _log(f"batch scaling: skipping {key} (quarantined: {q})")
            rows[key] = {"skipped": f"quarantined: {q}"}
            continue
        flush(f"batch_scaling/{key}")
        try:
            _batch_scaling_row(
                jax, jnp, np, dev, floor, rows, batch, model_name, key,
                model_kw, solver_kw, deadline=deadline,
            )
        except Exception as e:  # e.g. ViT-256 OOM: record, don't void
            _log(f"batch scaling: {key} FAILED: {e}")
            rows[key] = {"error": str(e)[:300]}
        flush()
    return rows


def _solver_for_spec(jnp, model_name, model_kw, solver_kw):
    """The ONE solver constructor for a BATCH_SCALING_SPECS row — shared
    by the measuring path and the AOT warmup child so the program the
    warmup compiles into the cache IS the program the row dispatches.
    A ``"policy"`` key in model_kw selects a named precision policy
    (threaded through trunk AND solver); the legacy rows stay the
    bf16-dtype construction byte-for-byte."""
    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    model_kw = dict(model_kw)
    policy = model_kw.pop("policy", None)
    if policy is not None:
        model = get_model(model_name, policy=policy, **model_kw)
    else:
        model = get_model(model_name, dtype=jnp.bfloat16, **model_kw)
    return Solver(
        model,
        REFERENCE_CONFIG,
        SolverConfig(
            base_lr=0.001, lr_policy="step", stepsize=10000, gamma=0.5,
            momentum=0.9, weight_decay=2e-5, display=0, snapshot=0,
        ),
        input_shape=(IMAGE, IMAGE, 3),
        precision=policy,
        **solver_kw,
    )


def _batch_scaling_row(jax, jnp, np, dev, floor, rows, batch, model_name,
                       key, model_kw, solver_kw, deadline=None):
    from npairloss_tpu.utils.profiling import next_timing_salt

    solver = _solver_for_spec(jnp, model_name, model_kw, solver_kw)
    rng = np.random.default_rng(0)
    # Per-run salt: see the headline comment (value-keyed tunnel memo).
    x = jax.device_put(jnp.asarray(
        rng.standard_normal((batch, IMAGE, IMAGE, 3)).astype(np.float32)
        + next_timing_salt() * 1e-6
    ))
    lab = jax.device_put(jnp.asarray(
        np.repeat(np.arange(batch // 2), 2).astype(np.int32)
    ))
    _log(f"batch scaling: compiling {key} ({model_name})...")
    steps = 10
    dts = _measure(
        lambda a, b: solver.step(a, b), [x, lab], 1, steps,
        lambda m: float(np.asarray(m["loss"])), floor, deadline=deadline,
    )
    dt = min(dts)
    mfu = None
    try:
        compiled = solver._step_fn.lower(solver.state, x, lab).compile()
        est = _mfu_estimate(compiled, dt, steps, dev.device_kind)
        if est["mfu"] is not None:
            mfu = round(est["mfu"], 4)
    except Exception as e:
        _log(f"batch {key} mfu estimate failed: {e}")
    rows[key] = {
        "emb_per_sec": round(batch * steps / dt, 1),
        "ms_per_step": round(dt / steps * 1e3, 2),
        "ms_per_step_windows": [round(d / steps * 1e3, 2) for d in dts],
        **({"mfu": mfu} if mfu is not None else {}),
    }
    _log(f"batch scaling: {key}: {rows[key]}")


def warmable_row_names():
    """Rows --warmup-rows may name: solver train-step programs only
    (engine rows are loss-only scans with no Solver.warmup path)."""
    return {"headline"} | {spec[2] for spec in BATCH_SCALING_SPECS}


def child_warmup(platform: str, rows_csv: str) -> int:
    """AOT-populate the committed persistent compile cache for the named
    rows, OUTSIDE a measuring window (ROADMAP item 1: the batch-480
    flagship compile ran 25 minutes inside a tunnel window and died
    UNAVAILABLE — quarantined since round 5).  ``Solver.warmup()``
    ``.lower().compile()``s each row's EXACT train-step program (the
    shared ``_solver_for_spec`` constructor guarantees that) with the
    cache enabled, so the later measuring dispatch pays deserialization
    instead of a multi-minute XLA compile.  Recipe:

        python bench.py --warmup-rows 480,480_policy,960_policy

    then commit the new bench_cache/xla_cache/ entries; the next bench
    round measures the (quarantine-cleared) rows instead of compiling
    them.
    """
    jax, dev = _child_setup(platform)
    import jax.numpy as jnp

    from npairloss_tpu.models import FLAGSHIP_POLICY, FLAGSHIP_TRUNK

    names = {r.strip() for r in rows_csv.split(",") if r.strip()}
    specs = ((BATCH, FLAGSHIP_TRUNK, "headline",
              {"policy": FLAGSHIP_POLICY}, {}),) + BATCH_SCALING_SPECS
    warmed, errors = {}, {}
    for batch, model_name, key, model_kw, solver_kw in specs:
        if key not in names:
            continue
        _log(f"warmup: AOT-compiling {key} ({model_name} @ batch "
             f"{batch})...")
        try:
            solver = _solver_for_spec(jnp, model_name, model_kw, solver_kw)
            warmed[key] = round(solver.warmup(batch), 1)
            _log(f"warmup: {key} compiled in {warmed[key]}s")
        except Exception as e:  # one row failing must not void the rest
            errors[key] = str(e)[:300]
            _log(f"warmup: {key} FAILED: {e}")
    print(json.dumps({
        "metric": "aot_warmup_compile_seconds",
        "mode": "warmup",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "cache_dir": CACHE_DIR,
        "warmed": warmed,
        **({"errors": errors} if errors else {}),
    }))
    # Always rc 0 once the record is printed: the parent's _run_child
    # discards child stdout on rc != 0, so a nonzero here would turn a
    # partial success (480 warmed in 25 min, 960 OOMed) into an opaque
    # "warmup child failed" — per-row failures travel in "errors".
    return 0


def child_smoke(platform: str) -> int:
    """Minimal always-works measurement: tiny MLP + loss, 5 steps."""
    jax, dev = _child_setup(platform)
    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    batch = 64
    solver = Solver(
        get_model("mlp", hidden=(256,), embedding_dim=64),
        REFERENCE_CONFIG,
        SolverConfig(base_lr=0.01, lr_policy="fixed", display=0, snapshot=0),
        input_shape=(32, 32, 3),
    )
    rng = np.random.default_rng(0)
    from npairloss_tpu.utils.profiling import next_timing_salt

    x = jnp.asarray(rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
                    + next_timing_salt() * 1e-6)
    lab = jnp.asarray(np.repeat(np.arange(batch // 2), 2).astype(np.int32))
    dt = min(_measure(
        lambda a, b: solver.step(a, b), [x, lab], 1, 5,
        lambda m: float(np.asarray(m["loss"])), _fetch_floor(jax),
    ))
    emb_per_sec = batch * 5 / dt
    print(
        json.dumps(
            {
                "metric": "smoke_mlp_npair_train_embeddings_per_sec",
                "value": round(emb_per_sec, 2),
                "unit": "embeddings/sec/chip",
                "vs_baseline": 0.0,
                "platform": dev.platform,
                "device_kind": dev.device_kind,
                "mode": "smoke",
                "note": "fallback smoke benchmark — full flagship bench did "
                "not complete on this backend",
            }
        )
    )
    return 0


# ---------------------------------------------------------------------------
# Parent: orchestration (no jax import — cannot hang)
# ---------------------------------------------------------------------------


def _run_child_ex(child_args, timeout: float):
    """Run a child bench subprocess.

    Returns (json_dict_or_None, reason) with reason in
    {"ok", "timeout", "rc", "nojson"} — callers that retry should only
    do so for "timeout" (an outage-shaped failure); rc/nojson failures
    are deterministic and re-running just delays the fallback."""
    cmd = [sys.executable, os.path.abspath(__file__)] + child_args
    _log(f"spawn {' '.join(child_args)} (timeout {timeout:.0f}s)")
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        _log(f"child {child_args} timed out after {timeout:.0f}s")
        return None, "timeout"
    if proc.returncode != 0:
        _log(f"child {child_args} exited rc={proc.returncode}")
        return None, "rc"
    for line in reversed(proc.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), "ok"
            except json.JSONDecodeError:
                continue
    _log(f"child {child_args} produced no JSON")
    return None, "nojson"


def _run_child(child_args, timeout: float):
    """Run a child bench subprocess; return its stdout JSON dict or None."""
    return _run_child_ex(child_args, timeout)[0]


# A row must be in flight at least this long before its death reads as
# "wedged the backend" rather than "parent budget ran out mid-row": the
# soft deadline leaves rows up to 25% of the full budget (750 s at the
# default --full-timeout of 3000 s — keep this threshold above that
# product when raising the timeout) to finish before the parent's hard
# kill, and no legitimate row has taken 15 minutes once the headline is
# compiled — the 2026-08-01 radix wedge sat for 37+ minutes.  Only
# wedge-shaped deaths quarantine the row; budget-shaped deaths just
# record it.
QUARANTINE_MIN_INFLIGHT_SECS = 900.0


def _write_spill(record, inflight) -> None:
    """Child side: persist the partial full-bench record atomically."""
    try:
        tmp = SPILL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "inflight": inflight,
                    "inflight_since": time.time() if inflight else None,
                    "record": record,
                },
                f,
            )
        os.replace(tmp, SPILL_PATH)
    except Exception:  # spilling is protection, never a failure source
        pass


def _clear_spill() -> None:
    try:
        os.unlink(SPILL_PATH)
    except OSError:
        pass


# (path, dict) memo: ~19 extras rows each consult the quarantine; one
# read per path suffices.  Keyed by path so tests that repoint
# QUARANTINE_PATH get a fresh load; _quarantine_add mutates the cached
# dict in place so parent-side additions stay visible.
_QUAR_CACHE = None


def _load_quarantine():
    global _QUAR_CACHE
    if _QUAR_CACHE is not None and _QUAR_CACHE[0] == QUARANTINE_PATH:
        return _QUAR_CACHE[1]
    try:
        with open(QUARANTINE_PATH) as f:
            q = json.load(f)
    except Exception:
        q = {}
    _QUAR_CACHE = (QUARANTINE_PATH, q)
    return q


def _quarantined(name):
    """Reason string if ``name`` wedged a previous run, else None."""
    ent = _load_quarantine().get(name)
    if ent:
        return ent.get("note", "wedged a previous run")
    return None


def _quarantine_add(row: str, note: str) -> None:
    import datetime

    global _QUAR_CACHE
    try:
        # Fresh read (bypassing the memo) narrows the read-modify-write
        # window against a concurrent run's addition; tmp+replace keeps
        # the committed file parseable even if this process dies mid-dump
        # (a truncated file would silently un-gate every entry).
        _QUAR_CACHE = None
        q = _load_quarantine()
        q[row] = {"date": datetime.date.today().isoformat(), "note": note}
        os.makedirs(os.path.dirname(QUARANTINE_PATH), exist_ok=True)
        tmp = QUARANTINE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(q, f, indent=1)
            f.write("\n")
        os.replace(tmp, QUARANTINE_PATH)
        _log(f"quarantined row {row!r}: {note}")
    except Exception as e:
        _log(f"quarantine write failed: {e}")


def _salvage_from_spill():
    """Parent side, after a full-child death: recover the partial record.

    Returns a full-mode record (flagged ``salvaged``) if the spill holds
    a measured headline, else None.  The row in flight at death is
    recorded in the output and quarantined for later runs."""
    try:
        with open(SPILL_PATH) as f:
            sp = json.load(f)
    except Exception:
        return None
    rec = sp.get("record") or {}
    if not rec.get("value"):
        return None
    rec["salvaged"] = True
    inflight = sp.get("inflight")
    if inflight:
        rec["wedged_row"] = inflight
        # Batch rows spill as "batch_scaling/<key>" so the error lands
        # in the namespace their consumers read; quarantine by bare key
        # (that's what the batch loop checks).
        home = rec.setdefault("extras", {})
        row_key = inflight
        if "/" in inflight:
            ns, row_key = inflight.split("/", 1)
            home = home.setdefault(ns, {})
        home.setdefault(
            row_key, {"error": "in flight when the child died (wedge?)"}
        )
        since = sp.get("inflight_since")
        stuck = (time.time() - since) if since else None
        if stuck is not None and stuck >= QUARANTINE_MIN_INFLIGHT_SECS:
            _quarantine_add(
                row_key,
                f"in flight {stuck / 60:.0f} min when the full bench "
                "child died (wedge-shaped) — skipped to protect later "
                "runs; clear this entry to re-try",
            )
        else:
            _log(
                f"row {row_key!r} was in flight only "
                f"{0 if stuck is None else stuck:.0f}s at child death — "
                "budget-shaped, not quarantining"
            )
    _log(f"salvaged partial full record from spill (inflight={inflight})")
    return rec


def _load_last_good():
    try:
        with open(LAST_GOOD_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _headline_measured(rec) -> bool:
    return bool(rec.get("value")) and not rec.get("headline_reused")


def _row_measured(v) -> bool:
    """A dict row holding a real number: engine rows carry emb_per_sec,
    serving rows carry p99_ms/qps (the serve_qps + *_qps_1m shapes)."""
    return isinstance(v, dict) and any(
        key in v for key in ("emb_per_sec", "p99_ms", "qps"))


def _measured_row_names(rec):
    """Names of FRESHLY MEASURED rows in a full-mode record: "headline",
    engine-extras names, and "batch_scaling/<key>"s.  Skip/error markers
    and a reused headline do not count."""
    names = set()
    if _headline_measured(rec):
        names.add("headline")
    extras = rec.get("extras") or {}
    for k, v in extras.items():
        if k == "batch_scaling":
            for bk, bv in (v or {}).items():
                if isinstance(bv, dict) and "emb_per_sec" in bv:
                    names.add(f"batch_scaling/{bk}")
        elif _row_measured(v):
            names.add(k)
    return names


def _merge_rows(base, donor, prefer=frozenset()):
    """A deep-copied ``base`` with ``donor``'s measured rows folded in
    wherever ``base`` lacks a measured row (headline included) — the
    merge direction for ADVICE #1: recovered rows are never lost, and a
    sparser record never clobbers a richer one wholesale.

    ``prefer`` names rows (the ``_measured_row_names`` vocabulary) whose
    freshly measured donor value REPLACES the base's even when the base
    already has one — the ``--rows`` re-pass direction: a row the
    operator explicitly re-measured must win over the stale value it
    was dispatched to replace."""
    import copy

    out = copy.deepcopy(base)
    if _headline_measured(donor) and (
        "headline" in prefer or not _headline_measured(out)
    ):
        for k in ("value", "vs_baseline", "ms_per_step",
                  "ms_per_step_windows", "mfu", "step_flops",
                  "fetch_floor_ms", "device_kind", "platform"):
            if k in donor:
                out[k] = copy.deepcopy(donor[k])
        out.pop("headline_reused", None)
    be = out.setdefault("extras", {})
    for k, v in (donor.get("extras") or {}).items():
        if k == "batch_scaling":
            bbs = be.setdefault("batch_scaling", {})
            for bk, bv in (v or {}).items():
                cur = bbs.get(bk)
                if isinstance(bv, dict) and "emb_per_sec" in bv and (
                    f"batch_scaling/{bk}" in prefer
                    or not (isinstance(cur, dict) and "emb_per_sec" in cur)
                ):
                    bbs[bk] = copy.deepcopy(bv)
        elif isinstance(v, dict):
            cur = be.get(k)
            if _row_measured(v) and (
                k in prefer or not _row_measured(cur)
            ):
                be[k] = copy.deepcopy(v)
        elif k not in be:  # scalar context keys (pool/steps/deltas)
            be[k] = v
    return out


def _save_last_good(rec) -> None:
    """Persist a successful full TPU payload as the last-known-good cache.

    The file is committed to the repo so a future outage round still has
    a machine-readable hardware number to report (flagged stale).

    Partial records never clobber measured evidence (ADVICE #1/#2):
    a ``--rows`` selective re-pass is MERGED into the existing payload,
    and a same-day salvaged partial either defers to a complete payload
    (as before) or is merged with the other salvage so the union of
    measured rows survives, with the richer record as the base."""
    import datetime

    today = datetime.date.today().isoformat()
    if rec.get("batch", 120) != 120 or rec.get("image", 224) != 224:
        _log(
            "last-good cache NOT refreshed: non-reference geometry "
            f"(batch {rec.get('batch')} @ {rec.get('image')})"
        )
        return
    lg = _load_last_good()
    payload = (lg or {}).get("payload") or {}
    date_out = today
    if rec.get("rows_filter"):
        if payload:
            if not _headline_measured(rec) and lg and lg.get("date"):
                # The top-level date drives the "same-day complete
                # payload beats salvaged partial" rule: a rows merge
                # that did NOT re-measure the headline must keep the
                # base's date, or old headline evidence masquerades as
                # today's and outranks a genuinely fresh salvage.
                date_out = lg["date"]
            # prefer = what this re-pass actually measured (skip/error
            # markers and a reused headline never override the base).
            merged = _merge_rows(payload, rec,
                                 prefer=_measured_row_names(rec))
            merged["rows_updated"] = {
                "date": today, "rows": rec["rows_filter"],
            }
            rec = merged
            _log("last-good cache: merged --rows re-pass into the "
                 "existing payload")
    elif rec.get("salvaged") and lg and lg.get("date") == today:
        if not payload.get("salvaged"):
            # A salvaged partial must not clobber a complete payload
            # captured the same day (e.g. an earlier successful run this
            # round); it SHOULD replace anything older — a fresh headline
            # beats a stale complete record.
            _log("last-good cache kept: same-day complete payload beats "
                 "this salvaged partial")
            return
        ours, theirs = _measured_row_names(rec), _measured_row_names(payload)
        if len(ours) >= len(theirs):
            rec = _merge_rows(rec, payload)
        else:
            # Strictly fewer measured rows: the existing salvage stays
            # the base; this run's recovered rows are folded in rather
            # than lost (the 2026-08-02 re-pass clobber, ADVICE #1).
            rec = _merge_rows(payload, rec)
            _log(
                "last-good cache: same-day salvage has fewer measured "
                f"rows ({len(ours)} < {len(theirs)}); merged into the "
                "richer existing payload instead of replacing it"
            )
    try:
        os.makedirs(os.path.dirname(LAST_GOOD_PATH), exist_ok=True)
        with open(LAST_GOOD_PATH, "w") as f:
            json.dump(
                {
                    "date": date_out,
                    "provenance": "bench.py full run (fetch-synced timing)",
                    "payload": rec,
                },
                f,
                indent=1,
            )
            f.write("\n")
        _log(f"last-good cache refreshed: {LAST_GOOD_PATH}")
    except Exception as e:  # cache refresh must never fail the bench
        _log(f"last-good cache write failed: {e}")


def _degraded_record(platform_status: str, fresh_rec):
    """Build the outage-shaped output: last-good hardware payload as the
    headline (flagged stale), fresh CPU smoke as the parity row."""
    lg = _load_last_good()
    payload = (lg or {}).get("payload") or {}
    out = {
        "metric": METRIC,
        "value": float(payload.get("value", 0.0)),
        "unit": UNIT,
        "vs_baseline": float(payload.get("vs_baseline", 0.0)),
        "degraded": True,
        "stale": lg is not None,
        "platform_status": platform_status,
        "last_good": lg,
    }
    if fresh_rec is not None:
        out["cpu_smoke"] = fresh_rec
    else:
        out["cpu_smoke"] = {"error": "cpu smoke bench also failed"}
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="tiny 5-step bench only")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    # Outage budget: tunnel outages run HOURS (round 3 lost the whole
    # driver window to 240s probes x 4 retries x 300s backoff).  Retrying
    # inside one bench run cannot outlast an outage, so fail FAST into a
    # structured degraded record instead: worst case here is
    # 120 + 30 + 120 = 270s of probing before the CPU fallback.
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--probe-retries", type=int, default=1)
    ap.add_argument("--probe-retry-wait", type=float, default=30.0)
    # The full child now times ~20 rows (headline + 11 engine-extras +
    # 8 batch/trunk rows incl. two ViT-B/16 compiles), each with TWO
    # timed windows (min taken — tunnel jitter); 900s truncated the
    # tail via the 0.75x soft deadline, so the budget matches the row
    # count and window doubling.  A mid-bench tunnel death still
    # degrades cleanly: the parent kills the child at this timeout,
    # salvages the spill, and falls through to the smoke + last-good
    # record only if not even the headline was measured.
    ap.add_argument("--full-timeout", type=float, default=3000.0)
    ap.add_argument("--smoke-timeout", type=float, default=300.0)
    ap.add_argument(
        "--rows", default=None, metavar="NAME,...",
        help="selective re-measure: only these rows ('headline', "
        "engine-extras names like blockwise_flagship, batch_scaling "
        "keys like vit_b16_128); everything else is marked skipped and "
        "the result MERGES into bench_cache/last_good.json instead of "
        "replacing it (re-pass recipe, ADVICE #2)",
    )
    ap.add_argument(
        "--warmup-rows", default=None, metavar="NAME,...",
        help="AOT-compile these rows' train-step programs into the "
        "committed bench_cache/xla_cache (Solver.warmup) and exit — "
        "run OUTSIDE a measuring window so large-batch compiles "
        "(480/960) stop burning tunnel minutes; names from the "
        "batch_scaling vocabulary plus 'headline'",
    )
    ap.add_argument("--warmup-timeout", type=float, default=5400.0,
                    help="wall budget for the --warmup-rows child (the "
                    "batch-480 compile alone has run 25 minutes)")
    # child modes (internal)
    ap.add_argument("--child", choices=["probe", "full", "smoke", "warmup"])
    ap.add_argument("--platform", default="default")
    ap.add_argument("--soft-budget", type=float, default=900.0)
    args = ap.parse_args(argv)

    # Validate --rows/--warmup-rows BEFORE dispatching: a typo'd row
    # name matches nothing downstream, so the re-pass would burn a
    # tunnel-window child measuring zero rows while still stamping
    # merge provenance (same contract as known_row_names for --rows).
    if args.rows:
        unknown = {r.strip() for r in args.rows.split(",") if r.strip()}
        unknown -= known_row_names()
        if unknown:
            ap.error(
                f"--rows: unknown row name(s) {sorted(unknown)}; "
                f"known: {sorted(known_row_names())}"
            )
    if args.warmup_rows:
        unknown = {r.strip() for r in args.warmup_rows.split(",")
                   if r.strip()}
        unknown -= warmable_row_names()
        if unknown:
            ap.error(
                f"--warmup-rows: unknown/unwarmable row name(s) "
                f"{sorted(unknown)}; warmable: "
                f"{sorted(warmable_row_names())}"
            )

    if args.child == "probe":
        return child_probe(args.platform)
    if args.child == "full":
        return child_full(args.platform, args.steps, args.warmup,
                          args.soft_budget, rows=args.rows)
    if args.child == "smoke":
        return child_smoke(args.platform)
    if args.child == "warmup":
        return child_warmup(args.platform, args.rows or "")

    os.makedirs(CACHE_DIR, exist_ok=True)

    # Phase 1: which backend comes up?  A hung TPU plugin init (observed:
    # axon backend UNAVAILABLE, BENCH_r01; multi-hour relay outage,
    # round 3) must not kill the bench — but a transient outage deserves
    # a few retries before surrendering the round's numbers to CPU.
    probe = None
    for attempt in range(args.probe_retries + 1):
        if attempt:
            _log(
                f"default backend probe timed out (attempt {attempt}); "
                f"retrying in {args.probe_retry_wait:.0f}s"
            )
            time.sleep(args.probe_retry_wait)
        probe, reason = _run_child_ex(["--child", "probe"], args.probe_timeout)
        if probe is not None or reason != "timeout":
            # Only timeout-shaped failures look like a transient tunnel
            # outage; rc/nojson failures are deterministic — retrying
            # them just delays the CPU fallback.
            break
    platform = "default"
    platform_status = "default backend ok"
    if probe is None:
        platform_status = (
            f"default (axon TPU) backend probe failed ({reason}) after "
            f"{args.probe_retries + 1} attempts x {args.probe_timeout:.0f}s "
            "— tunnel outage; reporting last-good hardware payload (stale) "
            "+ fresh CPU smoke"
        )
        _log("default backend failed to initialize; falling back to CPU")
        probe = _run_child(
            ["--child", "probe", "--platform", "cpu"],
            min(args.probe_timeout, 90.0),
        )
        platform = "cpu"
        if probe is None:
            rec = _degraded_record(
                platform_status + "; CPU probe ALSO failed", None
            )
            rec["error"] = "no jax backend (TPU or CPU) initialized within timeout"
            return _emit(rec)
    _log(f"probe ok: {probe}")

    if args.warmup_rows:
        # AOT warmup mode: populate the committed compile cache and
        # exit — no measurement, no last_good refresh.  Skipped on the
        # CPU-outage fallback: CPU executables in the committed cache
        # would be dead weight (entries are platform-keyed).
        if platform == "cpu":
            return _emit({
                "metric": "aot_warmup_compile_seconds",
                "mode": "warmup",
                "degraded": True,
                "platform_status": platform_status,
                "error": "TPU backend unavailable; refusing to warm the "
                         "committed cache with CPU executables",
            })
        rec = _run_child(
            ["--child", "warmup", "--platform", platform,
             "--rows", args.warmup_rows],
            args.warmup_timeout,
        )
        if rec is None:
            rec = {
                "metric": "aot_warmup_compile_seconds",
                "mode": "warmup",
                "error": "warmup child failed or timed out",
            }
        return _emit(rec)

    if platform == "cpu":
        # Outage path: run only the cheap CPU smoke as a liveness/parity
        # row, and headline the cached hardware number (flagged stale).
        smoke = _run_child(
            ["--child", "smoke", "--platform", "cpu"], args.smoke_timeout
        )
        return _emit(_degraded_record(platform_status, smoke))

    attempts = []
    if not args.smoke:
        full_args = ["--child", "full", "--platform", platform,
                     "--steps", str(args.steps),
                     "--warmup", str(args.warmup),
                     "--soft-budget", str(args.full_timeout)]
        if args.rows:
            full_args += ["--rows", args.rows]
        attempts.append((full_args, args.full_timeout))
    attempts.append((
        ["--child", "smoke", "--platform", platform], args.smoke_timeout,
    ))
    attempts.append((
        ["--child", "smoke", "--platform", "cpu"], args.smoke_timeout,
    ))

    # Pin a pid-scoped spill path into the children's environment so
    # concurrent bench runs cannot clobber or cross-salvage spills.
    global SPILL_PATH
    if "BENCH_SPILL_PATH" not in os.environ:
        SPILL_PATH = f"/tmp/bench_spill.{os.getpid()}.json"
        os.environ["BENCH_SPILL_PATH"] = SPILL_PATH
    _clear_spill()
    for child_args, timeout in attempts:
        rec = _run_child(child_args, timeout)
        if rec is None and "full" in child_args:
            # The full child died mid-run (tunnel wedge / OOM / kill):
            # salvage whatever it spilled — headline + completed extras
            # beat falling through to a stale degraded record.
            rec = _salvage_from_spill()
        if rec is not None:
            if rec.get("mode") == "full" and "error" not in rec:
                # A completed full bench is never "degraded" — but only a
                # TPU run refreshes the committed hardware cache.
                if rec.get("platform") == "tpu":
                    _save_last_good(rec)
            elif not args.smoke:
                # Probe succeeded but the full bench did not — mid-run
                # tunnel death or OOM.  Report the fresh (smoke) number
                # but attach the degraded context + last-good payload.
                rec = dict(rec)
                rec["degraded"] = True
                rec["platform_status"] = (
                    "backend probe ok but full bench failed; fresh record "
                    f"is {rec.get('mode', '?')}@{rec.get('platform', '?')}"
                )
                lg = _load_last_good()
                if lg is not None:
                    rec["last_good"] = lg
            _clear_spill()  # consumed (or superseded) — don't litter /tmp
            return _emit(rec)

    rec = _degraded_record(
        f"all bench variants failed or timed out (backend probe said {probe})",
        None,
    )
    rec["error"] = "all bench variants failed or timed out"
    _clear_spill()
    return _emit(rec)


if __name__ == "__main__":
    sys.exit(main())
