"""Benchmark: embeddings/sec/chip for the flagship training step.

Measures the reference's headline workload (BASELINE.md): GoogLeNet
embedding trunk + L2 normalize + mined N-pair loss (shipped def.prototxt
mining config) + analytic backward + Caffe-SGD update + in-graph
Recall@{1,5,10} metrics, batch 120 (60 ids x 2 imgs, def.prototxt:21-27),
as ONE jitted graph on the current accelerator.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
compares against a documented estimate of the Caffe+MPI original on its
contemporary GPU: ~400 embeddings/sec/GPU (GoogLeNet fwd+bwd at ~75 ms per
batch-32 on a Maxwell Titan X scaled to batch 120, plus the loss layer's
per-step host mining loop and CPU-buffer MPI round trips). North-star
target is >= 4x (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

BASELINE_EMBEDDINGS_PER_SEC = 400.0
BATCH = 120
IMAGE = 224
STEPS = 20
WARMUP = 3


def main():
    import jax
    import jax.numpy as jnp

    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("googlenet", dtype=jnp.bfloat16),
        REFERENCE_CONFIG,
        SolverConfig(
            base_lr=0.001, lr_policy="step", stepsize=10000, gamma=0.5,
            momentum=0.9, weight_decay=2e-5, display=0, snapshot=0,
        ),
        input_shape=(IMAGE, IMAGE, 3),
    )

    rng = np.random.default_rng(0)
    images = rng.standard_normal((BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
    labels = np.repeat(np.arange(BATCH // 2), 2).astype(np.int32)

    x = jax.device_put(jnp.asarray(images))
    lab = jax.device_put(jnp.asarray(labels))

    for _ in range(WARMUP):
        m = solver.step(x, lab)
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for _ in range(STEPS):
        m = solver.step(x, lab)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    emb_per_sec = BATCH * STEPS / dt
    print(
        json.dumps(
            {
                "metric": "googlenet_npair_train_embeddings_per_sec_per_chip",
                "value": round(emb_per_sec, 2),
                "unit": "embeddings/sec/chip",
                "vs_baseline": round(emb_per_sec / BASELINE_EMBEDDINGS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
