// Native data runtime: the TPU-side equivalent of the reference's C++
// MultibatchData layer (implied host framework, SURVEY.md §1 L1, §3.5).
//
// The reference decodes, resizes and assembles identity-balanced batches
// on CPU prefetch threads inside Caffe (usage/def.prototxt:2-29:
// root_folder + source list, batch = identity_num_per_batch x
// img_num_per_identity, shuffle, new_height/new_width).  This library
// reproduces that host runtime natively for the JAX framework:
//
//   * list-file dataset ("relative/path label" rows),
//   * identity-balanced sampler (same contract as
//     npairloss_tpu.data.sampler: distinct identities per batch,
//     within-identity draw-without-replacement with refill, replacement
//     only for degenerate identities),
//   * image decode (PPM/PGM, BMP 24/32-bit, NPY uint8) + bilinear
//     resize with OpenCV's half-pixel-center convention (what Caffe's
//     cv::resize INTER_LINEAR used),
//   * a worker thread pool filling a bounded prefetch ring of uint8
//     NHWC batch buffers.
//
// Exposed as a C ABI consumed via ctypes (npairloss_tpu/data/native.py).
// Augmentation stays on-device (data/transforms.py) — the host's job is
// only sample/decode/resize/assemble, which is exactly what this does.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <queue>
#include <fstream>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

// JPEG decode via the system libjpeg (CUB-200-2011 / Stanford Online
// Products — the reference's actual workloads, usage/def.prototxt:17-24 —
// are JPEG).  Compile-time optional: builds without the header fall back
// to the Python/PIL path for JPEG datasets; -DND_NO_JPEG force-disables
// (the binding's build uses it to retry when linking -ljpeg fails).
#if !defined(ND_NO_JPEG) && defined(__has_include)
#  if __has_include(<jpeglib.h>)
#    define ND_HAVE_LIBJPEG 1
#  endif
#endif
#ifdef ND_HAVE_LIBJPEG
#include <csetjmp>
#include <cstdio>
#include <jpeglib.h>
#endif

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

// ---------------------------------------------------------------------------
// Decoders -> uint8 RGB, row-major HWC
// ---------------------------------------------------------------------------

struct Image {
  int h = 0, w = 0;
  std::vector<uint8_t> rgb;  // h*w*3
};

bool read_file(const std::string& path, std::vector<uint8_t>& out) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) {
    set_error("cannot open file: " + path);
    return false;
  }
  std::streamsize size = f.tellg();
  f.seekg(0);
  out.resize(static_cast<size_t>(size));
  if (!f.read(reinterpret_cast<char*>(out.data()), size)) {
    set_error("short read: " + path);
    return false;
  }
  return true;
}

// PPM (P6) / PGM (P5), binary variants with maxval <= 255.  The header
// is parsed directly over the byte buffer (no bounded window, no
// stream-position arithmetic): arbitrarily long comment runs parse, and
// a truncated header fails cleanly instead of computing an offset from
// tellg() == -1 (ADVICE r1).
bool decode_pnm(const std::vector<uint8_t>& buf, Image& img) {
  const size_t n = buf.size();
  if (n < 2 || buf[0] != 'P' || (buf[1] != '5' && buf[1] != '6')) {
    set_error("not a binary PNM");
    return false;
  }
  const bool color = buf[1] == '6';
  size_t p = 2;
  long vals[3];
  for (int got = 0; got < 3;) {
    // Skip whitespace and '#' comments between header tokens.
    while (p < n) {
      if (buf[p] == '#') {
        while (p < n && buf[p] != '\n') ++p;
      } else if (std::isspace(buf[p])) {
        ++p;
      } else {
        break;
      }
    }
    if (p >= n || !std::isdigit(buf[p])) {
      set_error("bad PNM header");
      return false;
    }
    long v = 0;
    while (p < n && std::isdigit(buf[p])) {
      v = v * 10 + (buf[p] - '0');
      if (v > (1L << 30)) {
        set_error("bad PNM header (value overflow)");
        return false;
      }
      ++p;
    }
    vals[got++] = v;
  }
  if (vals[2] <= 0 || vals[2] > 255) {
    set_error("PNM maxval > 255 unsupported");
    return false;
  }
  if (vals[0] <= 0 || vals[1] <= 0) {
    set_error("PNM dimensions must be positive");
    return false;
  }
  img.w = static_cast<int>(vals[0]);
  img.h = static_cast<int>(vals[1]);
  // Pixel data starts after a single whitespace char past maxval (PNM
  // spec) — but Windows writers emit "\r\n"; treat CRLF as one
  // terminator or every pixel decodes one byte out of register.
  if (p >= n || !std::isspace(buf[p])) {
    set_error("bad PNM header (missing pixel-data separator)");
    return false;
  }
  size_t offset = p + 1;
  if (buf[p] == '\r' && offset < n && buf[offset] == '\n') ++offset;
  const size_t ch = color ? 3 : 1;
  const size_t need = static_cast<size_t>(img.h) * img.w * ch;
  if (buf.size() < offset + need) {
    set_error("truncated PNM pixel data");
    return false;
  }
  img.rgb.resize(static_cast<size_t>(img.h) * img.w * 3);
  const uint8_t* src = buf.data() + offset;
  if (color) {
    std::memcpy(img.rgb.data(), src, need);
  } else {
    for (size_t i = 0; i < need; ++i) {
      img.rgb[3 * i] = img.rgb[3 * i + 1] = img.rgb[3 * i + 2] = src[i];
    }
  }
  return true;
}

uint32_t le32(const uint8_t* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
}

// Uncompressed 24/32-bit BMP (BGR(A), bottom-up or top-down).
bool decode_bmp(const std::vector<uint8_t>& buf, Image& img) {
  if (buf.size() < 54) {
    set_error("truncated BMP header");
    return false;
  }
  const uint32_t pix_off = le32(&buf[10]);
  const int32_t w = static_cast<int32_t>(le32(&buf[18]));
  int32_t h = static_cast<int32_t>(le32(&buf[22]));
  const uint16_t bpp = buf[28] | (buf[29] << 8);
  const uint32_t compression = le32(&buf[30]);
  if (compression != 0 || (bpp != 24 && bpp != 32)) {
    set_error("only uncompressed 24/32-bit BMP supported");
    return false;
  }
  const bool bottom_up = h > 0;
  if (h < 0) h = -h;
  if (w <= 0 || h == 0) {
    set_error("BMP dimensions must be positive");
    return false;
  }
  const int bytes = bpp / 8;
  const size_t stride = (static_cast<size_t>(w) * bytes + 3) & ~size_t(3);
  if (buf.size() < pix_off + stride * h) {
    set_error("truncated BMP pixel data");
    return false;
  }
  img.w = w;
  img.h = h;
  img.rgb.resize(static_cast<size_t>(h) * w * 3);
  for (int y = 0; y < h; ++y) {
    const int src_y = bottom_up ? (h - 1 - y) : y;
    const uint8_t* row = buf.data() + pix_off + stride * src_y;
    uint8_t* dst = img.rgb.data() + static_cast<size_t>(y) * w * 3;
    for (int x = 0; x < w; ++x) {
      dst[3 * x + 0] = row[bytes * x + 2];  // BGR -> RGB
      dst[3 * x + 1] = row[bytes * x + 1];
      dst[3 * x + 2] = row[bytes * x + 0];
    }
  }
  return true;
}

// NPY v1/v2, uint8 ('|u1'), C-order, shape (H, W), (H, W, 1) or (H, W, 3).
bool decode_npy(const std::vector<uint8_t>& buf, Image& img) {
  if (buf.size() < 10 || std::memcmp(buf.data(), "\x93NUMPY", 6) != 0) {
    set_error("not an NPY file");
    return false;
  }
  const int major = buf[6];
  size_t hlen, data_off;
  if (major == 1) {
    hlen = buf[8] | (buf[9] << 8);
    data_off = 10 + hlen;
  } else {
    if (buf.size() < 12) {
      set_error("truncated NPY header");
      return false;
    }
    hlen = le32(&buf[8]);
    data_off = 12 + hlen;
  }
  if (buf.size() < data_off) {
    set_error("truncated NPY header");
    return false;
  }
  std::string header(reinterpret_cast<const char*>(
                         buf.data() + (major == 1 ? 10 : 12)), hlen);
  if (header.find("|u1") == std::string::npos) {
    set_error("NPY dtype must be uint8 ('|u1')");
    return false;
  }
  if (header.find("'fortran_order': False") == std::string::npos) {
    set_error("NPY must be C-order");
    return false;
  }
  const size_t sp = header.find("'shape': (");
  if (sp == std::string::npos) {
    set_error("NPY header missing shape");
    return false;
  }
  std::vector<long> dims;
  {
    std::istringstream ss(header.substr(sp + 10));
    long v;
    while (ss >> v) {
      dims.push_back(v);
      while (ss.peek() == ',' || ss.peek() == ' ') ss.get();
      if (ss.peek() == ')') break;
    }
  }
  int ch;
  if (dims.size() == 2 || (dims.size() == 3 && dims[2] == 1)) {
    ch = 1;
  } else if (dims.size() == 3 && dims[2] == 3) {
    ch = 3;
  } else {
    set_error("NPY shape must be (H,W), (H,W,1) or (H,W,3)");
    return false;
  }
  if (dims[0] <= 0 || dims[1] <= 0) {
    set_error("NPY dimensions must be positive");
    return false;
  }
  img.h = static_cast<int>(dims[0]);
  img.w = static_cast<int>(dims[1]);
  const size_t need = static_cast<size_t>(img.h) * img.w * ch;
  if (buf.size() < data_off + need) {
    set_error("truncated NPY data");
    return false;
  }
  const uint8_t* src = buf.data() + data_off;
  img.rgb.resize(static_cast<size_t>(img.h) * img.w * 3);
  if (ch == 3) {
    std::memcpy(img.rgb.data(), src, need);
  } else {
    for (size_t i = 0; i < need; ++i) {
      img.rgb[3 * i] = img.rgb[3 * i + 1] = img.rgb[3 * i + 2] = src[i];
    }
  }
  return true;
}

#ifdef ND_HAVE_LIBJPEG

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
  char msg[JMSG_LENGTH_MAX];
};

void jpeg_error_trap(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  (*cinfo->err->format_message)(cinfo, err->msg);
  longjmp(err->jump, 1);
}

// Baseline + progressive JPEG -> RGB via libjpeg (grayscale converts in
// the library; exotic CMYK/YCCK error out to the Python path).
bool decode_jpeg(const std::vector<uint8_t>& buf, Image& img) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  jerr.msg[0] = '\0';
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_error_trap;
  if (setjmp(jerr.jump)) {
    set_error(std::string("JPEG decode failed: ") + jerr.msg);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf.data(), static_cast<unsigned long>(buf.size()));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {
    set_error("JPEG output is not 3-channel RGB");
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  img.w = static_cast<int>(cinfo.output_width);
  img.h = static_cast<int>(cinfo.output_height);
  img.rgb.resize(static_cast<size_t>(img.h) * img.w * 3);
  const size_t stride = static_cast<size_t>(img.w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = img.rgb.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

#endif  // ND_HAVE_LIBJPEG

bool decode_image(const std::vector<uint8_t>& buf, Image& img) {
  if (buf.size() >= 2 && buf[0] == 'P' && (buf[1] == '5' || buf[1] == '6'))
    return decode_pnm(buf, img);
  if (buf.size() >= 2 && buf[0] == 'B' && buf[1] == 'M')
    return decode_bmp(buf, img);
  if (buf.size() >= 6 && std::memcmp(buf.data(), "\x93NUMPY", 6) == 0)
    return decode_npy(buf, img);
#ifdef ND_HAVE_LIBJPEG
  if (buf.size() >= 3 && buf[0] == 0xFF && buf[1] == 0xD8 && buf[2] == 0xFF)
    return decode_jpeg(buf, img);
  set_error("unsupported image format (supported: JPEG, PPM/PGM, BMP, NPY-u8)");
#else
  set_error("unsupported image format (supported: PPM/PGM, BMP, NPY-u8; "
            "built without libjpeg)");
#endif
  return false;
}

// Bilinear resize, OpenCV INTER_LINEAR convention (half-pixel centers):
// src = (dst + 0.5) * scale - 0.5, border-clamped — what Caffe's
// cv::resize did in the reference's implied data layer.
void bilinear_resize(const Image& src, int dh, int dw, uint8_t* dst) {
  if (src.h == dh && src.w == dw) {
    std::memcpy(dst, src.rgb.data(), static_cast<size_t>(dh) * dw * 3);
    return;
  }
  const float sy = static_cast<float>(src.h) / dh;
  const float sx = static_cast<float>(src.w) / dw;
  std::vector<int> x0s(dw), x1s(dw);
  std::vector<float> wxs(dw);
  for (int x = 0; x < dw; ++x) {
    float fx = (x + 0.5f) * sx - 0.5f;
    if (fx < 0) fx = 0;
    int x0 = static_cast<int>(fx);
    if (x0 > src.w - 1) x0 = src.w - 1;
    x0s[x] = x0;
    x1s[x] = std::min(x0 + 1, src.w - 1);
    wxs[x] = fx - x0;
  }
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    if (y0 > src.h - 1) y0 = src.h - 1;
    const int y1 = std::min(y0 + 1, src.h - 1);
    const float wy = fy - y0;
    const uint8_t* r0 = src.rgb.data() + static_cast<size_t>(y0) * src.w * 3;
    const uint8_t* r1 = src.rgb.data() + static_cast<size_t>(y1) * src.w * 3;
    uint8_t* out = dst + static_cast<size_t>(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const int x0 = 3 * x0s[x], x1 = 3 * x1s[x];
      const float wx = wxs[x];
      for (int c = 0; c < 3; ++c) {
        const float top = r0[x0 + c] + (r0[x1 + c] - r0[x0 + c]) * wx;
        const float bot = r1[x0 + c] + (r1[x1 + c] - r1[x0 + c]) * wx;
        const float v = top + (bot - top) * wy;
        out[3 * x + c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Dataset
// ---------------------------------------------------------------------------

struct Dataset {
  std::string root;
  std::vector<std::string> paths;
  std::vector<int64_t> labels;
  int new_h = 0, new_w = 0;

  std::string full_path(size_t index) const {
    std::string full = root;
    if (!full.empty() && full.back() != '/') full += '/';
    full += paths[index];
    return full;
  }

  bool load_into(size_t index, uint8_t* dst, int* out_h, int* out_w) const {
    std::vector<uint8_t> buf;
    Image img;
    if (!read_file(full_path(index), buf) || !decode_image(buf, img))
      return false;
    const int dh = new_h > 0 ? new_h : img.h;
    const int dw = new_w > 0 ? new_w : img.w;
    *out_h = dh;
    *out_w = dw;
    bilinear_resize(img, dh, dw, dst);
    return true;
  }

  bool dims(size_t index, int* out_h, int* out_w) const {
    if (new_h > 0 && new_w > 0) {  // fixed output shape, no decode needed
      *out_h = new_h;
      *out_w = new_w;
      return true;
    }
    std::vector<uint8_t> buf;
    Image img;
    if (!read_file(full_path(index), buf) || !decode_image(buf, img))
      return false;
    *out_h = img.h;
    *out_w = img.w;
    return true;
  }
};

// ---------------------------------------------------------------------------
// Identity-balanced sampler (contract of npairloss_tpu.data.sampler)
// ---------------------------------------------------------------------------

struct Sampler {
  std::vector<int64_t> identities;                       // sorted unique
  std::unordered_map<int64_t, std::vector<int64_t>> by_identity;
  std::unordered_map<int64_t, std::vector<int64_t>> pools;  // w/o-replacement
  std::vector<int64_t> id_order;                         // sequential mode
  size_t id_pos = 0;
  int ids_per_batch, imgs_per_id;
  bool rand_identity, shuffle;
  std::mt19937_64 rng;

  Sampler(const std::vector<int64_t>& labels, int ids, int imgs,
          bool rand_id, bool shuf, uint64_t seed)
      : ids_per_batch(ids), imgs_per_id(imgs), rand_identity(rand_id),
        shuffle(shuf), rng(seed) {
    for (size_t i = 0; i < labels.size(); ++i)
      by_identity[labels[i]].push_back(static_cast<int64_t>(i));
    identities.reserve(by_identity.size());
    for (auto& kv : by_identity) identities.push_back(kv.first);
    std::sort(identities.begin(), identities.end());
    id_order = identities;
    if (shuffle) std::shuffle(id_order.begin(), id_order.end(), rng);
  }

  void draw_images(int64_t identity, std::vector<int64_t>& out) {
    auto& pool = by_identity[identity];
    if (static_cast<int>(pool.size()) < imgs_per_id) {
      // Degenerate identity: with replacement (batch contract must hold
      // for the mining statistics).
      std::uniform_int_distribution<size_t> d(0, pool.size() - 1);
      for (int i = 0; i < imgs_per_id; ++i) out.push_back(pool[d(rng)]);
      return;
    }
    std::vector<int64_t> picked;
    while (static_cast<int>(picked.size()) < imgs_per_id) {
      auto& cached = pools[identity];
      if (cached.empty()) {
        // Refill excluding this batch's picks: a group never holds the
        // same image twice.
        for (int64_t i : pool)
          if (std::find(picked.begin(), picked.end(), i) == picked.end())
            cached.push_back(i);
        if (shuffle) std::shuffle(cached.begin(), cached.end(), rng);
      }
      picked.push_back(cached.back());
      cached.pop_back();
    }
    out.insert(out.end(), picked.begin(), picked.end());
  }

  void next_batch(std::vector<int64_t>& out) {
    std::vector<int64_t> chosen;
    if (rand_identity) {
      // Partial Fisher-Yates over a scratch copy: distinct identities.
      std::vector<int64_t> scratch = identities;
      for (int i = 0; i < ids_per_batch; ++i) {
        std::uniform_int_distribution<size_t> d(i, scratch.size() - 1);
        std::swap(scratch[i], scratch[d(rng)]);
        chosen.push_back(scratch[i]);
      }
    } else {
      while (static_cast<int>(chosen.size()) < ids_per_batch) {
        if (id_pos >= id_order.size()) {
          id_pos = 0;
          if (shuffle) std::shuffle(id_order.begin(), id_order.end(), rng);
        }
        const int64_t cand = id_order[id_pos++];
        if (std::find(chosen.begin(), chosen.end(), cand) == chosen.end())
          chosen.push_back(cand);
      }
    }
    out.clear();
    for (int64_t identity : chosen) draw_images(identity, out);
  }
};

// ---------------------------------------------------------------------------
// Prefetching loader: worker pool + bounded ring of batch buffers
// ---------------------------------------------------------------------------

struct Batch {
  uint64_t seq = 0;             // sampler draw order; delivery is in-order
  std::vector<uint8_t> images;  // batch*h*w*3
  std::vector<int32_t> labels;  // batch
};

struct BatchSeqGreater {
  bool operator()(const Batch& a, const Batch& b) const {
    return a.seq > b.seq;
  }
};

struct Loader {
  const Dataset* ds;
  Sampler sampler;
  int batch_size, h, w;
  size_t capacity;

  std::mutex sampler_mu;
  uint64_t next_seq = 0;        // guarded by sampler_mu
  std::mutex q_mu;
  std::condition_variable q_not_empty, q_not_full;
  // Min-heap on seq + in-order release: with threads > 1 workers finish
  // out of order, but consumers see batches in sampler draw order, so
  // seeded runs are reproducible like the single-worker Python loader.
  std::priority_queue<Batch, std::vector<Batch>, BatchSeqGreater> queue;
  uint64_t next_deliver = 0;    // guarded by q_mu
  std::atomic<bool> stop{false};
  std::string worker_error;  // guarded by q_mu; first error wins
  std::vector<std::thread> workers;

  Loader(const Dataset* d, int ids, int imgs, bool rand_id, bool shuf,
         uint64_t seed, int threads, int prefetch)
      : ds(d), sampler(d->labels, ids, imgs, rand_id, shuf, seed),
        batch_size(ids * imgs),
        h(d->new_h), w(d->new_w),
        capacity(std::max(prefetch, 1)) {
    for (int t = 0; t < std::max(threads, 1); ++t)
      workers.emplace_back([this] { work(); });
  }

  ~Loader() {
    stop.store(true);
    q_not_full.notify_all();
    q_not_empty.notify_all();
    for (auto& t : workers) t.join();
  }

  void work() {
    while (!stop.load()) {
      std::vector<int64_t> idx;
      uint64_t seq;
      {
        std::lock_guard<std::mutex> lk(sampler_mu);
        sampler.next_batch(idx);
        seq = next_seq++;
      }
      Batch b;
      b.seq = seq;
      b.images.resize(static_cast<size_t>(batch_size) * h * w * 3);
      b.labels.resize(batch_size);
      bool ok = true;
      for (int i = 0; i < batch_size; ++i) {
        int oh, ow;
        if (!ds->load_into(static_cast<size_t>(idx[i]),
                           b.images.data() +
                               static_cast<size_t>(i) * h * w * 3,
                           &oh, &ow)) {
          ok = false;
          break;
        }
        if (oh != h || ow != w) {
          set_error("image dims vary but no new_height/new_width given");
          ok = false;
          break;
        }
        b.labels[i] = static_cast<int32_t>(ds->labels[idx[i]]);
      }
      std::unique_lock<std::mutex> lk(q_mu);
      if (!ok) {
        if (worker_error.empty()) worker_error = g_last_error;
        stop.store(true);
        q_not_empty.notify_all();
        return;
      }
      // Window = capacity + worker count: the worker holding the next
      // deliverable seq can always enter, so in-order release cannot
      // deadlock behind later batches from faster workers.
      q_not_full.wait(lk, [this, seq] {
        return stop.load() ||
               seq < next_deliver + capacity + workers.size();
      });
      if (stop.load()) return;
      queue.push(std::move(b));
      q_not_empty.notify_all();
    }
  }

  // 0 ok, 1 failed (see nd_last_error)
  int next(uint8_t* images, int32_t* labels) {
    std::unique_lock<std::mutex> lk(q_mu);
    q_not_empty.wait(lk, [this] {
      return stop.load() ||
             (!queue.empty() && queue.top().seq == next_deliver);
    });
    if (queue.empty() || queue.top().seq != next_deliver) {
      set_error(worker_error.empty() ? "loader stopped" : worker_error);
      return 1;
    }
    Batch b = std::move(const_cast<Batch&>(queue.top()));
    queue.pop();
    ++next_deliver;
    q_not_full.notify_all();
    lk.unlock();
    std::memcpy(images, b.images.data(), b.images.size());
    std::memcpy(labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
    return 0;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

const char* nd_last_error() { return g_last_error.c_str(); }

// 1 when this build decodes JPEG natively (drives the binding's
// list-file routing: JPEG datasets stay on the C++ runtime only then).
int nd_has_jpeg() {
#ifdef ND_HAVE_LIBJPEG
  return 1;
#else
  return 0;
#endif
}

void* nd_dataset_open(const char* root, const char* source, int new_h,
                      int new_w, long long* n_items) {
  auto ds = new Dataset;
  ds->root = root ? root : "";
  ds->new_h = new_h;
  ds->new_w = new_w;
  std::ifstream f(source);
  if (!f) {
    set_error(std::string("cannot open list file: ") + source);
    delete ds;
    return nullptr;
  }
  std::string line;
  while (std::getline(f, line)) {
    // Trim trailing CR/whitespace; skip blanks and '#' comments.
    while (!line.empty() && std::isspace(
               static_cast<unsigned char>(line.back())))
      line.pop_back();
    if (line.empty() || line[0] == '#') continue;
    // Label is the last whitespace-separated token (paths may hold spaces).
    size_t cut = line.find_last_of(" \t");
    if (cut == std::string::npos) {
      set_error("malformed list line: " + line);
      delete ds;
      return nullptr;
    }
    const std::string lbl = line.substr(cut + 1);
    size_t start = line.find_last_not_of(" \t", cut);
    try {
      ds->labels.push_back(
          static_cast<int64_t>(std::stod(lbl)));
    } catch (...) {
      set_error("bad label in list line: " + line);
      delete ds;
      return nullptr;
    }
    ds->paths.push_back(line.substr(0, start + 1));
  }
  if (ds->paths.empty()) {
    set_error(std::string("empty list file: ") + source);
    delete ds;
    return nullptr;
  }
  *n_items = static_cast<long long>(ds->paths.size());
  return ds;
}

void nd_dataset_labels(void* handle, long long* out) {
  auto* ds = static_cast<Dataset*>(handle);
  for (size_t i = 0; i < ds->labels.size(); ++i) out[i] = ds->labels[i];
}

// Output dims of one item BEFORE loading: new_h/new_w when fixed, else
// the decoded native dims.  Completes the nd_dataset_load sizing
// contract for any ABI consumer (ADVICE r1: the contract used to be
// unsatisfiable outside the Python binding).
int nd_dataset_dims(void* handle, long long index, int* out_h, int* out_w) {
  auto* ds = static_cast<Dataset*>(handle);
  if (index < 0 || index >= static_cast<long long>(ds->paths.size())) {
    set_error("index out of range");
    return 1;
  }
  return ds->dims(static_cast<size_t>(index), out_h, out_w) ? 0 : 1;
}

// Decode + resize one item; the dst buffer must hold out_h*out_w*3 bytes
// as reported by nd_dataset_dims(index) (== new_h*new_w*3 when fixed).
int nd_dataset_load(void* handle, long long index, unsigned char* dst,
                    int* out_h, int* out_w) {
  auto* ds = static_cast<Dataset*>(handle);
  if (index < 0 || index >= static_cast<long long>(ds->paths.size())) {
    set_error("index out of range");
    return 1;
  }
  return ds->load_into(static_cast<size_t>(index), dst, out_h, out_w) ? 0 : 1;
}

void nd_dataset_close(void* handle) { delete static_cast<Dataset*>(handle); }

void* nd_loader_create(void* dataset, int ids_per_batch, int imgs_per_id,
                       int rand_identity, int shuffle,
                       unsigned long long seed, int threads, int prefetch) {
  auto* ds = static_cast<Dataset*>(dataset);
  if (ds->new_h <= 0 || ds->new_w <= 0) {
    set_error("loader requires new_height/new_width (fixed batch shape)");
    return nullptr;
  }
  std::unordered_set<int64_t> uniq(ds->labels.begin(), ds->labels.end());
  if (static_cast<int>(uniq.size()) < ids_per_batch) {
    set_error("need >= identity_num_per_batch distinct identities");
    return nullptr;
  }
  return new Loader(ds, ids_per_batch, imgs_per_id, rand_identity != 0,
                    shuffle != 0, seed, threads, prefetch);
}

int nd_loader_next(void* handle, unsigned char* images, int* labels) {
  return static_cast<Loader*>(handle)->next(
      images, reinterpret_cast<int32_t*>(labels));
}

void nd_loader_close(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
