"""Generate MultibatchData ``source`` list files from an image tree.

The reference's data layer consumes ``root_folder`` + ``source`` (a text
file of ``relative/path label`` lines, usage/def.prototxt:17-24) but the
tooling that produced those lists lived in the implied private fork.
This is its counterpart for the standard metric-learning layouts:

  class-per-directory (CUB-200-2011, Stanford Online Products extracts):
      root/<class_name>/<image>            -> label = class index

  optional train/test split by class id (the zero-shot protocol both
  CUB and SOP use: first half of classes train, second half test).

Usage:
  python tools/make_list.py ROOT --out train.txt
  python tools/make_list.py ROOT --out-train train.txt --out-test test.txt \
      --split-classes 100          # first 100 class ids -> train
  python tools/make_list.py ROOT --min-images 2   # drop singleton ids
                                  # (the sampler needs >= 2 per identity)

Deterministic: classes sorted by name, images sorted within a class.
"""

from __future__ import annotations

import argparse
import os
import sys

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".pgm", ".npy"}


def scan(root: str, min_images: int):
    """[(class_name, [relpath, ...])] sorted, singletons optionally dropped."""
    classes = []
    for name in sorted(os.listdir(root)):
        cdir = os.path.join(root, name)
        if not os.path.isdir(cdir):
            continue
        imgs = sorted(
            os.path.join(name, f)
            for f in os.listdir(cdir)
            if os.path.splitext(f)[1].lower() in IMAGE_EXTS
        )
        if len(imgs) >= min_images:
            classes.append((name, imgs))
        elif imgs:
            print(
                f"[make_list] dropping {name!r}: {len(imgs)} image(s) < "
                f"--min-images {min_images} (the identity-balanced sampler "
                "needs img_num_per_identity per id)",
                file=sys.stderr,
            )
    return classes


def write_list(path: str, entries):
    with open(path, "w", encoding="utf-8") as f:
        for rel, label in entries:
            f.write(f"{rel} {label}\n")
    print(f"[make_list] wrote {path}: {len(entries)} lines")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("root", help="image tree root (class-per-directory)")
    ap.add_argument("--out", help="single list file for ALL classes")
    ap.add_argument("--out-train", help="train list (with --split-classes)")
    ap.add_argument("--out-test", help="test list (with --split-classes)")
    ap.add_argument(
        "--split-classes", type=int, default=0,
        help="first N class ids -> train, rest -> test (zero-shot split)",
    )
    ap.add_argument(
        "--min-images", type=int, default=2,
        help="drop classes with fewer images (sampler needs >= 2/id)",
    )
    args = ap.parse_args()

    classes = scan(args.root, args.min_images)
    if not classes:
        print("[make_list] no classes found", file=sys.stderr)
        return 1

    if args.split_classes:
        if not (args.out_train and args.out_test):
            ap.error("--split-classes needs --out-train and --out-test")
        if not 0 < args.split_classes < len(classes):
            ap.error(
                f"--split-classes {args.split_classes} out of range: "
                f"{len(classes)} classes survive --min-images "
                f"{args.min_images}; a valid split leaves both sides "
                "non-empty"
            )
        train, test = [], []
        for label, (_, imgs) in enumerate(classes):
            dest = train if label < args.split_classes else test
            dest.extend((rel, label) for rel in imgs)
        write_list(args.out_train, train)
        write_list(args.out_test, test)
    else:
        if not args.out:
            ap.error("pass --out (or --split-classes with --out-train/--out-test)")
        entries = [
            (rel, label)
            for label, (_, imgs) in enumerate(classes)
            for rel in imgs
        ]
        write_list(args.out, entries)
    return 0


if __name__ == "__main__":
    sys.exit(main())
