"""ReplicaSet — N QueryEngine replicas behind one front end.

The serving tier's horizontal dimension (ROADMAP item 2; the
Gemma-serving shape from PAPERS.md — capacity is replicas x per-replica
throughput, operated against explicit p99/QPS targets): each replica is
one :class:`~npairloss_tpu.serve.engine.QueryEngine` with its OWN
:class:`~npairloss_tpu.serve.batcher.MicroBatcher` (own admission
queue, own dispatcher thread), and the front end routes each submitted
query to the least-loaded live replica.  Replicas of one index share
the primary engine's compiled programs
(``QueryEngine(share_compiled_with=...)``) so warming the primary warms
the tier and a replica restart deserializes from the shared persistent
compile cache instead of recompiling.

Crash containment: the ``serve.replica_crash`` failpoint
(docs/RESILIENCE.md) kills a replica mid-dispatch — its in-flight
batch, and every batch still queued on it, REROUTES to a surviving
replica (the server's ``_reroute``; replicas share one compiled-program
set, so the reroute costs no compile), and the router stops sending it
traffic: the crash is invisible to clients while any replica survives.
Only a whole-tier loss fails the work to error answers.  The front
end's accounting invariant (``queries == answered + errors +
rejected``) holds through the crash — pinned by
tests/test_serve_replicas.py and the resilience table.

Drain is per-replica: ``close(drain=True)`` drains every live replica's
queue to answers (the SIGTERM contract); a dead replica's queue drains
by rerouting, and fails loudly — never hangs — when no live replica
remains.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, List, Optional

from npairloss_tpu.serve.batcher import (
    BatcherConfig,
    MicroBatcher,
    QueueFullError,
)

log = logging.getLogger("npairloss_tpu.serve")


class ReplicaCrashError(RuntimeError):
    """A replica died (injected or real) and no live replica remains to
    absorb its work — with survivors the work reroutes instead, and
    this error never reaches a client."""


@dataclasses.dataclass
class Replica:
    """One engine + its batcher + liveness."""

    name: str
    engine: Any
    batcher: Optional[MicroBatcher] = None
    alive: bool = True


class ReplicaSet:
    """Route/submit/drain across N replicas.

    ``dispatch_factory(replica)`` returns the batcher dispatch callable
    for that replica (the server wires per-replica crash containment
    and the shared answer logic there).
    """

    def __init__(
        self,
        engines: List[Any],
        batcher_cfg: BatcherConfig,
        dispatch_factory: Callable[[Replica], Callable],
        span_fn=None,
        on_batch=None,
        on_pick=None,
    ):
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        self.replicas: List[Replica] = []
        for i, engine in enumerate(engines):
            rep = Replica(name=f"r{i}", engine=engine)
            rep.batcher = MicroBatcher(
                dispatch_factory(rep), batcher_cfg,
                span_fn=span_fn, on_batch=on_batch, on_pick=on_pick,
            )
            self.replicas.append(rep)
        # Rejections that never reached a batcher (no live replica) —
        # part of the aggregate ``rejected`` so the front-end invariant
        # holds even with the whole tier down.  Lock-guarded like every
        # other invariant term: concurrent HTTP submits against a down
        # tier must not lose counts.
        self.down_rejected = 0
        self._down_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaSet":
        for rep in self.replicas:
            rep.batcher.start()
        return self

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        for rep in self.replicas:
            # A dead replica drains by rerouting its queued batches to
            # the survivors; with the whole tier down its dispatch
            # fails every batch fast, which IS its drain.
            rep.batcher.close(drain=drain, timeout=timeout)

    # -- routing -----------------------------------------------------------

    def pick(self) -> Replica:
        """Least-loaded live replica; raises
        :class:`~npairloss_tpu.serve.batcher.QueueFullError` when the
        whole tier is down (counted in ``down_rejected``)."""
        live = [r for r in self.replicas if r.alive]
        if not live:
            with self._down_lock:
                self.down_rejected += 1
            raise QueueFullError("no live replicas")
        return min(live, key=lambda r: r.batcher.queue_depth)

    def submit(self, record):
        return self.pick().batcher.submit(record)

    # -- aggregates --------------------------------------------------------

    @property
    def alive_count(self) -> int:
        return sum(1 for r in self.replicas if r.alive)

    @property
    def queue_depth(self) -> int:
        return sum(r.batcher.queue_depth for r in self.replicas)

    @property
    def batches(self) -> int:
        return sum(r.batcher.batches for r in self.replicas)

    @property
    def dispatched(self) -> int:
        return sum(r.batcher.dispatched for r in self.replicas)

    @property
    def rejected(self) -> int:
        return (sum(r.batcher.rejected for r in self.replicas)
                + self.down_rejected)
