"""GalleryIndex — the mesh-resident gallery an online query runs against.

The gallery is the serving-side counterpart of the training negative
pool: (N, D) L2-normalized embeddings with their class labels and item
ids, laid out on the device mesh with rows sharded over the data
axis (``parallel.mesh`` sharding) so a gallery larger than one chip's
HBM still fits — each shard holds N/G rows and the query engine merges
per-shard top-k candidates.

Persistence rides the ``resilience.snapshot`` atomic-commit path: the
arrays are written as ``.npy`` into a ``.tmp-<pid>-<nonce>`` dir, a
``manifest.json`` with per-array CRC-32 records is fsync'd inside it,
and ``os.replace`` onto the final name is the commit point.  A torn or
bit-rotted index fails checksum verification at load and is skipped by
:func:`load_newest` with a logged reason — the same contract training
snapshots follow (docs/RESILIENCE.md), so a serving replica never
answers queries from a half-written gallery.

Rows are padded up to a multiple of the mesh size (and at least one row
per shard); padding rows carry ``valid == False`` and are masked to
-inf similarity inside the engine, so they can never appear in an
answer.  ``labels`` may be any int values — validity is tracked by the
mask, not a sentinel label.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.snapshot import (
    TMP_MARKER,
    SnapshotValidationError,
    _fsync_dir,
    read_manifest,
    state_checksums,
    validate_snapshot,
    verify_restored,
    write_manifest,
)

log = logging.getLogger("npairloss_tpu.serve")

INDEX_KIND = "gallery-index"
INDEX_SUFFIX = ".gidx"
_ARRAYS = ("emb", "labels", "ids")
# Committed-index kind -> loader class; ``ivf-index`` registers itself
# on import (serve/ivf.py) so load_index/load_newest dispatch without a
# hard import cycle.
_KIND_REGISTRY: dict = {}


def l2_normalize_rows(x: np.ndarray) -> np.ndarray:
    """Host-side safe row L2-normalize (an all-zero row stays zero) —
    the one definition build/add/query all share, so the gallery and
    the queries scored against it can never normalize differently."""
    return x / np.maximum(
        np.linalg.norm(x, axis=1, keepdims=True), 1e-12
    )


@dataclasses.dataclass
class GalleryIndex:
    """Mesh-resident gallery: sharded embeddings + labels + validity.

    ``emb``/``labels``/``valid`` are device arrays of padded length
    ``padded_size`` (rows sharded over ``mesh``'s axis when one is
    attached, single-device otherwise); ``ids`` is the host-side
    int64 item-id vector of TRUE length ``size`` — answers map a
    global gallery row back through it.  Build via :meth:`build` /
    :meth:`load`, never the raw constructor.
    """

    # Persistence identity: subclasses (serve/ivf.py's IVFIndex)
    # override these to commit extra arrays under their own kind while
    # reusing the one save/load/commit path.
    KIND = INDEX_KIND
    ARRAY_NAMES = _ARRAYS

    emb: jax.Array
    labels: jax.Array
    valid: jax.Array
    ids: np.ndarray
    size: int
    mesh: Optional[Mesh] = None
    axis: str = "dp"
    # Freshness identity (docs/OBSERVABILITY.md §Live observatory): the
    # wall time this gallery's content was committed/assembled —
    # ``load`` takes it from the commit manifest, ``build``/``add``
    # stamp now.  ``index_age_s`` on /healthz and per-answer freshness
    # stamps derive from it.
    created: Optional[float] = None
    # Durability watermark (docs/RESILIENCE.md §Durability): the last
    # WAL sequence number whose ingest this gallery CONTAINS.  Committed
    # into the manifest on save and restored on load, it is the one
    # sequence-number source of truth shared by snapshot publication,
    # cold-restart replay (records <= watermark are skipped —
    # exactly-once) and WAL segment GC.  0 = no WAL ingest applied.
    ingest_watermark: int = 0
    # Host master copy (unpadded, normalized): add() re-pads + re-places
    # from here instead of pulling the gallery back off the mesh.
    _host_emb: Optional[np.ndarray] = None
    _host_labels: Optional[np.ndarray] = None

    # -- construction -----------------------------------------------------

    @classmethod
    def build(
        cls,
        embeddings: np.ndarray,
        labels: np.ndarray,
        ids: Optional[np.ndarray] = None,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
        normalize: bool = True,
    ) -> "GalleryIndex":
        """Build the index from extracted embeddings (the ``extract``
        subcommand's output pair).  ``normalize=False`` trusts the rows
        are already unit-norm (extract output is); cosine similarity in
        the engine assumes unit rows either way."""
        emb = np.asarray(embeddings, np.float32)
        lab = np.asarray(labels, np.int32).reshape(-1)
        if emb.ndim != 2 or emb.shape[0] != lab.shape[0]:
            raise ValueError(
                f"embeddings {emb.shape} / labels {lab.shape} mismatch"
            )
        if emb.shape[0] == 0:
            raise ValueError("cannot build an empty gallery")
        if normalize:
            emb = l2_normalize_rows(emb)
        if ids is None:
            ids = np.arange(emb.shape[0], dtype=np.int64)
        else:
            ids = np.asarray(ids, np.int64).reshape(-1)
            if ids.shape[0] != emb.shape[0]:
                raise ValueError(
                    f"ids {ids.shape} / embeddings {emb.shape} mismatch"
                )
        import time

        idx = cls(
            emb=None, labels=None, valid=None, ids=ids,  # type: ignore
            size=int(emb.shape[0]), mesh=mesh, axis=axis,
            created=time.time(),
            _host_emb=emb, _host_labels=lab,
        )
        idx._place()
        return idx

    def _place(self) -> None:
        """Pad the host master copy to the mesh multiple and place it
        sharded (rows over the mesh axis) / on the default device."""
        n = self._host_emb.shape[0]
        g = self.mesh.size if self.mesh is not None else 1
        pad = (-n) % g
        emb = self._host_emb
        lab = self._host_labels
        valid = np.ones(n, bool)
        if pad:
            emb = np.concatenate(
                [emb, np.zeros((pad, emb.shape[1]), np.float32)]
            )
            lab = np.concatenate([lab, np.zeros(pad, np.int32)])
            valid = np.concatenate([valid, np.zeros(pad, bool)])
        if self.mesh is not None:
            # Placement via the declarative partition table
            # (parallel.partition.gallery_rules) instead of hand-placed
            # NamedShardings: rows shard over the mesh axis, and any
            # NEW gallery array must match a rule or fail loudly —
            # never silently replicate a pod-scale array.
            from npairloss_tpu.parallel.partition import (
                gallery_rules,
                match_partition_shardings,
                place_tree,
            )

            tree = {"emb": emb, "labels": lab, "valid": valid}
            placed = place_tree(
                tree,
                match_partition_shardings(
                    gallery_rules(self.axis), tree, self.mesh),
            )
            self.emb = placed["emb"]
            self.labels = placed["labels"]
            self.valid = placed["valid"]
        else:
            self.emb = jax.device_put(jnp.asarray(emb))
            self.labels = jax.device_put(jnp.asarray(lab))
            self.valid = jax.device_put(jnp.asarray(valid))
        self.size = n

    @property
    def padded_size(self) -> int:
        return int(self.emb.shape[0])

    @property
    def dim(self) -> int:
        return int(self.emb.shape[1])

    def _validate_added_rows(
        self,
        embeddings: np.ndarray,
        labels: np.ndarray,
        ids: Optional[np.ndarray],
        normalize: bool,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Coerce/validate an ``add()`` payload against this gallery
        (shared with the IVF subclass, whose add must also re-assign
        the rows into clusters before re-placing)."""
        emb = np.asarray(embeddings, np.float32)
        lab = np.asarray(labels, np.int32).reshape(-1)
        if emb.ndim != 2 or emb.shape[1] != self._host_emb.shape[1]:
            raise ValueError(
                f"added embeddings {emb.shape} do not match gallery dim "
                f"{self._host_emb.shape[1]}"
            )
        if emb.shape[0] != lab.shape[0]:
            raise ValueError(
                f"embeddings {emb.shape} / labels {lab.shape} mismatch"
            )
        if normalize:
            emb = l2_normalize_rows(emb)
        if ids is None:
            start = int(self.ids.max()) + 1 if self.ids.size else 0
            ids = np.arange(start, start + emb.shape[0], dtype=np.int64)
        else:
            ids = np.asarray(ids, np.int64).reshape(-1)
            if ids.shape[0] != emb.shape[0]:
                raise ValueError(
                    f"ids {ids.shape} / embeddings {emb.shape} mismatch"
                )
        return emb, lab, ids

    def add(
        self,
        embeddings: np.ndarray,
        labels: np.ndarray,
        ids: Optional[np.ndarray] = None,
        normalize: bool = True,
    ) -> int:
        """Incrementally append rows and re-place the gallery.

        O(N) host work + one fresh placement — the padded/sharded layout
        must be rebuilt, so adds are for index-refresh cadence (seconds),
        not the per-query hot path.  Returns the new ``size``.  The
        engine notices the new placement on its next dispatch; a changed
        PADDED size is a new program signature (one recompile, counted).
        """
        emb, lab, ids = self._validate_added_rows(
            embeddings, labels, ids, normalize)
        import time

        self._host_emb = np.concatenate([self._host_emb, emb])
        self._host_labels = np.concatenate([self._host_labels, lab])
        self.ids = np.concatenate([self.ids, ids])
        self._place()
        # Incremental content refresh IS a freshness event: the gallery
        # now reflects this wall time, and index_age_s restarts from it.
        self.created = time.time()
        return self.size

    # -- persistence (resilience.snapshot commit path) --------------------

    def _tree(self):
        return {
            "emb": self._host_emb,
            "labels": self._host_labels,
            "ids": self.ids,
        }

    def save(self, path: str) -> str:
        """Commit the index atomically at ``path``: arrays into a
        ``.tmp-`` dir, CRC manifest fsync'd inside, ``os.replace`` as
        the commit point.  A crash mid-save leaves only tmp debris the
        load scan never matches.  Overwriting an existing index (the
        ``--add-to`` re-commit) renames the old dir ASIDE first and
        deletes it only after the new commit + fsync — the committed
        data is never destroyed before its replacement is in place, so
        the worst crash leaves the old arrays intact under a
        ``.tmp-…-prev`` name instead of an empty prefix."""
        final = os.path.abspath(path)
        parent = os.path.dirname(final)
        os.makedirs(parent, exist_ok=True)
        nonce = f"{os.getpid()}-{os.urandom(2).hex()}"
        tmp = f"{final}{TMP_MARKER}{nonce}"
        os.makedirs(tmp)
        tree = self._tree()
        for name in self.ARRAY_NAMES:
            np.save(os.path.join(tmp, name + ".npy"), tree[name])
        write_manifest(
            tmp, 0, state_checksums(tree),
            extra={"kind": self.KIND, "size": self.size,
                   "dim": self.dim, **self._manifest_extra()},
        )
        old = None
        if os.path.isdir(final):
            old = f"{final}{TMP_MARKER}{nonce}-prev"
            os.replace(final, old)
        failpoints.fire("index.commit.crash")
        os.replace(tmp, final)
        _fsync_dir(parent)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        # Reclaim debris from earlier crashed saves of this path (their
        # nonce differs, so the rename-aside above never matches them).
        # Single-writer, same as resilience.snapshot's stale-tmp GC.
        stale_mark = os.path.basename(final) + TMP_MARKER
        for name in os.listdir(parent):
            if name.startswith(stale_mark):
                shutil.rmtree(os.path.join(parent, name),
                              ignore_errors=True)
        log.info("gallery index -> %s (%d rows, dim %d)",
                 final, self.size, self.dim)
        return final

    def _manifest_extra(self) -> dict:
        """Extra manifest keys this class commits; subclasses (IVF:
        cluster count) must MERGE ``super()._manifest_extra()`` so the
        ingest watermark survives every kind.  The key is omitted at 0
        to keep pre-WAL manifests byte-identical."""
        out: dict = {}
        if self.ingest_watermark:
            out["ingest_watermark"] = int(self.ingest_watermark)
        return out

    @classmethod
    def load(
        cls,
        path: str,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
    ) -> "GalleryIndex":
        """Restore a committed index, checksum-verified against its
        manifest; raises :class:`SnapshotValidationError` on a torn or
        corrupt index instead of serving garbage answers."""
        manifest = validate_snapshot(os.path.abspath(path))
        if manifest.get("kind") != cls.KIND:
            raise SnapshotValidationError(
                f"{path} is not a {cls.KIND} "
                f"(kind={manifest.get('kind')!r})"
            )
        tree = {}
        for name in cls.ARRAY_NAMES:
            p = os.path.join(path, name + ".npy")
            try:
                tree[name] = np.load(p)
            except (OSError, ValueError) as e:
                raise SnapshotValidationError(
                    f"unreadable index array {p}: {e}"
                ) from e
        verify_restored(tree, manifest)
        idx = cls._from_tree(tree, manifest, mesh, axis)
        # One restore site for every kind: subclasses override
        # _from_tree but the watermark contract is the base class's.
        wm = manifest.get("ingest_watermark")
        idx.ingest_watermark = int(wm) if isinstance(wm, int) else 0
        idx._place()
        return idx

    @classmethod
    def _from_tree(cls, tree, manifest, mesh, axis) -> "GalleryIndex":
        """Instance from verified arrays (pre-``_place``); subclasses
        extend with their extra arrays."""
        created = manifest.get("created")
        return cls(
            emb=None, labels=None, valid=None,  # type: ignore
            ids=np.asarray(tree["ids"], np.int64),
            size=int(tree["emb"].shape[0]), mesh=mesh, axis=axis,
            created=(float(created)
                     if isinstance(created, (int, float)) else None),
            _host_emb=np.asarray(tree["emb"], np.float32),
            _host_labels=np.asarray(tree["labels"], np.int32),
        )


def list_indexes(prefix: str) -> List[Tuple[str, str]]:
    """Committed index candidates ``<prefix>*.gidx`` as (name, path),
    sorted ascending by name; tmp dirs never match."""
    prefix = os.path.abspath(prefix)
    parent, base = os.path.dirname(prefix), os.path.basename(prefix)
    out: List[Tuple[str, str]] = []
    try:
        entries = os.listdir(parent)
    except OSError:
        return out
    for name in entries:
        if (name.startswith(base) and name.endswith(INDEX_SUFFIX)
                and TMP_MARKER not in name):
            path = os.path.join(parent, name)
            if os.path.isdir(path):
                out.append((name, path))
    out.sort()
    return out


def load_index(
    path: str,
    mesh: Optional[Mesh] = None,
    axis: str = "dp",
) -> GalleryIndex:
    """Load a committed index of ANY registered kind: the manifest's
    ``kind`` picks the class (gallery-index -> :class:`GalleryIndex`;
    ivf-index -> ``serve.ivf.IVFIndex``), so a serving prefix can mix
    flat and clustered commits and a consumer need not know which it
    got."""
    kind = read_manifest(path).get("kind")
    cls = _KIND_REGISTRY.get(kind, GalleryIndex if kind == INDEX_KIND
                             else None)
    if cls is None and kind == "ivf-index":
        # Importing serve.ivf registers the class; lazy to avoid a
        # module cycle (ivf imports this module).
        import npairloss_tpu.serve.ivf  # noqa: F401

        cls = _KIND_REGISTRY.get(kind)
    if cls is None:
        raise SnapshotValidationError(
            f"{path}: unknown index kind {kind!r}")
    return cls.load(path, mesh=mesh, axis=axis)


def load_newest(
    prefix: str,
    mesh: Optional[Mesh] = None,
    axis: str = "dp",
) -> Optional[Tuple[str, GalleryIndex]]:
    """Scan ``<prefix>*.gidx`` newest-first (by name — the build cadence
    names indexes sortably) and load the first one that validates,
    skipping torn/corrupt candidates with a logged reason — the serving
    twin of ``Solver.restore_auto``.  Returns (path, index) or None;
    the index may be any registered kind (see :func:`load_index`)."""
    for _, path in reversed(list_indexes(prefix)):
        try:
            return path, load_index(path, mesh=mesh, axis=axis)
        except Exception as e:  # noqa: BLE001 — skip, try the next
            log.warning("index load: skipping %s: %s", path, e)
    return None


def index_info(path: str) -> dict:
    """Manifest summary for tooling (no array loads)."""
    m = read_manifest(path)
    return {
        "path": os.path.abspath(path),
        "kind": m.get("kind"),
        "size": m.get("size"),
        "dim": m.get("dim"),
        "created": m.get("created"),
        "ingest_watermark": int(m.get("ingest_watermark", 0) or 0),
    }
