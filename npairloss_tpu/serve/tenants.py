"""Multi-tenant serving: one tier, many galleries (ROADMAP item 5).

"Millions of users" is never one gallery: this module turns the
single-gallery serving tier into a tenant-keyed service — per-tenant
``GalleryIndex``/IVF instance, freshness, WAL-backed ingest watermark,
quota, admission, and shadow scoring — behind ONE HTTP front end, ONE
replica tier, and ONE compiled-program family per geometry (the
Gemma-serving discipline from PAPERS.md: per-workload *operating*
targets, not one aggregate peak).

The pieces:

  * :data:`TENANTS_SCHEMA` + :func:`validate_tenants_manifest` — the
    versioned ``npairloss-tenants-v1`` manifest contract (tenant id ->
    index prefix, index kind, probe impl, quota, recall floor,
    admission params), validated jax-free so ``bench_check --tenants``
    can refuse a tampered manifest without the package.
  * :class:`TenantSpec` / :class:`TenantRegistry` — the parsed,
    loudly-validated registry.
  * :class:`TenantEntry` — one tenant's runtime slot inside
    :class:`~npairloss_tpu.serve.server.RetrievalServer` (engines,
    freshness, quota, admission, shadow, ingest, counters).
  * :class:`QuotaGate` — a token-bucket qps quota; a shed is a
    fast-reject counted per tenant, and the
    ``serve_quota_exhausted{tenant=...}`` gauge feeds the tenant's
    quota SLO so the shed is also a tenant-scoped alert.
  * :class:`TenantIngest` — the PR-18 durable-ingest discipline
    (WAL append -> fsync barrier -> apply -> ack; checkpoint
    publication + GC) applied per tenant.
  * :class:`ProgramCache` — the compile-sharing contract: bucketed
    shapes make programs tenant-agnostic, so the same (B, cap, D)
    program serves every tenant at that geometry and tenant count must
    not multiply compiles (asserted by tests/test_tenants.py).
  * :class:`TenantSwapper` — the PR-13 hot-swap discipline applied per
    entry: build + warm the new tier OFF the serving path, publish via
    ``swap_tenant_engines``; other tenants' answers never stop.
  * :func:`tenant_slo_specs` — per-tenant SLOs over the LABELED metric
    streams (``serve_p99_ms{tenant="a"}``), named ``tenant_*@<id>`` so
    one AlertEngine fires tenant-scoped alerts.

Module level is stdlib-only (the bench_check file-path-load contract);
everything that touches the engine/index/jax imports lazily.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

log = logging.getLogger("npairloss_tpu.serve")

TENANTS_SCHEMA = "npairloss-tenants-v1"

# Serving postures a tenant can request — the dict is the registry the
# jax-free choices tuple below is pinned to (analysis/vocab.py
# CHOICE_PINS), mirroring the cli.py _PRECISION_CHOICES idiom.
INDEX_KINDS = {
    "flat": "exact scan over the full gallery (the recall oracle)",
    "ivf": "clustered probe-top-C scan (serve/ivf.py)",
}
_INDEX_KIND_CHOICES = ("flat", "ivf")
# The jax-free restatement of ops.pallas_ivf.PROBE_IMPLS' keys, pinned
# by the same CHOICE_PINS entry that pins cli._PROBE_IMPL_CHOICES.
_PROBE_IMPL_CHOICES = ("scan", "fused", "auto")

# Tenant ids ride Prometheus label values, SLO names, WAL subdirs, and
# checkpoint prefixes — keep them filesystem- and label-safe.
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_-]*$")

# Per-tenant SLO names are ``tenant_<what>@<tenant_id>`` — the suffix
# is how one shared AlertEngine scopes an alert to its tenant.
TENANT_SLO_SEP = "@"

_SPEC_KEYS = frozenset((
    "tenant_id", "index_prefix", "index_kind", "probe_impl",
    "quota_qps", "quota_burst_s", "recall_floor", "recall_k",
    "p99_ms", "admission", "probe_every",
))


def tenant_of_slo(slo_name: str) -> Optional[str]:
    """The tenant id a ``tenant_*@<id>`` SLO/alert is scoped to, or
    None for a tier-wide name — the verdict/bench side of the naming
    contract."""
    if TENANT_SLO_SEP not in slo_name:
        return None
    return slo_name.split(TENANT_SLO_SEP, 1)[1]


def validate_tenants_manifest(manifest: Any) -> List[str]:
    """Problems with a ``npairloss-tenants-v1`` manifest (empty list =
    valid).  Jax-free and total: every problem is reported, not just
    the first, so a tampered manifest is refused with evidence."""
    if not isinstance(manifest, dict):
        return [f"manifest must be an object, got "
                f"{type(manifest).__name__}"]
    problems: List[str] = []
    schema = manifest.get("schema")
    if schema != TENANTS_SCHEMA:
        problems.append(
            f"schema is {schema!r}, expected {TENANTS_SCHEMA!r}")
    tenants = manifest.get("tenants")
    if not isinstance(tenants, list) or not tenants:
        problems.append("manifest needs a non-empty 'tenants' list")
        return problems
    seen: set = set()
    for i, t in enumerate(tenants):
        where = f"tenants[{i}]"
        if not isinstance(t, dict):
            problems.append(f"{where}: must be an object")
            continue
        tid = t.get("tenant_id")
        if not isinstance(tid, str) or not _ID_RE.match(tid):
            problems.append(
                f"{where}: tenant_id must match {_ID_RE.pattern}, "
                f"got {tid!r}")
        elif tid in seen:
            problems.append(f"{where}: duplicate tenant_id {tid!r}")
        else:
            seen.add(tid)
        prefix = t.get("index_prefix")
        if not isinstance(prefix, str) or not prefix:
            problems.append(
                f"{where}: index_prefix must be a non-empty string")
        kind = t.get("index_kind", "flat")
        if kind not in _INDEX_KIND_CHOICES:
            problems.append(
                f"{where}: index_kind {kind!r} not in "
                f"{list(_INDEX_KIND_CHOICES)}")
        impl = t.get("probe_impl")
        if impl is not None and impl not in _PROBE_IMPL_CHOICES:
            problems.append(
                f"{where}: probe_impl {impl!r} not in "
                f"{list(_PROBE_IMPL_CHOICES)}")
        qps = t.get("quota_qps", 0.0)
        if not isinstance(qps, (int, float)) or qps < 0:
            problems.append(
                f"{where}: quota_qps must be a number >= 0, got {qps!r}")
        burst = t.get("quota_burst_s", 2.0)
        if not isinstance(burst, (int, float)) or burst <= 0:
            problems.append(
                f"{where}: quota_burst_s must be > 0, got {burst!r}")
        floor = t.get("recall_floor")
        if floor is not None and not (
                isinstance(floor, (int, float)) and 0.0 <= floor <= 1.0):
            problems.append(
                f"{where}: recall_floor must be in [0, 1], got {floor!r}")
        rk = t.get("recall_k", 10)
        if not isinstance(rk, int) or rk < 1:
            problems.append(
                f"{where}: recall_k must be an int >= 1, got {rk!r}")
        p99 = t.get("p99_ms")
        if p99 is not None and not (
                isinstance(p99, (int, float)) and p99 > 0):
            problems.append(
                f"{where}: p99_ms must be > 0, got {p99!r}")
        if not isinstance(t.get("admission", True), bool):
            problems.append(f"{where}: admission must be a boolean")
        pe = t.get("probe_every", 8)
        if not isinstance(pe, int) or pe < 1:
            problems.append(
                f"{where}: probe_every must be an int >= 1, got {pe!r}")
        extra = sorted(set(t) - _SPEC_KEYS)
        if extra:
            problems.append(
                f"{where}: unknown key(s) {extra} — the "
                f"{TENANTS_SCHEMA} contract has no such fields")
    return problems


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's declared serving contract (one manifest entry).

    ``quota_qps`` 0 = unlimited; ``probe_impl`` None defers to the
    tier's engine config; ``recall_floor``/``p99_ms`` None = no SLO of
    that kind for this tenant; ``admission`` arms a per-tenant
    burn-driven controller over the tenant's own SLOs."""

    tenant_id: str
    index_prefix: str
    index_kind: str = "flat"
    probe_impl: Optional[str] = None
    quota_qps: float = 0.0
    quota_burst_s: float = 2.0
    recall_floor: Optional[float] = None
    recall_k: int = 10
    p99_ms: Optional[float] = None
    admission: bool = True
    probe_every: int = 8

    def __post_init__(self):
        problems = validate_tenants_manifest({
            "schema": TENANTS_SCHEMA,
            "tenants": [dataclasses.asdict(self)],
        })
        if problems:
            raise ValueError(
                f"invalid TenantSpec: {'; '.join(problems)}")

    @classmethod
    def from_dict(cls, entry: Dict[str, Any]) -> "TenantSpec":
        return cls(**{k: v for k, v in entry.items() if k in _SPEC_KEYS})


class TenantRegistry:
    """The parsed ``npairloss-tenants-v1`` manifest: an ordered,
    loudly-validated map of tenant id -> :class:`TenantSpec`."""

    def __init__(self, specs):
        specs = list(specs)
        if not specs:
            raise ValueError("TenantRegistry needs >= 1 tenant")
        self.specs: Dict[str, TenantSpec] = {}
        for spec in specs:
            if spec.tenant_id in self.specs:
                raise ValueError(
                    f"duplicate tenant_id {spec.tenant_id!r}")
            self.specs[spec.tenant_id] = spec

    @classmethod
    def from_manifest(cls, manifest: Any) -> "TenantRegistry":
        problems = validate_tenants_manifest(manifest)
        if problems:
            raise ValueError(
                "invalid tenants manifest: " + "; ".join(problems))
        return cls(TenantSpec.from_dict(t) for t in manifest["tenants"])

    @classmethod
    def load(cls, path: str) -> "TenantRegistry":
        try:
            with open(path, encoding="utf-8") as f:
                manifest = json.load(f)
        except ValueError as e:
            raise ValueError(f"tenants manifest {path}: bad JSON: {e}")
        return cls.from_manifest(manifest)

    def ids(self) -> List[str]:
        return list(self.specs)

    def get(self, tenant_id: str) -> TenantSpec:
        if tenant_id not in self.specs:
            raise KeyError(
                f"unknown tenant {tenant_id!r} (registered: "
                f"{self.ids()})")
        return self.specs[tenant_id]

    def __iter__(self) -> Iterator[TenantSpec]:
        return iter(self.specs.values())

    def __contains__(self, tenant_id) -> bool:
        return tenant_id in self.specs

    def __len__(self) -> int:
        return len(self.specs)


class QuotaGate:
    """A token-bucket qps quota (capacity ``qps * burst_s``, refill
    ``qps``/s).  ``admit()`` is a submit-path fast path: one lock, no
    I/O.  With a (tenant-scoped) registry attached, the
    ``serve_quota_exhausted`` gauge flips 1/0 around sheds — the
    sample stream the tenant's quota SLO burns on — and every shed
    increments the ``serve_quota_shed`` counter.  ``qps`` 0 disarms
    the gate (always admits, publishes nothing)."""

    def __init__(self, qps: float, burst_s: float = 2.0,
                 registry=None, clock=time.monotonic):
        if qps < 0:
            raise ValueError(f"quota qps must be >= 0, got {qps}")
        if burst_s <= 0:
            raise ValueError(f"quota burst_s must be > 0, got {burst_s}")
        self.qps = float(qps)
        self.burst_s = float(burst_s)
        self.capacity = max(self.qps * self.burst_s, 1.0)
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.capacity  # guarded-by: _lock
        self._last = clock()  # guarded-by: _lock
        self.sheds = 0  # guarded-by: _lock

    def admit(self) -> bool:
        if self.qps <= 0:
            return True
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.capacity,
                self._tokens + (now - self._last) * self.qps)
            self._last = now
            ok = self._tokens >= 1.0
            if ok:
                self._tokens -= 1.0
            else:
                self.sheds += 1
        if self.registry is not None:
            self.registry.set("serve_quota_exhausted",
                              0.0 if ok else 1.0)
            if not ok:
                self.registry.inc("serve_quota_shed")
        return ok

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "qps": self.qps,
                "burst_s": self.burst_s,
                "sheds": self.sheds,
                "tokens": round(self._tokens, 2),
            }


class TenantIngest:
    """The PR-18 durable-ingest discipline, one instance per tenant:
    WAL append + group-commit fsync barrier BEFORE the ack, apply under
    ``lock``, checkpoint publication + WAL GC at the same watermark
    read.  ``lock`` also serializes this tenant's hot-swap flip against
    its ingest applies (the server's ingest-lock-outside-serve-lock
    order, per tenant)."""

    def __init__(self, wal, apply_fn, *, checkpoint_fn=None,
                 checkpoint_every: int = 0, watermark: int = 0,
                 checkpoint_watermark: int = 0):
        self.wal = wal
        self.apply_fn = apply_fn
        self.checkpoint_fn = checkpoint_fn
        self.checkpoint_every = int(checkpoint_every)
        self.lock = threading.Lock()
        self.watermark = int(watermark)  # guarded-by: lock
        self.ckpt_watermark = int(checkpoint_watermark)  # guarded-by: lock
        self.since_ckpt = 0  # guarded-by: lock
        self.batches = 0  # guarded-by: lock
        self.vectors = 0  # guarded-by: lock
        self.errors = 0  # guarded-by: lock

    def note_error(self) -> None:
        with self.lock:
            self.errors += 1

    def commit(self, body: Dict[str, Any]) -> int:
        """Durably append one encoded ingest body, apply it, advance
        the watermark; returns the WAL seq the ack must carry.  The
        ack never precedes the fsync covering the record — the
        durability contract, unchanged from the single-tenant path."""
        seq = self.wal.append(body)
        self.wal.wait_durable(seq)
        body["seq"] = seq
        with self.lock:
            self.apply_fn(body)
            self.watermark = seq
            self.since_ckpt += 1
            self.batches += 1
            self.vectors += len(body["ids"])
        return seq

    def maybe_checkpoint(self) -> None:
        if self.checkpoint_fn is None or self.checkpoint_every <= 0:
            return
        with self.lock:
            due = self.since_ckpt >= self.checkpoint_every
        if due:
            self.checkpoint_now()

    def checkpoint_now(self) -> Optional[str]:
        if self.checkpoint_fn is None:
            return None
        with self.lock:
            wm = self.watermark
            if wm <= self.ckpt_watermark:
                return None
            try:
                path = self.checkpoint_fn(wm)
            except Exception as e:  # noqa: BLE001 — a failed publish is not data loss
                log.error("tenant ingest checkpoint at watermark %d "
                          "failed: %s — WAL retains the records", wm, e)
                return None
            self.ckpt_watermark = wm
            self.since_ckpt = 0
        if path is not None:
            try:
                self.wal.gc(wm)
            except Exception as e:  # noqa: BLE001 — GC is space, not safety
                log.error("tenant wal GC at watermark %d failed: %s",
                          wm, e)
        return path

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            out: Dict[str, Any] = {
                "batches": self.batches,
                "vectors": self.vectors,
                "errors": self.errors,
                "watermark": self.watermark,
                "checkpoint_watermark": self.ckpt_watermark,
            }
        try:
            out["wal"] = self.wal.stats() if self.wal is not None else {}
        except Exception as e:  # noqa: BLE001 — stats must not fail health
            out["wal"] = {"error": str(e)}
        return out


def _pct(values: List[float], q: float) -> float:
    """Nearest-rank percentile over an unsorted list (stdlib-only —
    this module must not import numpy)."""
    if not values:
        return 0.0
    vals = sorted(values)
    rank = max(int(round(q / 100.0 * len(vals) + 0.5)) - 1, 0)
    return float(vals[min(rank, len(vals) - 1)])


class TenantEntry:
    """One tenant's runtime slot inside the server's tenant map.  A
    plain container: the server is the only mutator, and the query/
    answer counters plus the ``engines``/``freshness`` pointers are
    guarded by the server's ``_lock`` (swap flips additionally hold
    ``ingest.lock`` — the per-tenant ingest-outside-serve order)."""

    def __init__(self, spec: TenantSpec, engines, freshness=None,
                 quota: Optional[QuotaGate] = None, admission=None,
                 shadow=None, ingest: Optional[TenantIngest] = None,
                 latency_window: int = 1024):
        self.spec = spec
        self.tenant_id = spec.tenant_id
        self.engines = list(engines)  # under the owning server's _lock
        if not self.engines:
            raise ValueError(
                f"tenant {spec.tenant_id!r} needs >= 1 engine")
        self.freshness = freshness  # under the owning server's _lock
        self.quota = quota
        self.admission = admission
        self.shadow = shadow
        self.ingest = ingest
        self.queries = 0  # under the owning server's _lock
        self.answered = 0  # under the owning server's _lock
        self.errors = 0  # under the owning server's _lock
        self.rejected = 0  # under the owning server's _lock
        self.swaps = 0  # under the owning server's _lock
        self.lat: collections.deque = collections.deque(
            maxlen=max(latency_window, 1))  # under the owning server's _lock
        self.window_lat: List[float] = []  # under the owning server's _lock

    def take_window(self) -> List[float]:
        """Swap out this window's latency samples (caller holds the
        server lock) — the per-tenant twin of ``_emit_window``'s
        snapshot."""
        lat, self.window_lat = self.window_lat, []
        return lat

    def percentiles(self) -> Dict[str, float]:
        lat = list(self.lat)
        return {"p50_ms": round(_pct(lat, 50), 3),
                "p99_ms": round(_pct(lat, 99), 3)}

    def stats_block(self) -> Dict[str, Any]:
        """This tenant's summary/healthz block: counters + freshness +
        every armed feature's evidence, each sub-block absent when the
        feature is off (the freshness-JSON contract, per tenant)."""
        pi = getattr(self.engines[0], "probe_impl", None)
        return {
            "queries": self.queries,
            "answered": self.answered,
            "errors": self.errors,
            "rejected": self.rejected,
            "index_kind": self.spec.index_kind,
            **({"probe_impl": pi} if pi is not None else {}),
            **self.percentiles(),
            **(self.freshness.identity()
               if self.freshness is not None else {}),
            **(self.freshness.ages()
               if self.freshness is not None else {}),
            **({"quota": self.quota.stats()}
               if self.quota is not None else {}),
            **({"shed": self.admission.sheds,
                "shedding": (self.admission.shedding
                             or self.admission.forced)}
               if self.admission is not None else {}),
            **({"hot_swaps": self.swaps} if self.swaps else {}),
            **({"ingest": self.ingest.stats()}
               if self.ingest is not None else {}),
            **({"quality": self.shadow.stats()}
               if self.shadow is not None else {}),
        }


class TenantTelemetry:
    """A telemetry facade that stamps ``tenant`` into every metrics
    row it logs (spans/instants and everything else pass through) —
    how a per-tenant ShadowScorer's quality rows reach the shared
    RegistrySink already labeled, so its recall gauges land as
    ``serve_recall_at_K{tenant=...}``."""

    def __init__(self, base, tenant_id: str):
        self._base = base
        self.tenant = tenant_id

    def log(self, phase: str, step: int, row: Dict[str, Any]) -> None:
        self._base.log(phase, step, {**row, "tenant": self.tenant})

    def __getattr__(self, name):
        return getattr(self._base, name)


def tenant_slo_specs(spec: TenantSpec) -> list:
    """This tenant's SLOs, targeting its LABELED metric streams.  The
    ``tenant_*@<id>`` names make every alert the shared AlertEngine
    fires tenant-scoped; the metrics are the labeled registry keys the
    per-tenant window rows / quota gate / shadow scorer publish, read
    by the unchanged evaluator (labels are just registry key
    spelling)."""
    from npairloss_tpu.obs.live.registry import labeled_name
    from npairloss_tpu.obs.live.slo import SLOSpec

    lab = {"tenant": spec.tenant_id}
    tid = spec.tenant_id
    out = []
    if spec.p99_ms is not None:
        out.append(SLOSpec(
            name=f"tenant_p99{TENANT_SLO_SEP}{tid}",
            metric=labeled_name("serve_p99_ms", lab), op="<=",
            target=float(spec.p99_ms), window_s=30.0,
            burn_threshold=0.5, min_samples=2, severity="critical",
            description=f"tenant {tid}: p99 latency over its own "
                        "serve windows",
        ))
    if spec.quota_qps > 0:
        out.append(SLOSpec(
            name=f"tenant_quota{TENANT_SLO_SEP}{tid}",
            metric=labeled_name("serve_quota_exhausted", lab), op="<=",
            target=0.0, window_s=30.0, burn_threshold=0.5,
            min_samples=1, severity="warning",
            description=f"tenant {tid}: quota token bucket exhausted "
                        "(submits are being quota-shed)",
        ))
    if spec.recall_floor is not None:
        out.append(SLOSpec(
            name=f"tenant_recall{TENANT_SLO_SEP}{tid}",
            metric=labeled_name(f"serve_recall_at_{spec.recall_k}", lab),
            op=">=", target=float(spec.recall_floor), window_s=120.0,
            burn_threshold=0.5, min_samples=1, severity="critical",
            description=f"tenant {tid}: shadow-estimated "
                        f"recall@{spec.recall_k} vs the exact oracle",
        ))
    return out


class ProgramCache:
    """The cross-tenant compile-sharing contract: bucketed shapes make
    the jitted top-k/encode programs tenant-agnostic (index arrays are
    dispatch ARGUMENTS), so engines for the same (EngineConfig, index
    kind, mesh, model) share one program family + signature set via
    ``QueryEngine.share_programs_with`` — tenant count must not
    multiply compiles.  The NEWEST engine per key becomes the share
    source, so a hot-swapped-out gallery is never pinned by the
    cache."""

    def __init__(self):
        self._lock = threading.Lock()
        self._primaries: Dict[Any, Any] = {}  # guarded-by: _lock

    @staticmethod
    def _key(index, cfg, model):
        mesh = getattr(index, "mesh", None)
        return (cfg, getattr(index, "KIND", type(index).__name__),
                id(mesh) if mesh is not None else None,
                getattr(index, "axis", None),
                id(model) if model is not None else None)

    def engine_for(self, index, cfg, model=None, state=None,
                   telemetry=None):
        """An engine for ``index`` that shares programs with every
        prior engine at the same geometry family (fresh build for a
        new family)."""
        from npairloss_tpu.serve.engine import QueryEngine

        key = self._key(index, cfg, model)
        with self._lock:
            primary = self._primaries.get(key)
        eng = QueryEngine(index, cfg, model=model, state=state,
                          telemetry=telemetry,
                          share_programs_with=primary)
        with self._lock:
            self._primaries[key] = eng
        return eng

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"families": len(self._primaries)}


def reconcile_index_kind(index, kind: str, clusters=None, mesh=None):
    """cmd_serve's ``--index-kind`` reconciliation, applied per tenant
    (docs/SERVING.md §Approximate index): the committed artifact never
    dictates the serving posture — a flat commit can serve through the
    IVF probe path and an IVF commit can serve flat.  Applied to every
    swapped-in index too, so a flat commit never demotes an
    IVF-serving tenant at its first swap."""
    from npairloss_tpu.serve.index import GalleryIndex
    from npairloss_tpu.serve.ivf import IVFIndex

    if kind == "ivf" and not isinstance(index, IVFIndex):
        return IVFIndex.from_gallery(index, clusters=clusters)
    if kind == "flat" and isinstance(index, IVFIndex):
        return GalleryIndex.build(
            index._host_emb, index._host_labels, ids=index.ids,
            mesh=mesh, normalize=False)
    return index


class TenantSwapper:
    """Per-tenant snapshot watch: the PR-13 hot-swap discipline applied
    per entry.  ``swap_one(tid)`` scans the tenant's index prefix for a
    STRICTLY newer commit, reconciles its kind, builds + warms a fresh
    engine set OFF the serving path (through the shared
    :class:`ProgramCache`, so an unchanged geometry costs zero
    compiles), then publishes via
    ``RetrievalServer.swap_tenant_engines`` — every other tenant's
    engines are untouched and no in-flight query drops.  ``sweep()``
    visits every tenant; ``start()`` runs sweeps on a daemon thread."""

    def __init__(self, server, programs: Optional[ProgramCache] = None,
                 mesh=None, telemetry=None, ivf_clusters=None):
        if not getattr(server, "tenants", None):
            raise ValueError(
                "TenantSwapper needs a server with an installed "
                "tenant map (RetrievalServer.enable_tenants)")
        self.server = server
        self.programs = programs if programs is not None else ProgramCache()
        self.mesh = mesh
        self.telemetry = telemetry
        self.ivf_clusters = ivf_clusters
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def swap_one(self, tenant_id: str) -> Dict[str, Any]:
        """Swap ONE tenant to its newest committed index; raises
        ``hotswap.NothingNewerError`` when nothing newer exists (an
        honest no-op for the sweep, an honest FAILED attempt for a
        remediation caller)."""
        from npairloss_tpu.serve.engine import QueryEngine
        from npairloss_tpu.serve.hotswap import (
            NothingNewerError,
            SnapshotSwapper,
        )
        from npairloss_tpu.serve.index import list_indexes, load_newest
        from npairloss_tpu.serve.server import Freshness

        entry = self.server.tenants[tenant_id]
        spec = entry.spec
        fresh = entry.freshness
        # Cheap directory-listing pre-check before any array load: the
        # watcher sweeps every few seconds across EVERY tenant, and
        # "nothing new" must cost a listdir, not an index load.
        cands = list_indexes(spec.index_prefix)
        current = fresh.index_path if fresh else None
        if not cands or not SnapshotSwapper._index_is_newer(
                cands[-1][1], current):
            raise NothingNewerError(
                f"tenant {tenant_id!r}: no index commit newer than "
                "the served one")
        found = load_newest(spec.index_prefix, mesh=self.mesh)
        if found is None or not SnapshotSwapper._index_is_newer(
                found[0], fresh.index_path if fresh else None):
            raise NothingNewerError(
                f"tenant {tenant_id!r}: no index commit newer than "
                "the served one")
        path, index = found
        index = reconcile_index_kind(
            index, spec.index_kind, clusters=self.ivf_clusters,
            mesh=self.mesh)
        old = entry.engines[0]
        primary = self.programs.engine_for(
            index, old.cfg, model=old.model, state=old.state,
            telemetry=self.telemetry)
        warmup_s = primary.warmup(
            self.server.input_shape if old.model is not None else None)
        engines = [primary] + [
            QueryEngine(index, old.cfg, model=old.model,
                        state=old.state, telemetry=self.telemetry,
                        share_compiled_with=primary)
            for _ in range(len(entry.engines) - 1)
        ]
        for e in engines[1:]:
            e.warmed = True
        freshness = Freshness.collect(index=index, index_path=path)
        self.server.swap_tenant_engines(tenant_id, engines, freshness)
        detail: Dict[str, Any] = {
            "tenant": tenant_id,
            "swapped": ["index"],
            "warmup_s": round(warmup_s, 3),
            **freshness.identity(),
        }
        if self.telemetry is not None:
            self.telemetry.instant("serve/hot_swap", **{
                k: v for k, v in detail.items() if k != "swapped"})
        return detail

    def sweep(self) -> Dict[str, Dict[str, Any]]:
        """One pass over every tenant; returns {tenant_id: swap detail}
        for the tenants that swapped.  A tenant with nothing newer is
        skipped silently; any OTHER failure is logged and contained to
        its tenant — one broken prefix must not stall the sweep."""
        from npairloss_tpu.serve.hotswap import NothingNewerError

        out: Dict[str, Dict[str, Any]] = {}
        for tid in list(self.server.tenants):
            try:
                out[tid] = self.swap_one(tid)
            except NothingNewerError:
                continue
            except Exception as e:  # noqa: BLE001 — contain per tenant
                log.error("tenant %r hot-swap failed: %s", tid, e)
        return out

    def start(self, period_s: float = 2.0) -> "TenantSwapper":
        if self._thread is not None:
            raise RuntimeError("TenantSwapper already started")
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(period_s):
                self.sweep()

        self._thread = threading.Thread(
            target=_loop, name="tenant-swapper", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
