"""QueryEngine — the jitted online query path: encode -> topk answers.

One dispatch per micro-batch: (optionally) encode raw inputs through the
restored model trunk, ``ops.normalize`` the query rows, then a
block-streamed similarity matmul against the mesh-resident gallery with
``lax.top_k`` merged across gallery blocks and mesh shards.  The
math is the deployment protocol of ``ops/eval_retrieval.py`` — fp32
HIGHEST-precision cosine on the MXU — so served answers are exactly
consistent with the offline ``gallery_recall_at_k`` numbers (parity is
pinned by tests/test_serve.py).

Streaming + merge layout (docs/SERVING.md):

  * within a shard, gallery rows stream in fixed blocks through a
    ``lax.scan`` carrying the running (B, k) best scores/rows — the
    B x N similarity matrix is never materialized (the
    ``ops/eval_retrieval.py`` trick, applied to the gallery axis);
  * across shards, each mesh shard returns its local top-k with GLOBAL
    row numbers (shard offset via ``axis_index``); the (G, B, k)
    candidates reshape to (B, G*k) in ascending-shard order and one
    final ``top_k`` merges them.

Both merges preserve ``lax.top_k``'s lowest-index-wins tie-break:
candidates always concatenate in ascending global-row order, so the
streamed/sharded answer is bit-identical to a dense single-device
``top_k`` over the whole gallery.

Steady-state serving never compiles: :meth:`warmup` compiles and primes
every padding bucket with one dummy dispatch each (populating the
persistent compile cache when one is enabled — see
:meth:`QueryEngine.warmup` for why AOT ``lower().compile()`` would pay
each compile twice).  Every later compile is COUNTED
(``compiles_after_warmup``) via
the jit cache size, and ``NPAIRLOSS_SERVE_COMPILE_GUARD=strict`` turns
a post-warmup compile into an error — the serving twin of the pipeline
sync guard.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from npairloss_tpu.ops.normalize import l2_normalize
from npairloss_tpu.ops.pallas_ivf import (
    PROBE_IMPLS,
    fused_probe_topk,
    resolve_probe_impl,
)
from npairloss_tpu.parallel._compat import REP_CHECK_OFF, shard_map
from npairloss_tpu.resilience import failpoints
from npairloss_tpu.serve.index import GalleryIndex, l2_normalize_rows
from npairloss_tpu.serve.ivf import SCORINGS, IVFIndex

log = logging.getLogger("npairloss_tpu.serve")

COMPILE_GUARD_ENV = "NPAIRLOSS_SERVE_COMPILE_GUARD"

_NEG_FILL = float(-np.finfo(np.float32).max)


class ServeCompileError(RuntimeError):
    """A post-warmup XLA compile happened under the strict guard."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """``buckets`` are the fixed query padding sizes (ascending); every
    micro-batch pads to the smallest bucket that fits, so steady state
    dispatches only ``len(buckets)`` distinct programs.  ``top_k`` is
    the answer length; ``gallery_block`` the gallery rows streamed per
    scan step inside a shard (bounds the similarity working set).

    ``probes`` is the IVF probe width (clusters scored per query —
    clamped to the cluster count; ignored by a flat index).
    ``scoring`` picks the similarity-matmul dtype: ``fp32`` is the
    oracle's HIGHEST-precision path; ``bf16`` halves the scan's
    bandwidth/MXU cost (the ring bf16 bench row's ~6.7x headroom);
    ``int8`` additionally quantizes the stored slab with a per-cluster
    scale (IVF only — flat storage has no cluster to scale by).  Both
    reduced modes are gated by the recall-parity harness
    (docs/SERVING.md §Approximate index).

    ``probe_impl`` picks the IVF probe-path implementation from the
    :data:`npairloss_tpu.ops.pallas_ivf.PROBE_IMPLS` registry:
    ``scan`` is the lax.scan gather+score baseline, ``fused`` the
    single-pass Pallas kernel, ``auto`` the per-platform pick (fused
    on TPU, scan elsewhere) — resolved once at engine build and
    stamped into /healthz and bench records.  Ignored by a flat
    index."""

    top_k: int = 10
    buckets: Tuple[int, ...] = (1, 8, 32)
    gallery_block: int = 4096
    probes: int = 8
    scoring: str = "fp32"
    probe_impl: str = "scan"

    def __post_init__(self):
        if not self.buckets or list(self.buckets) != sorted(
                set(int(b) for b in self.buckets)):
            raise ValueError(
                f"buckets must be ascending and unique, got {self.buckets}"
            )
        if self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.probes < 1:
            raise ValueError(f"probes must be >= 1, got {self.probes}")
        if self.scoring not in SCORINGS:
            raise ValueError(
                f"scoring must be one of {SCORINGS}, got {self.scoring!r}"
            )
        if self.probe_impl not in PROBE_IMPLS:
            raise ValueError(
                f"probe_impl must be one of {sorted(PROBE_IMPLS)}, "
                f"got {self.probe_impl!r}"
            )


def _scored_matmul(q, g, scoring: str):
    """The similarity gemm in the configured dtype, fp32-accumulated:
    ``fp32`` is the oracle's HIGHEST path; ``bf16`` casts both sides
    (MXU-native width; the recall-parity harness gates the answer
    drift).  ``g`` may arrive int8 (the IVF quantized slab) — the cast
    happens AFTER the gather, so the bandwidth win is real; the caller
    applies the per-cluster scale to the product."""
    if scoring == "fp32":
        return jnp.dot(
            q, g.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    return jnp.dot(
        q.astype(jnp.bfloat16), g.astype(jnp.bfloat16).T,
        preferred_element_type=jnp.float32,
    )


def _stream_topk(q, emb, labels_unused, valid, k: int, block: int,
                 scoring: str = "fp32"):
    """Running top-k of ``q @ emb.T`` over gallery blocks.

    Returns (scores, rows) of shape (B, k) with rows GLOBAL over ``emb``
    (0-based).  Invalid (padding) rows never win; the final clamped
    block masks rows a previous block already scored, so each gallery
    row is a candidate exactly once.
    """
    n = emb.shape[0]
    b = int(min(block, n))
    n_blocks = -(-n // b)
    kb = min(k, b)
    bq = q.shape[0]

    def one_block(carry, j):
        best_s, best_r = carry
        start = jnp.minimum(j * b, n - b)
        g = jax.lax.dynamic_slice_in_dim(emb, start, b, axis=0)
        v = jax.lax.dynamic_slice_in_dim(valid, start, b, axis=0)
        # named_scope: the scoring gemm vs the top-k merge show up as
        # separate regions in `prof --step serve` (obs.perf) — the
        # split that decides whether bf16/int8 scoring pays.
        with jax.named_scope("serve/score"):
            sims = _scored_matmul(q, g, scoring)
        rows = start + jnp.arange(b, dtype=jnp.int32)
        # Mask padding rows AND the final block's clamped overlap (rows
        # below the unclamped start were scored by an earlier block — a
        # duplicate candidate would corrupt the top-k answer).
        ok = v & (rows >= j * b)
        with jax.named_scope("serve/merge"):
            sims = jnp.where(ok[None, :], sims, jnp.float32(_NEG_FILL))
            blk_s, blk_i = jax.lax.top_k(sims, kb)
            blk_r = rows[blk_i]
            # Merge: best-first concat keeps candidates in ascending
            # global row order within equal scores, so top_k's
            # lowest-index-first tie-break reproduces the dense answer
            # exactly.
            cand_s = jnp.concatenate([best_s, blk_s], axis=1)
            cand_r = jnp.concatenate([best_r, blk_r], axis=1)
            new_s, sel = jax.lax.top_k(cand_s, k)
            new_r = jnp.take_along_axis(cand_r, sel, axis=1)
        return (new_s, new_r), None

    init = (
        jnp.full((bq, k), jnp.float32(_NEG_FILL)),
        jnp.zeros((bq, k), jnp.int32),
    )
    (best_s, best_r), _ = jax.lax.scan(
        one_block, init, jnp.arange(n_blocks, dtype=jnp.int32)
    )
    return best_s, best_r


def _ivf_probe_topk(q, packed, rows, centroids, cvalid, scale,
                    k: int, probes: int, scoring: str, g0):
    """Probe-top-C clustered top-k over one shard's packed slab.

    ``q`` (B, D) replicated; ``packed`` (KC_local, cap, D) this shard's
    cluster slabs (fp32/bf16, or int8 with ``scale`` (KC_local,));
    ``rows`` (KC_local, cap) GLOBAL gallery row ids (-1 pad);
    ``centroids``/``cvalid`` the full replicated (KC, D)/(KC,) tables;
    ``g0`` this shard's first global cluster id.  Returns (B, kl)
    scores + global rows, kl = min(k, probes*cap) — all shards compute
    the SAME global probe set from the replicated centroids, each
    gathers only the probed clusters it owns (the rest mask to -inf),
    and the cross-shard merge is exactly the flat engine's.

    Every static extent (cap, probe width, kl) derives from the TRACED
    shapes, so an ``add()`` that grows ``cap`` forces the retrace that
    recomputes them — the flat path's add contract, kept.
    """
    kc_full = centroids.shape[0]
    kc_local = packed.shape[0]
    cap = packed.shape[1]
    c = min(probes, kc_full)
    kl = min(k, c * cap)
    bq = q.shape[0]

    with jax.named_scope("serve/probe"):
        # Centroid scan: one small (B, KC) gemm picks the probe set.
        # Padded/empty clusters mask out so a probe slot is never
        # wasted on a slab of -1 rows while a real cluster waits.
        cs = jnp.dot(
            q, centroids.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        cs = jnp.where(cvalid[None, :], cs, jnp.float32(_NEG_FILL))
        _, probe = jax.lax.top_k(cs, c)  # (B, c) global cluster ids

    def one_probe(carry, j):
        best_s, best_r = carry
        cid = probe[:, j]
        owned = (cid >= g0) & (cid < g0 + kc_local)
        lid = jnp.where(owned, cid - g0, 0)
        g = packed[lid]   # (B, cap, D) gather — the scan's working set
        r = rows[lid]     # (B, cap) global row ids
        with jax.named_scope("serve/score"):
            if scoring == "fp32":
                sims = jnp.einsum(
                    "bcd,bd->bc", g, q,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
            else:
                sims = jnp.einsum(
                    "bcd,bd->bc",
                    g.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                if scale is not None:
                    sims = sims * scale[lid][:, None]
        ok = (r >= 0) & owned[:, None]
        with jax.named_scope("serve/merge"):
            sims = jnp.where(ok, sims, jnp.float32(_NEG_FILL))
            kb = min(kl, cap)
            blk_s, blk_i = jax.lax.top_k(sims, kb)
            blk_r = jnp.take_along_axis(r, blk_i, axis=1)
            cand_s = jnp.concatenate([best_s, blk_s], axis=1)
            cand_r = jnp.concatenate([best_r, blk_r], axis=1)
            new_s, sel = jax.lax.top_k(cand_s, kl)
            new_r = jnp.take_along_axis(cand_r, sel, axis=1)
        return (new_s, new_r), None

    init = (
        jnp.full((bq, kl), jnp.float32(_NEG_FILL)),
        jnp.zeros((bq, kl), jnp.int32),
    )
    (best_s, best_r), _ = jax.lax.scan(
        one_probe, init, jnp.arange(c, dtype=jnp.int32)
    )
    return best_s, best_r


def _finalize_topk(s, r, k: int):
    """Clamp an IVF candidate list to the answer shape (B, k): pad with
    -inf columns when the probe set cannot yield k candidates, and pin
    every unfilled slot's row to 0 (a VALID gallery row — the host-side
    label/id mapping must never index with a mask sentinel)."""
    kl = s.shape[1]
    if kl < k:
        pad = k - kl
        s = jnp.concatenate(
            [s, jnp.full((s.shape[0], pad), jnp.float32(_NEG_FILL))], 1)
        r = jnp.concatenate(
            [r, jnp.zeros((r.shape[0], pad), jnp.int32)], 1)
    else:
        s, sel = jax.lax.top_k(s, k)
        r = jnp.take_along_axis(r, sel, axis=1)
    r = jnp.where(s > jnp.float32(_NEG_FILL) * 0.5, r, 0)
    return s, r


class QueryEngine:
    """Answers ``(B, D)`` query embeddings with the gallery's top-k.

    ``model``/``state`` (a Flax module + the ``restore_for_inference``
    tree) enable :meth:`encode` for raw-input queries; embedding-only
    serving needs neither.  ``telemetry`` records a ``serve/topk`` span
    per dispatch.  Thread-safety: dispatches are serialized by the
    MicroBatcher (one dispatcher thread); the engine itself keeps no
    per-call mutable state beyond the compile counters.
    """

    def __init__(
        self,
        index: GalleryIndex,
        cfg: EngineConfig = EngineConfig(),
        model=None,
        state: Optional[Dict[str, Any]] = None,
        telemetry=None,
        share_compiled_with: Optional["QueryEngine"] = None,
        share_programs_with: Optional["QueryEngine"] = None,
    ):
        if cfg.top_k > index.size:
            raise ValueError(
                f"top_k={cfg.top_k} exceeds gallery size {index.size}"
            )
        self.index = index
        self.cfg = cfg
        self.model = model
        self.state = state
        self.telemetry = telemetry
        self.warmed = False
        self.compiles_total = 0
        self.compiles_after_warmup = 0
        self._guard = os.environ.get(COMPILE_GUARD_ENV, "").strip().lower()
        self._ivf = isinstance(index, IVFIndex)
        # Resolved once here ("auto" -> the platform pick) so every
        # consumer — the jitted program choice, /healthz, bench rows,
        # the qtrace fused flag — reports the impl that actually runs.
        # None for flat engines: the probe path does not exist there,
        # and /healthz keeps its pre-IVF shape (absent-when-off).
        self.probe_impl = (
            resolve_probe_impl(cfg.probe_impl) if self._ivf else None)
        if cfg.scoring == "int8" and not self._ivf:
            raise ValueError(
                "scoring='int8' needs an IVF index (the per-cluster "
                "scale has no flat-gallery equivalent); use bf16 or "
                "--index-kind ivf"
            )
        if share_compiled_with is not None and \
                share_programs_with is not None:
            raise ValueError(
                "share_compiled_with and share_programs_with are "
                "mutually exclusive"
            )
        if share_programs_with is not None:
            # Cross-index program sharing (multi-tenant serving,
            # docs/SERVING.md §Multi-tenant): the jitted topk/encode
            # closures capture ONLY the config (k, block, probes,
            # scoring, probe impl) and the mesh/axis — index arrays and
            # model state are traced ARGUMENTS — so engines over
            # DIFFERENT galleries can reuse one set of callables.  Two
            # tenants at one (bucket, padded_size, D) geometry then hit
            # the same executable: tenant count never multiplies
            # compiles (the shared ``_seen_sigs`` set plus the cache-
            # size accounting prove it per dispatch).  Everything the
            # closures DO capture must match, loudly:
            other = share_programs_with
            if other.cfg != cfg:
                raise ValueError(
                    "share_programs_with requires an identical "
                    f"EngineConfig (got {cfg} vs {other.cfg})"
                )
            if other._ivf != self._ivf:
                raise ValueError(
                    "share_programs_with requires the same index kind "
                    "(flat vs IVF programs differ)"
                )
            if other.index.mesh is not index.mesh or \
                    other.index.axis != index.axis:
                raise ValueError(
                    "share_programs_with requires the same mesh object "
                    "and axis (the sharded program captures them)"
                )
            if other.model is not model:
                raise ValueError(
                    "share_programs_with requires the same model object "
                    "(the encode program captures it; state is an "
                    "argument)"
                )
            self._seen_sigs = other._seen_sigs
            self._topk_fn = other._topk_fn
            self._encode_fn = other._encode_fn
            return
        if share_compiled_with is not None:
            # Replica-tier compile sharing (docs/SERVING.md): replicas
            # of ONE index+config reuse the primary's jitted callables
            # AND its signature set, so warming the primary warms the
            # whole tier and no replica ever pays (or falsely counts)
            # a duplicate XLA compile.
            other = share_compiled_with
            if other.index is not index or other.cfg != cfg:
                raise ValueError(
                    "share_compiled_with requires the same index object "
                    "and an identical EngineConfig"
                )
            self._seen_sigs = other._seen_sigs
            self._topk_fn = other._topk_fn
            self._encode_fn = other._encode_fn
        else:
            self._seen_sigs: set = set()
            self._build_fns()

    # -- jitted programs ---------------------------------------------------

    def _build_fns(self) -> None:
        if self._ivf:
            self._build_ivf_fns()
        else:
            self._build_flat_fns()
        self._build_encode_fn()

    def _build_flat_fns(self) -> None:
        k = self.cfg.top_k
        block = self.cfg.gallery_block
        scoring = self.cfg.scoring
        index = self.index

        def topk_single(q, emb, labels, valid):
            return _stream_topk(q, emb, labels, valid, k, block, scoring)

        if index.mesh is not None:
            mesh, axis = index.mesh, index.axis

            def per_shard(q, emb, labels, valid):
                # Shard extent comes from the TRACED local shard, not a
                # value captured at engine build: GalleryIndex.add() can
                # grow padded_size, and the retrace the new shapes force
                # must compute offsets for the NEW layout.
                shard_n = emb.shape[0]
                kl = min(k, shard_n)
                s, r = _stream_topk(q, emb, labels, valid, kl, block,
                                    scoring)
                offset = jax.lax.axis_index(axis) * shard_n
                return s[None], (r + offset)[None]

            sharded = shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis)),
                out_specs=(P(axis), P(axis)),
            )

            def topk(q, emb, labels, valid):
                # (G, B, kl) per-shard candidates -> (B, G*kl) in
                # ascending-shard (== ascending global row) order, then
                # one merging top_k.
                s, r = sharded(q, emb, labels, valid)
                g, _, kl = s.shape
                s = jnp.transpose(s, (1, 0, 2)).reshape(q.shape[0], g * kl)
                r = jnp.transpose(r, (1, 0, 2)).reshape(q.shape[0], g * kl)
                best_s, sel = jax.lax.top_k(s, k)
                best_r = jnp.take_along_axis(r, sel, axis=1)
                return best_s, best_r

            self._topk_fn = jax.jit(topk)
        else:
            self._topk_fn = jax.jit(topk_single)

    def _build_ivf_fns(self) -> None:
        """The probe-top-C clustered path (serve/ivf.py): centroid scan
        -> gather probed clusters -> scored top-k merge across clusters
        and mesh shards.  Same dispatch protocol as the flat path —
        (B, k) scores + GLOBAL gallery rows — so the server, warmup,
        and compile accounting are unchanged."""
        k = self.cfg.top_k
        probes = self.cfg.probes
        scoring = self.cfg.scoring
        index = self.index
        with_scale = scoring == "int8"
        # Both impls share the exact operand/return protocol, so the
        # registry choice is one function pointer — everything
        # downstream (finalize, shard merge, compile accounting) is
        # impl-agnostic.
        probe_fn = (fused_probe_topk if self.probe_impl == "fused"
                    else _ivf_probe_topk)

        def single(q, packed, rows, cents, cvalid, scale=None):
            s, r = probe_fn(
                q, packed, rows, cents, cvalid, scale,
                k=k, probes=probes, scoring=scoring, g0=0)
            return _finalize_topk(s, r, k)

        if index.mesh is not None:
            mesh, axis = index.mesh, index.axis
            g = mesh.size

            def per_shard(q, packed, rows, cents, cvalid, scale=None):
                kc_local = packed.shape[0]
                g0 = jax.lax.axis_index(axis) * kc_local
                s, r = probe_fn(
                    q, packed, rows, cents, cvalid, scale,
                    k=k, probes=probes, scoring=scoring, g0=g0)
                return s[None], r[None]

            specs = [P(), P(axis), P(axis), P(), P()]
            if with_scale:
                specs.append(P(axis))
            sharded = shard_map(
                per_shard, mesh=mesh,
                in_specs=tuple(specs),
                out_specs=(P(axis), P(axis)),
                # The replication checker has no pallas_call rule; the
                # fused kernel's outputs are all P(axis)-varying anyway.
                **(REP_CHECK_OFF if self.probe_impl == "fused" else {}),
            )

            def topk(q, packed, rows, cents, cvalid, scale=None):
                args = (q, packed, rows, cents, cvalid)
                if with_scale:
                    args += (scale,)
                s, r = sharded(*args)
                _, _, kl = s.shape
                s = jnp.transpose(s, (1, 0, 2)).reshape(q.shape[0], g * kl)
                r = jnp.transpose(r, (1, 0, 2)).reshape(q.shape[0], g * kl)
                return _finalize_topk(s, r, k)

            self._topk_fn = jax.jit(topk)
        else:
            self._topk_fn = jax.jit(single)

    def _build_encode_fn(self) -> None:
        if self.model is not None:
            model = self.model

            def encode(state, x):
                variables = {"params": state["params"]}
                if state.get("batch_stats"):
                    variables["batch_stats"] = state["batch_stats"]
                with jax.named_scope("serve/encode"):
                    emb = model.apply(variables, x, train=False)
                with jax.named_scope("serve/normalize"):
                    return l2_normalize(emb)

            self._encode_fn = jax.jit(encode)
        else:
            self._encode_fn = None

    def _span(self, name: str, **args):
        if self.telemetry is None:
            import contextlib

            return contextlib.nullcontext()
        return self.telemetry.span(name, **args)

    def _cache_size(self) -> Optional[int]:
        sizes = []
        for fn in (self._topk_fn, self._encode_fn):
            if fn is None:
                continue
            get = getattr(fn, "_cache_size", None)
            if get is None:
                return None
            sizes.append(get())
        return sum(sizes) if sizes else 0

    def _count_compiles(self, sig, n_before: Optional[int]) -> None:
        """Signature-set + executable-cache-size compile accounting; the
        cache size also catches sharding/aval-keyed recompiles the
        signature heuristic cannot predict (the PR-4 lesson)."""
        fresh = sig not in self._seen_sigs
        self._seen_sigs.add(sig)
        grew = (n_before is not None
                and (self._cache_size() or 0) > n_before)
        # serve.compile_storm (docs/RESILIENCE.md): count a PHANTOM
        # post-warmup compile — no real XLA work, but every consumer of
        # the accounting (watchdog, strict guard, window rows) sees one,
        # so the re-warm remediation is deterministically drivable.
        # Short-circuit order matters: an unwarmed (re-warming) engine
        # must not consume armed fires.
        storm = self.warmed and failpoints.should_fire(
            "serve.compile_storm")
        if not (fresh or grew or storm):
            return
        self.compiles_total += 1
        if not self.warmed:
            return
        self.compiles_after_warmup += 1
        if self.telemetry is not None:
            self.telemetry.instant("serve/recompile", sig=str(sig))
        log.warning("serve: post-warmup XLA compile (sig=%s)", sig)
        if self._guard == "strict":
            raise ServeCompileError(
                f"post-warmup compile in the serving hot path (sig={sig}); "
                "warm every bucket before taking traffic "
                "(docs/SERVING.md)"
            )

    # -- query path --------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n (callers chunk above max)."""
        for b in self.cfg.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket "
            f"{self.cfg.buckets[-1]} (the batcher must chunk)"
        )

    def encode(self, inputs: np.ndarray) -> np.ndarray:
        """Raw inputs -> unit-norm query embeddings via the restored
        trunk (eval mode), padded per bucket like :meth:`query`."""
        if self._encode_fn is None:
            raise RuntimeError(
                "engine built without model/state: embedding queries only"
            )
        x = np.asarray(inputs, np.float32)
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            x = np.concatenate(
                [x, np.zeros((bucket - n, *x.shape[1:]), np.float32)]
            )
        sig = ("encode", tuple(x.shape))
        n_before = self._cache_size()
        with self._span("serve/encode", batch=n, bucket=bucket):
            emb = self._encode_fn(self.state, jnp.asarray(x))
        self._count_compiles(sig, n_before)
        return np.asarray(emb)[:n]

    def query(
        self, embeddings: np.ndarray, normalize: bool = True,
        stages: Optional[Dict[str, float]] = None,
    ) -> Dict[str, np.ndarray]:
        """Top-k for ``(B, D)`` query embeddings.

        Pads B to the smallest bucket (chunking batches above the
        largest), dispatches the jitted streamed/sharded top-k, and maps
        winning gallery rows to labels/ids host-side.  Returns
        ``{"scores", "rows", "labels", "ids"}``, each (B, top_k).

        ``stages`` (optional) is a per-call accumulator the qtrace
        layer passes in: the device top-k wall time lands in
        ``score_us`` and the host label/id gather in ``merge_us``,
        summed across bucket chunks.  Per-call (not an engine
        attribute) on purpose — a crash reroute dispatches two batches
        on one engine concurrently, and racing attributes would charge
        one batch's score time to the other's trace.
        """
        q = np.asarray(embeddings, np.float32)
        if q.ndim != 2 or q.shape[1] != self.index.dim:
            raise ValueError(
                f"queries {q.shape} do not match gallery dim "
                f"{self.index.dim}"
            )
        if q.shape[0] == 0:
            k = self.cfg.top_k
            return {
                "scores": np.zeros((0, k), np.float32),
                "rows": np.zeros((0, k), np.int32),
                "labels": np.zeros((0, k), np.int32),
                "ids": np.zeros((0, k), np.int64),
            }
        if normalize:
            q = l2_normalize_rows(q)
        max_b = self.cfg.buckets[-1]
        outs = [self._query_bucketed(q[i:i + max_b], stages=stages)
                for i in range(0, q.shape[0], max_b)]
        return {
            key: np.concatenate([o[key] for o in outs])
            for key in outs[0]
        }

    def _topk_call(self, bucket: int):
        """(dispatch args, compile signature) for the current index
        state — read ONCE per dispatch, so an IVF republish (add())
        lands between dispatches, never inside one."""
        idx = self.index
        if self._ivf:
            layout = idx.layout
            slab, scale = idx.scored_arrays(self.cfg.scoring,
                                            layout=layout)
            args = (slab, layout.rows, layout.centroids,
                    layout.cluster_valid)
            if scale is not None:
                args += (scale,)
            sig = ("ivf", bucket, tuple(layout.packed.shape),
                   self.cfg.scoring, self.probe_impl)
            return args, sig
        return ((idx.emb, idx.labels, idx.valid),
                ("topk", bucket, idx.padded_size, idx.dim))

    def _query_bucketed(
        self, q: np.ndarray,
        stages: Optional[Dict[str, float]] = None,
    ) -> Dict[str, np.ndarray]:
        n = q.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            q = np.concatenate(
                [q, np.zeros((bucket - n, q.shape[1]), np.float32)]
            )
        idx = self.index
        # serve.recall_drop (docs/RESILIENCE.md): deterministically
        # mis-probe the IVF top-C selection for this dispatch — the
        # centroid scan runs against the NEGATED query, so the probe
        # set is the worst clusters and recall collapses while shapes,
        # sharding, and compile signatures stay identical (zero
        # recompiles, the strict guard never trips).  Gated on
        # ``warmed`` so warmup/re-warm dispatches never consume armed
        # fires, and on the IVF path so a flat tier (the recall
        # oracle) leaves the arming untouched.
        if self._ivf and self.warmed and \
                failpoints.should_fire("serve.recall_drop"):
            q = -q
        args, sig = self._topk_call(bucket)
        n_before = self._cache_size()
        t_score = time.perf_counter()
        with self._span("serve/topk", batch=n, bucket=bucket):
            scores, rows = self._topk_fn(jnp.asarray(q), *args)
            scores = np.asarray(scores)[:n]
            rows = np.asarray(rows)[:n]
        self._count_compiles(sig, n_before)
        t_merge = time.perf_counter()
        out = {
            "scores": scores,
            "rows": rows,
            "labels": idx._host_labels[rows],
            "ids": idx.ids[rows],
        }
        if stages is not None:
            # Device scoring vs host gather, accumulated across bucket
            # chunks (the qtrace score/topk_merge split).
            stages["score_us"] = stages.get("score_us", 0.0) \
                + (t_merge - t_score) * 1e6
            stages["merge_us"] = stages.get("merge_us", 0.0) \
                + (time.perf_counter() - t_merge) * 1e6
        return out

    # -- warmup ------------------------------------------------------------

    def warmup(self, input_shape: Optional[Sequence[int]] = None) -> float:
        """Compile and prime every padding bucket with one dummy
        dispatch each — after this returns, steady-state serving
        performs ZERO XLA compiles (the counters prove it).  The
        dispatch-time compile consults AND populates the persistent
        compile cache when one is enabled, so replica restarts
        deserialize instead of recompiling.  (An AOT
        ``lower().compile()`` first would pay every compile twice: jit's
        dispatch cache ignores AOT executables, so the priming dispatch
        recompiles from scratch.)  Returns the wall seconds spent."""
        import time as _time

        idx = self.index
        t0 = _time.perf_counter()
        for bucket in self.cfg.buckets:
            with self._span("serve/warmup", bucket=bucket, kind="topk"):
                self._query_bucketed(np.zeros((bucket, idx.dim),
                                              np.float32))
            if self._encode_fn is not None:
                if input_shape is None:
                    raise ValueError(
                        "warmup needs input_shape to warm the encode path"
                    )
                with self._span("serve/warmup", bucket=bucket,
                                kind="encode"):
                    self.encode(np.zeros((bucket, *tuple(input_shape)),
                                         np.float32))
        self.warmed = True
        dt = _time.perf_counter() - t0
        log.info("serve warmup: %d bucket(s) compiled in %.2fs",
                 len(self.cfg.buckets), dt)
        return dt

    def rewarm(self, input_shape: Optional[Sequence[int]] = None) -> float:
        """Re-prime every padding bucket and RESET the post-warmup
        compile counter — the compile-storm remediation action
        (docs/RESILIENCE.md §Remediation).  The re-warm dispatches run
        with ``warmed`` cleared, so any compile they trigger counts as
        warmup (never trips the strict guard), and
        ``compiles_after_warmup`` restarts at zero so the post-warmup-
        compile watchdog can observe recovery.  Returns wall seconds.

        A re-warm that RAISES resets nothing: the engine keeps serving
        (``warmed`` restored so accounting stays armed) and the storm
        evidence in ``compiles_after_warmup`` survives — the alert that
        triggered the failed remediation must keep its basis."""
        self.warmed = False
        try:
            dt = self.warmup(input_shape)  # sets warmed=True on success
        except BaseException:
            self.warmed = True
            raise
        self.compiles_after_warmup = 0
        return dt

    def compile_stats(self) -> Dict[str, Any]:
        return {
            "warmed": self.warmed,
            "compiles_total": self.compiles_total,
            "compiles_after_warmup": self.compiles_after_warmup,
            "executable_cache_size": self._cache_size(),
        }
