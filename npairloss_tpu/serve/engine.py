"""QueryEngine — the jitted online query path: encode -> topk answers.

One dispatch per micro-batch: (optionally) encode raw inputs through the
restored model trunk, ``ops.normalize`` the query rows, then a
block-streamed similarity matmul against the mesh-resident gallery with
``lax.top_k`` merged across gallery blocks and mesh shards.  The
math is the deployment protocol of ``ops/eval_retrieval.py`` — fp32
HIGHEST-precision cosine on the MXU — so served answers are exactly
consistent with the offline ``gallery_recall_at_k`` numbers (parity is
pinned by tests/test_serve.py).

Streaming + merge layout (docs/SERVING.md):

  * within a shard, gallery rows stream in fixed blocks through a
    ``lax.scan`` carrying the running (B, k) best scores/rows — the
    B x N similarity matrix is never materialized (the
    ``ops/eval_retrieval.py`` trick, applied to the gallery axis);
  * across shards, each mesh shard returns its local top-k with GLOBAL
    row numbers (shard offset via ``axis_index``); the (G, B, k)
    candidates reshape to (B, G*k) in ascending-shard order and one
    final ``top_k`` merges them.

Both merges preserve ``lax.top_k``'s lowest-index-wins tie-break:
candidates always concatenate in ascending global-row order, so the
streamed/sharded answer is bit-identical to a dense single-device
``top_k`` over the whole gallery.

Steady-state serving never compiles: :meth:`warmup` compiles and primes
every padding bucket with one dummy dispatch each (populating the
persistent compile cache when one is enabled — see
:meth:`QueryEngine.warmup` for why AOT ``lower().compile()`` would pay
each compile twice).  Every later compile is COUNTED
(``compiles_after_warmup``) via
the jit cache size, and ``NPAIRLOSS_SERVE_COMPILE_GUARD=strict`` turns
a post-warmup compile into an error — the serving twin of the pipeline
sync guard.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from npairloss_tpu.ops.normalize import l2_normalize
from npairloss_tpu.parallel._compat import shard_map
from npairloss_tpu.serve.index import GalleryIndex, l2_normalize_rows

log = logging.getLogger("npairloss_tpu.serve")

COMPILE_GUARD_ENV = "NPAIRLOSS_SERVE_COMPILE_GUARD"

_NEG_FILL = float(-np.finfo(np.float32).max)


class ServeCompileError(RuntimeError):
    """A post-warmup XLA compile happened under the strict guard."""


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """``buckets`` are the fixed query padding sizes (ascending); every
    micro-batch pads to the smallest bucket that fits, so steady state
    dispatches only ``len(buckets)`` distinct programs.  ``top_k`` is
    the answer length; ``gallery_block`` the gallery rows streamed per
    scan step inside a shard (bounds the similarity working set)."""

    top_k: int = 10
    buckets: Tuple[int, ...] = (1, 8, 32)
    gallery_block: int = 4096

    def __post_init__(self):
        if not self.buckets or list(self.buckets) != sorted(
                set(int(b) for b in self.buckets)):
            raise ValueError(
                f"buckets must be ascending and unique, got {self.buckets}"
            )
        if self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")


def _stream_topk(q, emb, labels_unused, valid, k: int, block: int):
    """Running top-k of ``q @ emb.T`` over gallery blocks.

    Returns (scores, rows) of shape (B, k) with rows GLOBAL over ``emb``
    (0-based).  Invalid (padding) rows never win; the final clamped
    block masks rows a previous block already scored, so each gallery
    row is a candidate exactly once.
    """
    n = emb.shape[0]
    b = int(min(block, n))
    n_blocks = -(-n // b)
    kb = min(k, b)
    bq = q.shape[0]

    def one_block(carry, j):
        best_s, best_r = carry
        start = jnp.minimum(j * b, n - b)
        g = jax.lax.dynamic_slice_in_dim(emb, start, b, axis=0)
        v = jax.lax.dynamic_slice_in_dim(valid, start, b, axis=0)
        # named_scope: the scoring gemm vs the top-k merge show up as
        # separate regions in `prof --step serve` (obs.perf) — the
        # split that decides whether bf16/int8 scoring pays.
        with jax.named_scope("serve/score"):
            sims = jnp.dot(
                q, g.T,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        rows = start + jnp.arange(b, dtype=jnp.int32)
        # Mask padding rows AND the final block's clamped overlap (rows
        # below the unclamped start were scored by an earlier block — a
        # duplicate candidate would corrupt the top-k answer).
        ok = v & (rows >= j * b)
        with jax.named_scope("serve/merge"):
            sims = jnp.where(ok[None, :], sims, jnp.float32(_NEG_FILL))
            blk_s, blk_i = jax.lax.top_k(sims, kb)
            blk_r = rows[blk_i]
            # Merge: best-first concat keeps candidates in ascending
            # global row order within equal scores, so top_k's
            # lowest-index-first tie-break reproduces the dense answer
            # exactly.
            cand_s = jnp.concatenate([best_s, blk_s], axis=1)
            cand_r = jnp.concatenate([best_r, blk_r], axis=1)
            new_s, sel = jax.lax.top_k(cand_s, k)
            new_r = jnp.take_along_axis(cand_r, sel, axis=1)
        return (new_s, new_r), None

    init = (
        jnp.full((bq, k), jnp.float32(_NEG_FILL)),
        jnp.zeros((bq, k), jnp.int32),
    )
    (best_s, best_r), _ = jax.lax.scan(
        one_block, init, jnp.arange(n_blocks, dtype=jnp.int32)
    )
    return best_s, best_r


class QueryEngine:
    """Answers ``(B, D)`` query embeddings with the gallery's top-k.

    ``model``/``state`` (a Flax module + the ``restore_for_inference``
    tree) enable :meth:`encode` for raw-input queries; embedding-only
    serving needs neither.  ``telemetry`` records a ``serve/topk`` span
    per dispatch.  Thread-safety: dispatches are serialized by the
    MicroBatcher (one dispatcher thread); the engine itself keeps no
    per-call mutable state beyond the compile counters.
    """

    def __init__(
        self,
        index: GalleryIndex,
        cfg: EngineConfig = EngineConfig(),
        model=None,
        state: Optional[Dict[str, Any]] = None,
        telemetry=None,
    ):
        if cfg.top_k > index.size:
            raise ValueError(
                f"top_k={cfg.top_k} exceeds gallery size {index.size}"
            )
        self.index = index
        self.cfg = cfg
        self.model = model
        self.state = state
        self.telemetry = telemetry
        self.warmed = False
        self.compiles_total = 0
        self.compiles_after_warmup = 0
        self._guard = os.environ.get(COMPILE_GUARD_ENV, "").strip().lower()
        self._seen_sigs: set = set()
        self._build_fns()

    # -- jitted programs ---------------------------------------------------

    def _build_fns(self) -> None:
        k = self.cfg.top_k
        block = self.cfg.gallery_block
        index = self.index

        def topk_single(q, emb, labels, valid):
            return _stream_topk(q, emb, labels, valid, k, block)

        if index.mesh is not None:
            mesh, axis = index.mesh, index.axis

            def per_shard(q, emb, labels, valid):
                # Shard extent comes from the TRACED local shard, not a
                # value captured at engine build: GalleryIndex.add() can
                # grow padded_size, and the retrace the new shapes force
                # must compute offsets for the NEW layout.
                shard_n = emb.shape[0]
                kl = min(k, shard_n)
                s, r = _stream_topk(q, emb, labels, valid, kl, block)
                offset = jax.lax.axis_index(axis) * shard_n
                return s[None], (r + offset)[None]

            sharded = shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis)),
                out_specs=(P(axis), P(axis)),
            )

            def topk(q, emb, labels, valid):
                # (G, B, kl) per-shard candidates -> (B, G*kl) in
                # ascending-shard (== ascending global row) order, then
                # one merging top_k.
                s, r = sharded(q, emb, labels, valid)
                g, _, kl = s.shape
                s = jnp.transpose(s, (1, 0, 2)).reshape(q.shape[0], g * kl)
                r = jnp.transpose(r, (1, 0, 2)).reshape(q.shape[0], g * kl)
                best_s, sel = jax.lax.top_k(s, k)
                best_r = jnp.take_along_axis(r, sel, axis=1)
                return best_s, best_r

            self._topk_fn = jax.jit(topk)
        else:
            self._topk_fn = jax.jit(topk_single)

        if self.model is not None:
            model = self.model

            def encode(state, x):
                variables = {"params": state["params"]}
                if state.get("batch_stats"):
                    variables["batch_stats"] = state["batch_stats"]
                with jax.named_scope("serve/encode"):
                    emb = model.apply(variables, x, train=False)
                with jax.named_scope("serve/normalize"):
                    return l2_normalize(emb)

            self._encode_fn = jax.jit(encode)
        else:
            self._encode_fn = None

    def _span(self, name: str, **args):
        if self.telemetry is None:
            import contextlib

            return contextlib.nullcontext()
        return self.telemetry.span(name, **args)

    def _cache_size(self) -> Optional[int]:
        sizes = []
        for fn in (self._topk_fn, self._encode_fn):
            if fn is None:
                continue
            get = getattr(fn, "_cache_size", None)
            if get is None:
                return None
            sizes.append(get())
        return sum(sizes) if sizes else 0

    def _count_compiles(self, sig, n_before: Optional[int]) -> None:
        """Signature-set + executable-cache-size compile accounting; the
        cache size also catches sharding/aval-keyed recompiles the
        signature heuristic cannot predict (the PR-4 lesson)."""
        fresh = sig not in self._seen_sigs
        self._seen_sigs.add(sig)
        grew = (n_before is not None
                and (self._cache_size() or 0) > n_before)
        if not (fresh or grew):
            return
        self.compiles_total += 1
        if not self.warmed:
            return
        self.compiles_after_warmup += 1
        if self.telemetry is not None:
            self.telemetry.instant("serve/recompile", sig=str(sig))
        log.warning("serve: post-warmup XLA compile (sig=%s)", sig)
        if self._guard == "strict":
            raise ServeCompileError(
                f"post-warmup compile in the serving hot path (sig={sig}); "
                "warm every bucket before taking traffic "
                "(docs/SERVING.md)"
            )

    # -- query path --------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n (callers chunk above max)."""
        for b in self.cfg.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket "
            f"{self.cfg.buckets[-1]} (the batcher must chunk)"
        )

    def encode(self, inputs: np.ndarray) -> np.ndarray:
        """Raw inputs -> unit-norm query embeddings via the restored
        trunk (eval mode), padded per bucket like :meth:`query`."""
        if self._encode_fn is None:
            raise RuntimeError(
                "engine built without model/state: embedding queries only"
            )
        x = np.asarray(inputs, np.float32)
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            x = np.concatenate(
                [x, np.zeros((bucket - n, *x.shape[1:]), np.float32)]
            )
        sig = ("encode", tuple(x.shape))
        n_before = self._cache_size()
        with self._span("serve/encode", batch=n, bucket=bucket):
            emb = self._encode_fn(self.state, jnp.asarray(x))
        self._count_compiles(sig, n_before)
        return np.asarray(emb)[:n]

    def query(
        self, embeddings: np.ndarray, normalize: bool = True
    ) -> Dict[str, np.ndarray]:
        """Top-k for ``(B, D)`` query embeddings.

        Pads B to the smallest bucket (chunking batches above the
        largest), dispatches the jitted streamed/sharded top-k, and maps
        winning gallery rows to labels/ids host-side.  Returns
        ``{"scores", "rows", "labels", "ids"}``, each (B, top_k).
        """
        q = np.asarray(embeddings, np.float32)
        if q.ndim != 2 or q.shape[1] != self.index.dim:
            raise ValueError(
                f"queries {q.shape} do not match gallery dim "
                f"{self.index.dim}"
            )
        if q.shape[0] == 0:
            k = self.cfg.top_k
            return {
                "scores": np.zeros((0, k), np.float32),
                "rows": np.zeros((0, k), np.int32),
                "labels": np.zeros((0, k), np.int32),
                "ids": np.zeros((0, k), np.int64),
            }
        if normalize:
            q = l2_normalize_rows(q)
        max_b = self.cfg.buckets[-1]
        outs = [self._query_bucketed(q[i:i + max_b])
                for i in range(0, q.shape[0], max_b)]
        return {
            key: np.concatenate([o[key] for o in outs])
            for key in outs[0]
        }

    def _query_bucketed(self, q: np.ndarray) -> Dict[str, np.ndarray]:
        n = q.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            q = np.concatenate(
                [q, np.zeros((bucket - n, q.shape[1]), np.float32)]
            )
        idx = self.index
        sig = ("topk", bucket, idx.padded_size, idx.dim)
        n_before = self._cache_size()
        with self._span("serve/topk", batch=n, bucket=bucket):
            scores, rows = self._topk_fn(
                jnp.asarray(q), idx.emb, idx.labels, idx.valid
            )
            scores = np.asarray(scores)[:n]
            rows = np.asarray(rows)[:n]
        self._count_compiles(sig, n_before)
        return {
            "scores": scores,
            "rows": rows,
            "labels": idx._host_labels[rows],
            "ids": idx.ids[rows],
        }

    # -- warmup ------------------------------------------------------------

    def warmup(self, input_shape: Optional[Sequence[int]] = None) -> float:
        """Compile and prime every padding bucket with one dummy
        dispatch each — after this returns, steady-state serving
        performs ZERO XLA compiles (the counters prove it).  The
        dispatch-time compile consults AND populates the persistent
        compile cache when one is enabled, so replica restarts
        deserialize instead of recompiling.  (An AOT
        ``lower().compile()`` first would pay every compile twice: jit's
        dispatch cache ignores AOT executables, so the priming dispatch
        recompiles from scratch.)  Returns the wall seconds spent."""
        import time as _time

        idx = self.index
        t0 = _time.perf_counter()
        for bucket in self.cfg.buckets:
            with self._span("serve/warmup", bucket=bucket, kind="topk"):
                self._query_bucketed(np.zeros((bucket, idx.dim),
                                              np.float32))
            if self._encode_fn is not None:
                if input_shape is None:
                    raise ValueError(
                        "warmup needs input_shape to warm the encode path"
                    )
                with self._span("serve/warmup", bucket=bucket,
                                kind="encode"):
                    self.encode(np.zeros((bucket, *tuple(input_shape)),
                                         np.float32))
        self.warmed = True
        dt = _time.perf_counter() - t0
        log.info("serve warmup: %d bucket(s) compiled in %.2fs",
                 len(self.cfg.buckets), dt)
        return dt

    def compile_stats(self) -> Dict[str, Any]:
        return {
            "warmed": self.warmed,
            "compiles_total": self.compiles_total,
            "compiles_after_warmup": self.compiles_after_warmup,
            "executable_cache_size": self._cache_size(),
        }
