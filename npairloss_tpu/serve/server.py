"""RetrievalServer — snapshot-to-answers front ends over the engine.

Two front ends share one serving core (admit -> micro-batch -> jitted
top-k -> answer):

  * **stdin/JSONL** (:meth:`RetrievalServer.run_jsonl`): one request
    object per line in, one answer object per line out, in request
    order.  The loop reads ahead (bounded by the batcher's admission
    queue) so consecutive requests coalesce into micro-batches.
  * **localhost HTTP** (:meth:`RetrievalServer.run_http`): ``POST
    /query`` with a JSON request (or JSONL body of several), ``GET
    /healthz`` for liveness/stats.  Each request thread submits and
    waits, so concurrent clients batch naturally.

Request: ``{"id": ..., "embedding": [...]}`` (a query embedding) or
``{"id": ..., "input": [...]}`` (raw input, needs a restored model).
Answer: ``{"id", "neighbors": [{"rank", "row", "gallery_id", "label",
"score"}, ...]}``; a rejected/failed query answers ``{"id", "error"}``
instead of being silently dropped.  An ingest record ``{"id",
"ingest": {"ids", "labels", "embeddings"}}`` takes the durable path
instead (docs/RESILIENCE.md §Durability): write-ahead log append +
group-commit fsync barrier BEFORE the ``{"id", "ingested", "seq"}``
ack, so a SIGKILL after the ack can never lose the vectors.

Shutdown is the training preemption contract (docs/RESILIENCE.md)
applied to serving: SIGTERM/SIGINT set the ``resilience.preempt`` flag,
the front end stops ADMITTING, every in-flight query drains to an
answer, telemetry flushes, and the process exits
:data:`~npairloss_tpu.resilience.preempt.EXIT_PREEMPTED` (75) so a
supervisor knows the stop was graceful.  A final ``serve_drain``
summary record (queries, answers, p50/p99, compile counters) is the
last line the JSONL front end writes.
"""

from __future__ import annotations

import base64
import collections
import contextlib
import dataclasses
import json
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.preempt import EXIT_PREEMPTED, PreemptionSignal
from npairloss_tpu.serve.batcher import BatcherConfig, QueueFullError
from npairloss_tpu.serve.engine import QueryEngine

log = logging.getLogger("npairloss_tpu.serve")


class UnknownTenantError(ValueError):
    """A record named a tenant the registry does not know.  Raised
    from ``submit`` BEFORE the query is counted: an unregistered id is
    a malformed request (the bad-JSON accounting — errors, never
    queries/rejected), not admitted-then-shed traffic."""


def encode_ingest_body(ingest: Dict[str, Any]) -> Dict[str, Any]:
    """A client ingest block -> the ``npairloss-wal-v1`` ``kind: "add"``
    record body (docs/RESILIENCE.md §Durability).  ``ids`` are REQUIRED:
    the WAL is the replay source of truth, and auto-assigned ids would
    come out different on every replay — breaking the exactly-once
    duplicate check.  The embedding matrix rides as base64 float32 so
    the record (and the jax-free WAL validator reading it) stays
    numpy-free."""
    if not isinstance(ingest, dict):
        raise ValueError("ingest must be an object")
    emb = np.asarray(ingest.get("embeddings"), np.float32)
    if emb.ndim != 2 or emb.shape[0] == 0 or emb.shape[1] == 0:
        raise ValueError(
            f"ingest embeddings must be a non-empty 2-D matrix, got "
            f"shape {emb.shape}")
    labels = ingest.get("labels")
    ids = ingest.get("ids")
    if not isinstance(labels, list) or len(labels) != emb.shape[0]:
        raise ValueError("ingest labels must list one label per row")
    if not isinstance(ids, list) or len(ids) != emb.shape[0]:
        raise ValueError(
            "ingest ids must list one id per row (replay determinism "
            "forbids auto-assignment)")
    return {
        "kind": "add",
        "ids": [int(i) for i in ids],
        "labels": [int(x) for x in labels],
        "dim": int(emb.shape[1]),
        "emb": base64.b64encode(emb.tobytes()).decode("ascii"),
    }


def decode_ingest_payload(payload: Dict[str, Any]
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The inverse of :func:`encode_ingest_body`: a replayed WAL record
    body -> ``(embeddings, labels, ids)`` ready for ``index.add``."""
    ids = np.asarray(payload["ids"], np.int64)
    raw = base64.b64decode(payload["emb"])
    emb = np.frombuffer(raw, np.float32)
    dim = int(payload["dim"])
    if dim < 1 or emb.size != ids.shape[0] * dim:
        raise ValueError(
            f"ingest record seq {payload.get('seq')}: embedding bytes "
            f"({emb.size} float32) do not match {ids.shape[0]} row(s) "
            f"of dim {dim}")
    return (emb.reshape(ids.shape[0], dim).copy(),
            np.asarray(payload["labels"], np.int32), ids)


@dataclasses.dataclass(frozen=True)
class Freshness:
    """What the serving tier is answering FROM, and how old it is
    (ROADMAP item 4 first slice; docs/OBSERVABILITY.md §Live
    observatory).  ``snapshot_*`` identify the restored model behind
    the encode path (None for embedding-only serving);
    ``index_created`` is the gallery's commit/assembly wall time
    (``GalleryIndex.created``).  ``ages()`` turns both into seconds —
    stamped on every answer, on ``/healthz``, and on the drain
    summary, live-obs on or off."""

    index_path: Optional[str] = None
    index_created: Optional[float] = None
    snapshot_path: Optional[str] = None
    snapshot_step: Optional[int] = None
    snapshot_created: Optional[float] = None

    @classmethod
    def collect(cls, index=None, index_path: Optional[str] = None,
                snapshot_path: Optional[str] = None) -> "Freshness":
        """From the served objects: the index's ``created`` attribute
        plus the snapshot's commit manifest (``train.snapshot_info`` —
        no array loads)."""
        snap_step = snap_created = None
        if snapshot_path is not None:
            from npairloss_tpu.train import snapshot_info

            info = snapshot_info(snapshot_path)
            snapshot_path = info["path"]
            snap_step, snap_created = info["step"], info["created"]
        return cls(
            index_path=index_path,
            index_created=getattr(index, "created", None),
            snapshot_path=snapshot_path,
            snapshot_step=snap_step,
            snapshot_created=snap_created,
        )

    def ages(self, now: Optional[float] = None) -> Dict[str, float]:
        """``model_age_s``/``index_age_s`` — keys absent when the
        corresponding identity is unknown (embedding-only serving has
        no model age; a manifest-less index has no commit time), so a
        consumer never mistakes "unknown" for "fresh"."""
        now = time.time() if now is None else now
        out: Dict[str, float] = {}
        if self.index_created is not None:
            out["index_age_s"] = round(max(now - self.index_created, 0.0), 3)
        if self.snapshot_created is not None:
            out["model_age_s"] = round(
                max(now - self.snapshot_created, 0.0), 3)
        return out

    def identity(self) -> Dict[str, Any]:
        """The non-age half (for /healthz + the drain summary): which
        snapshot/index, omitting unknown fields."""
        out: Dict[str, Any] = {}
        if self.index_path is not None:
            out["index_path"] = self.index_path
        if self.snapshot_path is not None:
            out["snapshot_path"] = self.snapshot_path
        if self.snapshot_step is not None:
            out["snapshot_step"] = self.snapshot_step
        return out


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """``metrics_window``: queries per emitted latency/throughput row
    (0 = none); ``latency_window``: ring capacity for the percentile
    estimate; ``poll_s``: front-end wakeup period for noticing a drain
    request while idle; ``explicit_drops``: carry ``queries_dropped``
    in the summary/healthz even at zero (a gameday verdict's zero-drop
    gate must read a MEASURED 0, not an absent key — the
    ``compiles_after_warmup`` explicit-key posture; default off keeps
    clean streams byte-identical to pre-PR)."""

    metrics_window: int = 100
    latency_window: int = 1024
    poll_s: float = 0.1
    explicit_drops: bool = False


class RetrievalServer:
    """N replica engines + per-replica batchers + the request/answer
    protocol (one engine is the degenerate, pre-replica-tier shape)."""

    def __init__(
        self,
        engine,
        batcher_cfg: BatcherConfig = BatcherConfig(),
        cfg: ServerConfig = ServerConfig(),
        telemetry=None,
        preempt: Optional[PreemptionSignal] = None,
        freshness: Optional[Freshness] = None,
        live=None,
        admission=None,
        input_shape=None,
        qtrace=None,
    ):
        from npairloss_tpu.serve.replicas import ReplicaSet

        # ``engine`` may be one QueryEngine or a sequence of replica
        # engines (docs/SERVING.md §Approximate index): each replica
        # gets its own batcher/admission queue; routing is least-loaded
        # live replica.  ``self.engine`` stays the primary — compile
        # stats and index identity are tier-wide (replicas share the
        # primary's compiled programs).
        engines = (list(engine) if isinstance(engine, (list, tuple))
                   else [engine])
        self.engines: List[QueryEngine] = engines  # guarded-by: _lock
        self.engine = engines[0]  # guarded-by: _lock
        self.cfg = cfg
        self.telemetry = telemetry
        self.preempt = preempt
        # Freshness identity (stamped on every answer + /healthz + the
        # drain summary — live-obs on or off) and the optional
        # LiveObservatory (obs.live): /metrics exposition + SLO status
        # on /healthz.  Both default None: the pre-PR server shape.
        self.freshness = freshness  # guarded-by: _lock
        self.live = live
        # SLO-burn-driven admission control (serve/admission.py): when
        # set, submits consult it BEFORE routing — a shed is a
        # fast-reject counted in the ``rejected`` invariant.
        self.admission = admission
        # Per-query stage tracing (obs.qtrace): trace ids assigned at
        # ingestion ride each record through admission, the router, the
        # batcher, and the engine; None (the default) keeps every
        # emitted stream byte-identical to a qtrace-free build (the
        # shadow=None posture, pinned by tests/test_qtrace.py).
        self.qtrace = qtrace
        # Raw-input shape for encode-path re-warms (None = embedding-
        # only serving) and the optional RemediationEngine whose
        # last-action-per-policy the summary/healthz surface
        # (docs/RESILIENCE.md §Remediation).
        self.input_shape = (tuple(input_shape)
                            if input_shape is not None else None)
        self.remediation = None
        # Optional ShadowScorer (obs.quality.shadow): the dispatch
        # OFFERS every answered query; the scorer samples, queues, and
        # re-scores off the hot path.  None (the default) keeps the
        # serving path and every emitted stream byte-identical to a
        # shadow-free build (pinned by tests/test_quality.py).
        self.shadow = None
        # Hot-swap state (serve/hotswap.py): count of engine-tier
        # republishes, and whether a re-warm has made the window rows'
        # compiles_after_warmup key EXPLICIT (present even at zero) so
        # the post-warmup-compile watchdog can observe recovery — clean
        # never-remediated runs keep the absent-when-zero contract.
        self.swaps = 0  # guarded-by: _lock
        self._explicit_compile_key = False
        # Durable-ingest state (docs/RESILIENCE.md §Durability): all
        # None/zero until ``attach_wal`` arms the path, so a WAL-less
        # server keeps its pre-PR behavior and summary shape.  The
        # ingest lock serializes record application, checkpointing, and
        # the hot-swap flip — ``_lock`` is only ever taken INSIDE it
        # (never the reverse), so the two can nest without deadlock.
        self.wal = None
        self._ingest_lock = threading.Lock()
        self._ingest_apply: Optional[Callable[[Dict[str, Any]], None]] = None
        self._checkpoint_fn: Optional[Callable[[int], Optional[str]]] = None
        self._checkpoint_every = 0
        self.ingest_batches = 0  # guarded-by: _lock
        self.ingest_vectors = 0  # guarded-by: _lock
        self.ingest_errors = 0  # guarded-by: _lock
        self._ingest_watermark = 0  # guarded-by: _ingest_lock
        self._ckpt_watermark = 0  # guarded-by: _ingest_lock
        self._ingest_since_ckpt = 0  # guarded-by: _ingest_lock
        # Multi-tenant map (serve/tenants.py): empty until
        # ``enable_tenants`` installs it, so a single-tenant server
        # keeps every pre-PR behavior and stream byte-identical.  When
        # armed, each query/ingest record must carry a registered
        # "tenant" id; counters, freshness, quota, admission, shadow,
        # and ingest split per entry while the replica tier, front
        # ends, and compiled programs stay shared.
        self.tenants: Dict[str, Any] = {}
        self._replica_idx: Dict[str, int] = {}
        self.replicaset = ReplicaSet(
            engines, batcher_cfg, self._replica_dispatch,
            span_fn=self._span, on_batch=self._record_batch,
            on_pick=self._qtrace_pick if qtrace is not None else None,
        )
        self._lat = collections.deque(maxlen=max(cfg.latency_window, 1))
        # THIS window's latencies, cleared at each emission: window rows
        # report the window they describe (a live p99 watchdog must see
        # recovery when behavior recovers — a 1024-deep running ring
        # would keep an old incident's tail in every later row);
        # the drain/healthz percentiles still read the smoothed ring.
        self._window_lat: list = []
        # Request threads, the dispatcher, and the hot-swap path all
        # touch the counters and the published engine tier: mutations
        # hold the lock (enforced by `staticcheck`, docs/STATICCHECK.md;
        # the swap attrs engine/engines/freshness/swaps are annotated
        # where cmd_serve first publishes them, in ``swap_engines``).
        self._lock = threading.Lock()
        self.queries = 0  # guarded-by: _lock
        self.answered = 0  # guarded-by: _lock
        self.errors = 0  # guarded-by: _lock
        # Errors refused BEFORE admission (bad JSON, unknown tenant):
        # counted in ``errors`` but never in ``queries``, so the drop
        # residual must exclude them or a refusal reads as a negative
        # drop count.
        self.errors_refused = 0  # guarded-by: _lock
        self._window_t0 = time.perf_counter()
        self._window_n = 0
        self._last_batch: Dict[str, Any] = {}
        # Tracer event-index cursor for the per-window latency
        # decomposition (obs.perf.decompose): each emitted window reads
        # only the spans appended since the previous one (appends
        # happen at span END, so a span in flight across the boundary
        # lands in the next window instead of vanishing), and the read
        # is O(window), never a full-buffer rescan under the tracer
        # lock.  The cursor's read-advance is guarded by its own lock:
        # window emissions run on whichever request thread crossed the
        # window threshold (deliberately outside self._lock), and two
        # concurrent emissions reading the same stale cursor would
        # double-count one window's spans into both splits.  Both
        # cursors baseline at CONSTRUCTION time: cmd_serve warms the
        # engine first, and warmup's serve/topk spans are XLA compiles
        # — seconds-long outliers that would otherwise own the first
        # window's and the drain summary's p99.
        tracer = self._tracer()
        baseline = tracer.num_events if tracer is not None else 0
        self._events_start_idx = baseline
        self._window_events_idx = baseline
        self._window_events_lock = threading.Lock()

    @property
    def batcher(self):
        """The primary replica's batcher (the pre-replica-tier attribute;
        aggregate counters live on ``self.replicaset``)."""
        return self.replicaset.replicas[0].batcher

    def _replica_dispatch(self, replica):
        """Per-replica dispatch wrapper: crash containment around the
        shared answer logic.  The ``serve.replica_crash`` failpoint
        (docs/RESILIENCE.md) kills THIS replica: its in-flight batch —
        and every batch still queued on it — REROUTES to a surviving
        replica (zero client-visible errors), and the router stops
        selecting it.  Only a whole-tier loss fails the work."""

        def dispatch(items: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
            if not replica.alive:
                return self._reroute(replica, items)
            if failpoints.should_fire("serve.replica_crash"):
                replica.alive = False
                log.error("replica %s crashed (injected); %d live "
                          "replica(s) remain — rerouting its work",
                          replica.name, self.replicaset.alive_count)
                return self._reroute(replica, items)
            return self._dispatch(items, engine=replica.engine,
                                  replica=replica.name)

        return dispatch

    def _reroute(self, dead, items: List[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
        """Dispatch a dead replica's batch on a surviving replica's
        engine — the ``serve.replica_crash`` containment promise: a
        replica loss stays invisible to clients while ANY replica
        survives.  Runs on the dead replica's own dispatcher thread
        (replicas share one compiled-program set, so the reroute costs
        no extra compile and never waits on another queue); a
        whole-tier loss raises, failing the batch to error answers.
        Deliberately NOT ``replicaset.pick()``: pick counts a
        whole-tier miss in ``rejected``, and these queries are about to
        be counted in ``errors`` — one query must land in exactly one
        term of the drain invariant."""
        from npairloss_tpu.serve.replicas import ReplicaCrashError

        live = [r for r in self.replicaset.replicas if r.alive]
        if not live:
            raise ReplicaCrashError(
                f"replica {dead.name} is down and no live replica "
                "remains")
        target = min(live, key=lambda r: r.batcher.queue_depth)
        log.warning("rerouting %d quer%s from dead replica %s to %s",
                    len(items), "y" if len(items) == 1 else "ies",
                    dead.name, target.name)
        if self.qtrace is not None:
            # The reroute instant explains the detour in any exemplar
            # that rode it (and the gameday attribution check reads the
            # marker count as the replica-crash evidence).
            self.qtrace.marker("crash_reroute", dead=dead.name,
                               target=target.name, queries=len(items))
        return self._dispatch(items, engine=target.engine,
                              replica=target.name)

    # -- telemetry ---------------------------------------------------------

    def _span(self, name: str, **args):
        if self.telemetry is None:
            return contextlib.nullcontext()
        return self.telemetry.span(name, **args)

    def _record_batch(self, stats: Dict[str, Any]) -> None:
        self._last_batch = stats

    # -- qtrace glue (no-ops unless a QueryTracer is attached) -------------

    def _qtrace_begin(self, rec):
        """Assign a trace id at ingestion; the context rides the record
        itself so the batcher/replica threads need no side channel."""
        if self.qtrace is None or not isinstance(rec, dict):
            return None
        qt = self.qtrace.begin(rec.get("id"))
        rec["_qt"] = qt
        return qt

    def _qtrace_pick(self, item) -> None:
        """Batcher ``on_pick`` hook: the dispatcher pulled this record
        off its replica's admission queue — ``queue_wait`` ends."""
        qt = item.get("_qt") if isinstance(item, dict) else None
        if qt is not None:
            self.qtrace.picked(qt)

    def _qtrace_drop(self, qt, error: bool = False) -> None:
        """A query that will never be answered: counted by the tracer,
        excluded from both aggregation populations (the same population
        the latency rings keep — see ``_record_latency``)."""
        if qt is not None and self.qtrace is not None:
            self.qtrace.drop(qt, error=error)

    def _record_latency(self, seconds: float, qt=None,
                        entry=None) -> None:
        if qt is not None and self.qtrace is not None:
            # Finish the trace BEFORE the window-threshold check so the
            # query that closes a window lands in that window's stage
            # decomposition, mirroring its latency sample below.
            self.qtrace.finish(qt)
        qps, lat_snap = 0.0, None
        with self._lock:
            self._lat.append(seconds * 1e3)
            if self.cfg.metrics_window:
                # One population, two views: a sample enters the
                # smoothed ring AND the window list here or nowhere
                # (dropped/errored queries enter neither) — with
                # windows off the per-window list must stay empty, not
                # accumulate a divergent unbounded copy of the ring
                # (pinned by tests/test_qtrace.py).
                self._window_lat.append(seconds * 1e3)
            if entry is not None:
                # The tenant's own rings: same sample, same population
                # rule — its p99 SLO burns on ITS tail, not the tier's.
                entry.answered += 1
                entry.lat.append(seconds * 1e3)
                if self.cfg.metrics_window:
                    entry.window_lat.append(seconds * 1e3)
            self.answered += 1
            self._window_n += 1
            if (self.cfg.metrics_window
                    and self._window_n >= self.cfg.metrics_window):
                now = time.perf_counter()
                qps = self._window_n / max(now - self._window_t0, 1e-9)
                lat_snap = self._window_lat
                self._window_lat = []
                self._window_t0 = now
                self._window_n = 0
        if lat_snap is not None:
            self._emit_window(qps, lat_snap)

    def _account(self, answer: Dict[str, Any], t0: float,
                 qt=None) -> Dict[str, Any]:
        """Per-answer bookkeeping: an ``{"id", "error"}`` answer (a
        malformed record the dispatch answered individually) counts as
        an error, everything else as an answered query with latency —
        attributed to the answer's tenant in tenant mode (the dispatch
        stamped the id, so no side channel is needed)."""
        entry = (self.tenants.get(answer.get("tenant"))
                 if self.tenants and isinstance(answer, dict) else None)
        if "error" in answer:
            with self._lock:
                self.errors += 1
                if entry is not None:
                    entry.errors += 1
            self._qtrace_drop(qt, error=True)
        else:
            self._record_latency(time.perf_counter() - t0, qt,
                                 entry=entry)
        return answer

    def _percentiles(
        self, lat: Optional[List[float]] = None
    ) -> Dict[str, float]:
        if lat is None:
            lat = list(self._lat)
        if not lat:
            return {"p50_ms": 0.0, "p99_ms": 0.0}
        return {
            "p50_ms": float(np.percentile(lat, 50)),
            "p99_ms": float(np.percentile(lat, 99)),
        }

    def _tracer(self):
        tel = self.telemetry
        return getattr(tel, "tracer", None) if tel is not None else None

    @staticmethod
    def _latency_split(events) -> Dict[str, float]:
        """Per-stage p50/p99 (encode/batch/dispatch/topk/admit) from a
        list of serve/* span events — flattened to
        ``<stage>_p50_ms``/``<stage>_p99_ms`` row keys (the Gemma-
        serving-style latency decomposition, obs.perf.decompose)."""
        from npairloss_tpu.obs.perf.decompose import (
            serve_latency_decomposition,
        )

        split = serve_latency_decomposition(events)
        return {
            f"{stage}_{q}": v
            for stage, row in split.items()
            for q, v in row.items() if q != "count"
        }

    def _window_latency_split(self) -> Dict[str, float]:
        """The current window's split: spans appended (= finished)
        since the last window read, via the tracer's incremental
        cursor.  ``spans_dropped`` surfaces the tracer's max_events cap
        in the row stream itself — a capped tracer means the split has
        silently gone partial, and that must be visible where the
        p50/p99 numbers are read."""
        tracer = self._tracer()
        if tracer is None:
            return {}
        with self._window_events_lock:
            events, self._window_events_idx, dropped = tracer.events_since(
                self._window_events_idx)
        out = self._latency_split(events)
        if dropped:
            out["spans_dropped"] = dropped
        return out

    def _emit_window(self, qps: float, lat: List[float]) -> None:
        """One latency/throughput/queue-depth row per window — the
        serving counterpart of the train loop's display cadence.  The
        counters were snapshot under the lock; the percentile math and
        telemetry/log I/O here run OUTSIDE it so concurrent answer
        accounting never stalls on a window emission."""
        row = {
            "qps": round(qps, 1),
            **{k: round(v, 3) for k, v in self._percentiles(lat).items()},
            "queue_depth": self.replicaset.queue_depth,
            "batches": self.replicaset.batches,
            "rejected": self._rejected_total(),
            **self._window_latency_split(),
            # THIS window's p99 budget decomposition: the dominant
            # stage among its worst queries (absent with qtrace off —
            # the spans_dropped byte-identity contract).
            **(self.qtrace.window_row()
               if self.qtrace is not None else {}),
            **{f"batch_{k}": round(v, 3) if isinstance(v, float) else v
               for k, v in self._last_batch.items()},
        }
        if len(self.engines) > 1:
            # Replica-tier keys only exist on a replicated tier, so a
            # single-replica row stream stays byte-identical to pre-PR
            # (the spans_dropped contract).
            row["replicas_alive"] = self.replicaset.alive_count
        if self.admission is not None and self.admission.sheds:
            row["shed"] = self.admission.sheds
        compiles = self._compiles_after_warmup()
        if compiles or self._explicit_compile_key:
            # The strict guard's counting twin, in-row (the
            # spans_dropped contract: present only when > 0, so clean
            # streams stay byte-identical to pre-PR) — the live-obs
            # post-warmup-compile watchdog reads exactly this key.
            # After a re-warm remediation the key turns EXPLICIT
            # (present at zero): absent-when-zero would starve the
            # watchdog of the good samples resolution requires
            # (silence holds a burning SLO, by design).
            row["compiles_after_warmup"] = compiles
        if self.telemetry is not None and self.telemetry.metrics_enabled:
            try:
                self.telemetry.log("serve", self.answered, row)
            except Exception as e:  # noqa: BLE001 — telemetry is not the run
                log.error("serve metrics emission failed: %s", e)
        log.info("serve window: %s", row)
        if self.tenants:
            self._emit_tenant_windows()

    def _emit_tenant_windows(self) -> None:
        """One tenant-stamped row per tenant that answered this window.
        The ``tenant`` key makes the RegistrySink land every metric on
        labeled series (``serve_p99_ms{tenant="a"}``) — the sample
        streams the per-tenant SLOs burn on — so a noisy tenant's tail
        cannot hide inside the aggregate window row, and a quiet
        tenant emits nothing (no stale gauges)."""
        snaps: List[tuple] = []
        with self._lock:
            for tid in sorted(self.tenants):
                entry = self.tenants[tid]
                lat = entry.take_window()
                if lat:
                    snaps.append((tid, entry, lat))
        for tid, entry, lat in snaps:
            trow = {
                "tenant": tid,
                "queries": len(lat),
                **{k: round(v, 3)
                   for k, v in self._percentiles(lat).items()},
            }
            if entry.quota is not None and entry.quota.sheds:
                trow["quota_sheds"] = entry.quota.sheds
            if entry.admission is not None and entry.admission.sheds:
                trow["shed"] = entry.admission.sheds
            if entry.rejected:
                trow["rejected"] = entry.rejected
            if self.telemetry is not None \
                    and self.telemetry.metrics_enabled:
                try:
                    self.telemetry.log("serve", self.answered, trow)
                except Exception as e:  # noqa: BLE001 — telemetry is not the run
                    log.error("tenant %r metrics emission failed: %s",
                              tid, e)
            log.info("serve tenant window: %s", trow)

    # -- serving core ------------------------------------------------------

    def _dispatch(self, items: List[Dict[str, Any]],
                  engine: Optional[QueryEngine] = None,
                  replica: Optional[str] = None
                  ) -> List[Dict[str, Any]]:
        """Batcher dispatch.  Single-tenant: straight through to the
        core.  Tenant mode: a micro-batch may coalesce queries for
        SEVERAL galleries (the batchers are shared — that is the
        one-tier contract), so the batch splits by tenant id and each
        group dispatches on its tenant's engine for THIS replica; the
        answers reassemble in item order."""
        if not self.tenants:
            return self._dispatch_core(items, engine=engine,
                                       replica=replica)
        ridx = self._replica_idx.get(replica, 0)
        groups: Dict[Any, List[int]] = {}
        for i, rec in enumerate(items):
            tid = rec.get("tenant") if isinstance(rec, dict) else None
            groups.setdefault(tid, []).append(i)
        answers: List[Optional[Dict[str, Any]]] = [None] * len(items)
        for tid, idxs in groups.items():
            entry = self.tenants.get(tid)
            if entry is None:
                # Defensive: submit() already refuses unknown tenants;
                # a record that lost its id between admit and dispatch
                # still answers instead of crashing its co-riders.
                for i in idxs:
                    answers[i] = {"id": items[i].get("id"),
                                  "tenant": tid,
                                  "error": f"unknown tenant {tid!r}"}
                continue
            eng = entry.engines[ridx if ridx < len(entry.engines)
                                else 0]
            group = self._dispatch_core([items[i] for i in idxs],
                                        engine=eng, replica=replica,
                                        entry=entry)
            for i, ans in zip(idxs, group):
                answers[i] = ans
        return answers

    def _dispatch_core(self, items: List[Dict[str, Any]],
                       engine: Optional[QueryEngine] = None,
                       replica: Optional[str] = None,
                       entry=None) -> List[Dict[str, Any]]:
        """Coalesced query records -> per-query answers.  A malformed
        record (missing field, wrong embedding shape, ragged input)
        answers ``{"id", "error"}`` WITHOUT failing its co-riders — one
        hostile client must not degrade unrelated traffic sharing the
        micro-batch.  Raw-'input' records encode as ONE stacked
        dispatch (that is the batcher's whole point), then merge with
        the embedding records for one top-k dispatch.  ``entry`` scopes
        freshness stamps, the shadow offer, and the answers' ``tenant``
        key to one tenant (None = the single-tenant tier)."""
        from npairloss_tpu.serve.engine import ServeCompileError

        if engine is None:
            engine = self.engine
        qts = ([qt for it in items
                if isinstance(it, dict)
                and (qt := it.get("_qt")) is not None]
               if self.qtrace is not None else [])
        # Answers carry their tenant id in tenant mode — the routing
        # evidence bench_check's tenant gate audits (and the key
        # _account uses to attribute errors without a side channel).
        tstamp = ({"tenant": entry.tenant_id}
                  if entry is not None else {})
        if qts:
            # ``batch_assemble`` ends here; everything from this point
            # to the answers — parse, encode, failpoint stalls, the
            # engine call — is the ``dispatch`` stage (score/topk_merge
            # are split back out of it below).
            self.qtrace.dispatch_begin(qts, replica=replica)
        stages: Optional[Dict[str, float]] = {} if qts else None
        if failpoints.should_fire("serve.latency"):
            # Deterministic latency fault (docs/RESILIENCE.md): every
            # query in this batch pays the stall — the p99 spike the
            # live-obs alert lifecycle is tested against.  Sited here
            # (not in the engine) so warmup's dispatches stay fast.
            time.sleep(failpoints.SERVE_LATENCY_FAULT_S)
        dim = engine.index.dim
        answers: List[Optional[Dict[str, Any]]] = [None] * len(items)
        emb_rows: List[tuple] = []  # (item position, (D,) query row)
        enc_rows: List[tuple] = []  # (item position, raw input array)
        for i, rec in enumerate(items):
            try:
                if "embedding" in rec:
                    e = np.asarray(rec["embedding"], np.float32)
                    if e.shape != (dim,):
                        raise ValueError(
                            f"embedding shape {e.shape} does not match "
                            f"gallery dim ({dim},)"
                        )
                    emb_rows.append((i, e))
                elif "input" in rec:
                    enc_rows.append(
                        (i, np.asarray(rec["input"], np.float32))
                    )
                else:
                    raise ValueError(
                        "query record needs an 'embedding' or 'input' field"
                    )
            except Exception as e:  # noqa: BLE001 — answer THIS record
                answers[i] = {"id": rec.get("id"), **tstamp,
                              "error": str(e)}
        if enc_rows:
            try:
                enc = engine.encode(
                    np.stack([x for _, x in enc_rows])
                )
                emb_rows.extend(
                    (i, row) for (i, _), row in zip(enc_rows, enc)
                )
            except ServeCompileError:
                raise  # strict-guard trip is a server fault, fail loudly
            except Exception as e:  # noqa: BLE001 — ragged stack, no model
                for i, _ in enc_rows:
                    answers[i] = {"id": items[i].get("id"), **tstamp,
                                  "error": str(e)}
        t_merge = 0.0
        if emb_rows:
            batch = np.stack([x for _, x in emb_rows])
            # Only thread the stage-clock dict through when tracing is
            # live: engine stand-ins (tests, external adapters) need not
            # grow the kwarg to serve an untraced tier.
            out = (engine.query(batch) if stages is None
                   else engine.query(batch, stages=stages))
            t_asm0 = time.perf_counter()
            fresh = (entry.freshness if entry is not None
                     else self.freshness)
            ages = fresh.ages() if fresh is not None else {}
            for j, (i, _) in enumerate(emb_rows):
                answers[i] = {
                    "id": items[i].get("id"),
                    **tstamp,
                    # Per-answer freshness stamp (ROADMAP item 4): how
                    # old the model/index behind THIS answer is — the
                    # TENANT'S freshness in tenant mode.
                    **ages,
                    "neighbors": [
                        {
                            "rank": r,
                            "row": int(out["rows"][j, r]),
                            "gallery_id": int(out["ids"][j, r]),
                            "label": int(out["labels"][j, r]),
                            "score": round(float(out["scores"][j, r]), 6),
                        }
                        for r in range(out["scores"].shape[1])
                    ],
                }
            # Host-side answer assembly is merge work: it joins the
            # device top-K with labels/ids/freshness into the wire
            # shape, so it lands in ``topk_merge``, not dispatch self.
            t_merge = time.perf_counter() - t_asm0
            shadow = (entry.shadow if entry is not None
                      else self.shadow)
            if shadow is not None:
                # Shadow offer AFTER the answers are built: a hash +
                # bounded put per sampled query, never a wait — the
                # scorer re-scores on its own thread (obs.quality).
                # Tenant mode offers to the TENANT'S scorer, whose
                # oracle is that tenant's gallery.
                try:
                    for j, (i, row) in enumerate(emb_rows):
                        # The raw query row — the oracle re-normalizes
                        # exactly like the serving engine did.
                        shadow.offer(items[i].get("id"), row,
                                     out["rows"][j],
                                     out["scores"][j])
                except Exception as e:  # noqa: BLE001 — shadow must not fail answers
                    log.error("shadow offer failed: %s", e)
        if qts:
            self.qtrace.dispatch_end(
                qts,
                score_us=(stages or {}).get("score_us", 0.0),
                merge_us=((stages or {}).get("merge_us", 0.0)
                          + t_merge * 1e6),
                # Fused probe path: the score/merge clocks came out of
                # ONE Pallas dispatch, so the trace wraps them in a
                # probe_fused span (the stage vocabulary is unchanged).
                fused=getattr(engine, "probe_impl", None) == "fused")
        return answers

    # -- durable ingest (docs/RESILIENCE.md §Durability) --------------------

    def attach_wal(self, wal, apply_fn: Callable[[Dict[str, Any]], None],
                   *, checkpoint_fn: Optional[Callable[[int],
                                                       Optional[str]]] = None,
                   checkpoint_every: int = 0, watermark: int = 0,
                   checkpoint_watermark: int = 0) -> None:
        """Arm the durable-ingest path: ``wal`` takes every record
        BEFORE the ack, ``apply_fn(payload)`` applies a durable record
        to the ingest gallery, and ``checkpoint_fn(watermark)``
        publishes a snapshot covering everything up to ``watermark``
        (returning its path, or None when there was nothing new) —
        after which the server GCs the WAL segments that snapshot
        covers.  ``watermark`` seeds the applied high-water mark (the
        cold-restart replay already happened by the time this is
        called); ``checkpoint_watermark`` seeds the last PUBLISHED
        watermark (the base artifact's)."""
        self.wal = wal
        self._ingest_apply = apply_fn
        self._checkpoint_fn = checkpoint_fn
        self._checkpoint_every = int(checkpoint_every)
        self._ingest_watermark = int(watermark)  # unguarded-ok: attach_wal runs at startup, before run_jsonl/serve threads exist
        self._ckpt_watermark = int(checkpoint_watermark)  # unguarded-ok: startup-only, no concurrent ingest yet

    @property
    def ingest_watermark(self) -> int:
        """The last WAL sequence number applied to the ingest gallery
        (== the last acknowledged ingest; acks happen-after apply)."""
        with self._ingest_lock:
            return self._ingest_watermark

    def _handle_ingest(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        """One ingest record, start to ack: encode -> WAL append ->
        group-commit durability barrier -> apply -> ack.  The ack NEVER
        precedes the fsync covering the record — that ordering is the
        whole durability contract, and the SIGKILL drill's oracle
        assumes it.  Ingest records never enter the query pipeline, so
        the drain invariant's population (queries == answered + errors
        + rejected) is untouched."""
        rid = rec.get("id")
        if self.tenants:
            # Tenant mode: the record routes to its tenant's own WAL +
            # watermark (one durability domain per tenant — a noisy
            # neighbor's ingest burst cannot delay another tenant's
            # checkpoint).
            try:
                entry = self._tenant_entry(rec)
            except UnknownTenantError as e:
                with self._lock:
                    self.ingest_errors += 1
                return {"id": rid, "error": str(e)}
            return self._tenant_ingest(entry, rec)
        if self.wal is None or self._ingest_apply is None:
            with self._lock:
                self.ingest_errors += 1
            return {"id": rid,
                    "error": "ingest requires a WAL (serve --wal-dir)"}
        try:
            body = encode_ingest_body(rec.get("ingest"))
        except (ValueError, TypeError) as e:
            with self._lock:
                self.ingest_errors += 1
            return {"id": rid, "error": f"bad ingest record: {e}"}
        try:
            seq = self.wal.append(body)
            self.wal.wait_durable(seq)
        except Exception as e:  # noqa: BLE001 — the client must hear "not durable"
            with self._lock:
                self.ingest_errors += 1
            log.error("ingest %r failed before durability: %s", rid, e)
            return {"id": rid, "error": f"ingest not durable: {e}"}
        body["seq"] = seq
        with self._ingest_lock:
            self._ingest_apply(body)
            self._ingest_watermark = seq
            self._ingest_since_ckpt += 1
        n = len(body["ids"])
        with self._lock:
            self.ingest_batches += 1
            self.ingest_vectors += n
        return {"id": rid, "ingested": n, "seq": seq}

    def _tenant_ingest(self, entry, rec: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """One tenant's ingest record through ITS durability domain
        (serve/tenants.py TenantIngest): same encode -> WAL -> fsync
        barrier -> apply -> ack ordering as the single-tenant path,
        against the tenant's own WAL and watermark.  Aggregate ingest
        counters still tick, so Σ per-tenant == tier totals."""
        rid = rec.get("id")
        tid = entry.tenant_id
        ing = entry.ingest
        if ing is None:
            with self._lock:
                self.ingest_errors += 1
            return {"id": rid, "tenant": tid,
                    "error": f"tenant {tid!r} ingest requires a WAL "
                             "(serve --wal-dir)"}
        try:
            body = encode_ingest_body(rec.get("ingest"))
        except (ValueError, TypeError) as e:
            ing.note_error()
            with self._lock:
                self.ingest_errors += 1
            return {"id": rid, "tenant": tid,
                    "error": f"bad ingest record: {e}"}
        try:
            seq = ing.commit(body)
        except Exception as e:  # noqa: BLE001 — the client must hear "not durable"
            ing.note_error()
            with self._lock:
                self.ingest_errors += 1
            log.error("tenant %r ingest %r failed before durability: "
                      "%s", tid, rid, e)
            return {"id": rid, "tenant": tid,
                    "error": f"ingest not durable: {e}"}
        n = len(body["ids"])
        with self._lock:
            self.ingest_batches += 1
            self.ingest_vectors += n
        ing.maybe_checkpoint()
        return {"id": rid, "tenant": tid, "ingested": n, "seq": seq}

    def _maybe_checkpoint(self) -> None:
        if (self._checkpoint_fn is None or self._checkpoint_every <= 0):
            return
        with self._ingest_lock:
            due = self._ingest_since_ckpt >= self._checkpoint_every
        if due:
            self.checkpoint_now()

    def checkpoint_now(self) -> Optional[str]:
        """Publish an index snapshot at the current applied watermark,
        then GC the WAL segments it covers — the one place snapshot
        publication and WAL GC read the same sequence number.  Returns
        the published path (None when nothing new was applied or no
        checkpoint sink is attached)."""
        if self._checkpoint_fn is None or self.wal is None:
            return None
        with self._ingest_lock:
            wm = self._ingest_watermark
            if wm <= self._ckpt_watermark:
                return None
            try:
                path = self._checkpoint_fn(wm)
            except Exception as e:  # noqa: BLE001 — a failed publish is not data loss
                log.error("ingest checkpoint at watermark %d failed: %s "
                          "— WAL retains the records", wm, e)
                return None
            self._ckpt_watermark = wm
            self._ingest_since_ckpt = 0
        if path is not None:
            try:
                self.wal.gc(wm)
            except Exception as e:  # noqa: BLE001 — GC is space, not safety
                log.error("wal GC at watermark %d failed: %s", wm, e)
        return path

    def ingest_stats(self) -> Dict[str, Any]:
        """The /healthz + drain ``ingest`` block (present only when a
        WAL is attached — the freshness-JSON contract): counters, the
        two watermarks, and the WAL's own durability stats (including
        the torn-tail counts recovery promised to surface)."""
        with self._ingest_lock:
            wm, ckpt = self._ingest_watermark, self._ckpt_watermark
        with self._lock:
            out: Dict[str, Any] = {
                "batches": self.ingest_batches,
                "vectors": self.ingest_vectors,
                "errors": self.ingest_errors,
            }
        out["watermark"] = wm
        out["checkpoint_watermark"] = ckpt
        try:
            out["wal"] = self.wal.stats() if self.wal is not None else {}
        except Exception as e:  # noqa: BLE001 — stats must not fail health
            out["wal"] = {"error": str(e)}
        return out

    # -- multi-tenant map (serve/tenants.py) --------------------------------

    def enable_tenants(self, entries: Dict[str, Any]) -> None:
        """Install the tenant-keyed serving map — startup-only, like
        ``attach_wal``: one ``TenantEntry`` per tenant id, each holding
        exactly one engine per replica (replica r serves tenant t from
        ``entry.engines[r]``, so the tier's batchers/queues stay
        shared while every tenant answers from its own gallery)."""
        if self.tenants:
            raise ValueError("tenant map already installed")
        entries = dict(entries)
        if not entries:
            raise ValueError("enable_tenants needs >= 1 tenant entry")
        for tid, entry in entries.items():
            if len(entry.engines) != len(self.engines):
                raise ValueError(
                    f"tenant {tid!r} has {len(entry.engines)} "
                    f"engine(s); the replica tier has "
                    f"{len(self.engines)}")
        self.tenants = entries  # unguarded-ok: enable_tenants runs at startup, before serving threads exist
        self._replica_idx = {
            rep.name: i
            for i, rep in enumerate(self.replicaset.replicas)}

    def _tenant_entry(self, record) -> Any:
        """The entry a record routes to (tenant mode only); raises
        :class:`UnknownTenantError` for a missing/unregistered id so
        the caller accounts it as a malformed request."""
        tid = record.get("tenant") if isinstance(record, dict) else None
        entry = self.tenants.get(tid)
        if entry is None:
            raise UnknownTenantError(
                f"unknown tenant {tid!r} (registered: "
                f"{sorted(self.tenants)})")
        return entry

    def swap_tenant_engines(self, tenant_id: str, engines,
                            freshness: Optional[Freshness] = None
                            ) -> None:
        """Atomically republish ONE tenant's engine set — the
        ``swap_engines`` commit point scoped to an entry.  Every other
        tenant's pointers are untouched; in-flight batches finish on
        the engines they started with (the dispatcher resolves
        ``entry.engines`` per batch), so no tenant drops a query.
        The flip holds the tenant's ingest lock (when it has one) so a
        durable-ingest apply never races the republish — the
        single-tenant lock order, per entry."""
        entry = self.tenants.get(tenant_id)
        if entry is None:
            raise UnknownTenantError(
                f"unknown tenant {tenant_id!r} (registered: "
                f"{sorted(self.tenants)})")
        engines = list(engines)
        if len(engines) != len(entry.engines):
            raise ValueError(
                f"tenant {tenant_id!r} swap must preserve the replica "
                f"count: got {len(engines)}, entry has "
                f"{len(entry.engines)}")
        ingest_lock = (entry.ingest.lock if entry.ingest is not None
                       else contextlib.nullcontext())
        with ingest_lock:
            with self._lock:
                entry.engines = engines
                if freshness is not None:
                    entry.freshness = freshness
                entry.swaps += 1
                self.swaps += 1
                generation = self.swaps
        if self.qtrace is not None:
            self.qtrace.marker("hotswap_flip", generation=generation,
                               tenant=tenant_id)
        log.warning(
            "hot-swap %d: tenant %r republished (%s)", generation,
            tenant_id,
            freshness.identity() if freshness else "same identity")

    def _all_engines(self) -> List[QueryEngine]:
        """Every distinct engine behind the tier: the replica anchors
        plus each tenant's sets, deduped by identity (tenant 0's
        engines ARE ``self.engines``) — the population compile
        counters sum over."""
        seen: Dict[int, QueryEngine] = {id(e): e for e in self.engines}
        for entry in self.tenants.values():
            for e in entry.engines:
                seen.setdefault(id(e), e)
        return list(seen.values())

    # -- remediation actuators (docs/RESILIENCE.md §Remediation) -----------

    def swap_engines(self, engines, freshness: Optional[Freshness] = None,
                     prepare: Optional[Callable[[], None]] = None
                     ) -> None:
        """Atomically publish a fresh engine tier — the hot-swap commit
        point (ROADMAP item 3's actuation half).  The caller must have
        built AND WARMED the new primary off the serving path
        (serve/hotswap.py does); here each replica's engine pointer
        flips, so its NEXT batch dispatches on the new engine while any
        in-flight batch finishes on the engine it started with — zero
        dropped queries, zero serving-path compiles.  Freshness flips
        with the tier, so per-answer model/index ages drop at the same
        instant the answers start coming from the new snapshot."""
        engines = list(engines)
        if len(engines) != len(self.engines):
            raise ValueError(
                f"swap must preserve the replica count: got "
                f"{len(engines)}, tier has {len(self.engines)}")
        # The flip runs under the ingest lock so a durable-ingest apply
        # or checkpoint never races the republish (``prepare`` is the
        # hot-swap's chance to reconcile ingest state against the
        # incoming tier's watermark at the same serialization point);
        # WAL-less servers pay one uncontended acquire.
        with self._ingest_lock:
            if prepare is not None:
                prepare()
            with self._lock:
                self.engines = engines
                self.engine = engines[0]
                if freshness is not None:
                    self.freshness = freshness
                self.swaps += 1
            for rep, eng in zip(self.replicaset.replicas, engines):
                rep.engine = eng
        if self.qtrace is not None:
            # The generation-flip instant: answers after this marker
            # come from the new snapshot — a tail spike next to it is
            # swap cost, not load (docs/OBSERVABILITY.md runbook).
            self.qtrace.marker("hotswap_flip", generation=self.swaps)
        log.warning("hot-swap %d: serving tier republished (%s)",
                    self.swaps,
                    freshness.identity() if freshness else "same identity")

    def rewarm(self) -> Dict[str, Any]:
        """Re-warm every padding bucket and reset the tier's
        post-warmup compile counters — the compile-storm remediation
        action.  From here on the window rows carry an EXPLICIT
        ``compiles_after_warmup`` (including 0) so the watchdog sees
        recovery."""
        dt = self.engine.rewarm(self.input_shape)
        for e in self.engines[1:]:
            # Replicas share the primary's programs + signature set;
            # only their counters need the reset.
            e.compiles_after_warmup = 0
        for entry in self.tenants.values():
            # Each tenant's primary re-dispatches its own buckets (a
            # shared signature set makes repeats free); replicas again
            # only reset counters.  rewarm never clears shared
            # signatures, so the loop cannot thrash the cache.
            if entry.engines[0] is not self.engine:
                dt += entry.engines[0].rewarm(self.input_shape)
            for e in entry.engines:
                if e is not entry.engines[0] and e is not self.engine:
                    e.compiles_after_warmup = 0
        self._explicit_compile_key = True
        return {"warmup_s": round(dt, 3)}

    def _rejected_total(self) -> int:
        """Every rejection source, once each: batcher backpressure +
        whole-tier-down + admission sheds — the ``rejected`` term of
        the drain invariant."""
        total = self.replicaset.rejected
        if self.admission is not None:
            total += self.admission.sheds
        for entry in self.tenants.values():
            # Per-tenant fast-rejects (quota + tenant admission) never
            # reach the replicaset or the global controller, so adding
            # them double-counts nothing; backpressure and global sheds
            # were counted above and only ATTRIBUTED to entry.rejected.
            if entry.quota is not None:
                total += entry.quota.sheds
            if entry.admission is not None:
                total += entry.admission.sheds
        return total

    def _compiles_after_warmup(self) -> int:
        # Replicas (and same-geometry tenants) share one signature set,
        # so summing never double-counts a compile; single-engine this
        # is the old value.
        return sum(e.compiles_after_warmup for e in self._all_engines())

    def submit(self, record: Dict[str, Any]):
        """Admit one query record; returns (future, t_submit).  Raises
        :class:`QueueFullError` on backpressure — from a full replica
        queue, a fully-down tier, or the admission controller shedding
        under SLO burn (all counted in ``rejected``)."""
        qt = (record.get("_qt")
              if self.qtrace is not None and isinstance(record, dict)
              else None)
        # Tenant resolution happens BEFORE any counting: an unknown
        # tenant is a malformed request (UnknownTenantError -> errors,
        # like bad JSON), never an admitted-then-shed query.
        entry = self._tenant_entry(record) if self.tenants else None
        if entry is not None and qt is not None:
            qt.tenant = entry.tenant_id
        with self._span("serve/admit"):
            with self._lock:  # HTTP front end submits from many threads
                self.queries += 1
                if entry is not None:
                    entry.queries += 1
            if entry is not None and entry.quota is not None and \
                    not entry.quota.admit():
                # Quota shed: THIS tenant's token bucket ran dry — a
                # per-tenant fast-reject (its neighbors' queues and
                # counters never see the query).
                with self._lock:
                    entry.rejected += 1
                raise QueueFullError(
                    f"quota exceeded for tenant "
                    f"{entry.tenant_id!r}; retry after backoff")
            if entry is not None and entry.admission is not None and \
                    not entry.admission.admit(trace=qt):
                with self._lock:
                    entry.rejected += 1
                raise QueueFullError(
                    f"load shed: tenant {entry.tenant_id!r} SLO "
                    "burning (admission control); retry after backoff")
            if self.admission is not None and \
                    not self.admission.admit(trace=qt):
                if entry is not None:
                    # Tier-wide shed, attributed to the tenant whose
                    # query it refused (sum of per-tenant rejected must
                    # reproduce the aggregate).
                    with self._lock:
                        entry.rejected += 1
                raise QueueFullError(
                    "load shed: SLO burning (admission control); retry "
                    "after backoff")
            if qt is not None:
                # ``admit_wait`` closes BEFORE the enqueue: the record
                # becomes visible to the dispatcher the instant it
                # lands in the queue, and the queue put is the only
                # ordering edge between this thread and ``picked``.
                self.qtrace.admitted(qt)
            try:
                fut = self.replicaset.submit(record)
            except QueueFullError:
                if entry is not None:
                    # Backpressure lands on the submitting tenant too:
                    # counted where replicaset.rejected counts it.
                    with self._lock:
                        entry.rejected += 1
                raise
            return fut, time.perf_counter()

    def handle_many(
        self,
        records: List[Dict[str, Any]],
        timeout: Optional[float] = 60.0,
    ) -> List[Dict[str, Any]]:
        """Blocking multi-query path: admit EVERY record before waiting
        on any, so co-riders from one request coalesce into shared
        micro-batches instead of each paying its own deadline wait."""
        staged: List[Any] = []
        for rec in records:
            qt = self._qtrace_begin(rec)
            try:
                staged.append((rec, *self.submit(rec), qt))
            except UnknownTenantError as e:
                # Malformed request (never admitted): errors, not
                # queries/rejected — the bad-JSON accounting.
                with self._lock:
                    self.errors += 1
                    self.errors_refused += 1
                self._qtrace_drop(qt, error=True)
                staged.append((rec, None, str(e), None))
            except QueueFullError as e:
                # counted in batcher.rejected — NOT also in errors, or
                # the drain invariant queries == answered + errors +
                # rejected double-counts every rejection
                self._qtrace_drop(qt)
                staged.append((rec, None, str(e), None))
        answers = []
        for rec, fut, t0_or_err, qt in staged:
            if fut is None:
                answers.append({"id": rec.get("id"),
                                "error": t0_or_err})
                continue
            try:
                answer = fut.result(timeout=timeout)
            except Exception as e:  # noqa: BLE001 — answer the error
                with self._lock:
                    self.errors += 1
                self._qtrace_drop(qt, error=True)
                answers.append({"id": rec.get("id"), "error": str(e)})
                continue
            answers.append(self._account(answer, t0_or_err, qt))
        return answers

    def handle(self, record: Dict[str, Any],
               timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        """Blocking one-query path (the HTTP front end's per-thread
        call): admit, wait, account latency."""
        return self.handle_many([record], timeout=timeout)[0]

    def _queries_dropped(self) -> int:
        """The drain invariant's residual: admitted queries no term of
        ``answered + errors + rejected`` accounts for.  At drain (all
        batchers closed, every future resolved) a nonzero residual is a
        real drop — a query the tier swallowed; read mid-flight it also
        counts queries still in their batch, which is why the key is
        absent-when-zero unless ``explicit_drops`` asks for the
        measured 0.  Refused-before-admission errors (bad JSON,
        unknown tenant) sit in ``errors`` but never entered
        ``queries``, so they are excluded — a refusal is not a
        negative drop."""
        return (self.queries - self.answered
                - (self.errors - self.errors_refused)
                - self._rejected_total())

    def summary(self) -> Dict[str, Any]:
        dropped = self._queries_dropped()
        return {
            "event": "serve_drain",
            "queries": self.queries,
            "answered": self.answered,
            "errors": self.errors,
            "rejected": self._rejected_total(),
            # Zero-drop evidence (docs/RESILIENCE.md §Gameday): present
            # whenever nonzero, and present AT zero when explicit_drops
            # is on — the gameday zero-drop gate refuses an absent key.
            **({"queries_dropped": dropped}
               if (dropped or self.cfg.explicit_drops) else {}),
            "batches": self.replicaset.batches,
            # Replica/admission state only when the feature is on (the
            # single-replica summary keeps its pre-PR shape).
            **({"replicas": len(self.engines),
                "replicas_alive": self.replicaset.alive_count}
               if len(self.engines) > 1 else {}),
            **({"shed": self.admission.sheds,
                "shedding": (self.admission.shedding
                             or self.admission.forced)}
               if self.admission is not None else {}),
            # Freshness identity + ages (live-obs on or off): what this
            # run was answering from, and how stale it had become.
            **(self.freshness.identity()
               if self.freshness is not None else {}),
            **(self.freshness.ages()
               if self.freshness is not None else {}),
            # Hot-swap count (absent when the tier never swapped) and
            # the last remediation per policy (key absent = policy
            # never fired; block absent = no engine attached — the
            # freshness-JSON contract, docs/RESILIENCE.md §Remediation).
            **({"hot_swaps": self.swaps} if self.swaps else {}),
            **({"remediation": self.remediation.last_by_policy()}
               if self.remediation is not None else {}),
            # Durable-ingest evidence (block absent = no WAL attached —
            # the freshness-JSON contract): counters, watermarks, and
            # the WAL's torn-tail counts, on /healthz and the drain
            # summary alike (docs/RESILIENCE.md §Durability).
            **({"ingest": self.ingest_stats()}
               if self.wal is not None else {}),
            # The online recall estimate (obs.quality): block absent =
            # shadowing off — the freshness-JSON contract again, so a
            # --shadow-rate 0 run keeps its pre-PR summary shape.
            **({"quality": self.shadow.stats()}
               if self.shadow is not None else {}),
            # The per-stage p99 budget decomposition (obs.qtrace):
            # block absent = tracing off — the freshness-JSON contract
            # once more, so an untraced run keeps its pre-PR shape.
            **({"qtrace": self.qtrace.summary_block()}
               if self.qtrace is not None else {}),
            # Per-tenant evidence (serve/tenants.py): one block per
            # tenant — counters, freshness, quota, shed, ingest,
            # quality — absent entirely in single-tenant mode (the
            # freshness-JSON contract), so Σ per-tenant counters can be
            # audited against the aggregates above (bench_check
            # --tenants does).
            **({"tenants": {tid: self.tenants[tid].stats_block()
                            for tid in sorted(self.tenants)}}
               if self.tenants else {}),
            # Errors no tenant row can own (unknown-tenant refusals,
            # bad JSON — never admitted, so never attributed): the
            # explicit remainder that makes the tenant error audit
            # exact — Σ per-tenant errors + this == aggregate errors.
            **({"errors_unattributed":
                self.errors - sum(e.errors
                                  for e in self.tenants.values())}
               if self.tenants else {}),
            **{k: round(v, 3) for k, v in self._percentiles().items()},
            # Whole-run latency split: where an answer's time went,
            # stage by stage (one read at drain, not per window; from
            # the construction-time baseline so warmup compiles never
            # masquerade as serving tail latency).
            **(self._latency_split(
                self._tracer().events_since(self._events_start_idx)[0])
               if self._tracer() is not None else {}),
            # Compile counters are tier-wide sums (replicas — and
            # same-geometry tenants — share one signature set, so sums
            # never double-count and both keys stay mutually consistent
            # — whichever engine took a count must not make
            # after_warmup exceed total).
            **{**self.engine.compile_stats(),
               "compiles_total": sum(e.compiles_total
                                     for e in self._all_engines()),
               "compiles_after_warmup": self._compiles_after_warmup()},
        }

    def healthz(self) -> Dict[str, Any]:
        """The /healthz payload: liveness + the whole-run summary
        (which now carries the freshness identity/ages), enriched with
        per-SLO status and active alerts when a LiveObservatory is
        attached — the JSON shape tests/test_live.py pins."""
        out = {
            "ok": True,
            "draining": self._preempted(),
            **self.summary(),
            # The RESOLVED IVF probe impl (scan/fused — never "auto")
            # behind this tier's answers; absent on a flat tier, where
            # the probe path does not exist (absent-when-off, the
            # freshness-JSON contract).  Survives hot-swap because
            # swap_engines rebuilds from the old EngineConfig.
            **({"probe_impl": pi}
               if (pi := getattr(self.engine, "probe_impl", None))
               is not None else {}),
        }
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        if self.live is not None:
            out.update(self.live.health())
        return out

    def _drain(self) -> Dict[str, Any]:
        """Finish in-flight batches, flush telemetry, return the
        summary record.  Idempotent enough for every exit path."""
        self.replicaset.close(drain=True)
        if self.wal is not None:
            # Final ingest checkpoint: everything acked this run lands
            # in a published snapshot before the process exits, so a
            # clean shutdown leaves nothing for cold-restart replay.
            try:
                self.checkpoint_now()
            except Exception as e:  # noqa: BLE001 — drain must finish
                log.error("drain-time ingest checkpoint failed: %s", e)
        for tid in sorted(self.tenants):
            # Same clean-shutdown promise per tenant's durability
            # domain; one tenant's failed publish must not stop the
            # others' (its WAL keeps the records either way).
            ing = self.tenants[tid].ingest
            if ing is None:
                continue
            try:
                ing.checkpoint_now()
            except Exception as e:  # noqa: BLE001 — drain must finish
                log.error("drain-time tenant %r checkpoint failed: %s",
                          tid, e)
        s = self.summary()
        if self.qtrace is not None and self.qtrace.out_path:
            try:
                self.qtrace.write()
            except Exception as e:  # noqa: BLE001 — the artifact is not the run
                log.error("qtrace artifact write failed: %s", e)
        if self.telemetry is not None:
            with contextlib.suppress(Exception):
                if self.telemetry.metrics_enabled:
                    self.telemetry.log("serve", self.answered, s)
                self.telemetry.flush()
        log.info("serve drain: %s", s)
        return s

    def _preempted(self) -> bool:
        return self.preempt is not None and self.preempt.requested

    # -- stdin/JSONL front end --------------------------------------------

    def run_jsonl(self, in_stream, out_stream) -> int:
        """Serve line-delimited JSON until EOF or preemption; answers go
        out in request order.  Returns the process exit code (0 on EOF,
        EXIT_PREEMPTED after a graceful drain)."""
        self.replicaset.start()
        pending: collections.deque = collections.deque()
        emit_lock = threading.Lock()

        def emit(obj) -> None:
            with emit_lock:
                out_stream.write(json.dumps(obj) + "\n")
                out_stream.flush()

        def flush_ready(block: bool) -> None:
            while pending:
                rec_id, fut, t0, qt = pending[0]
                if not block and not fut.done():
                    return
                try:
                    answer = self._account(fut.result(timeout=120.0),
                                           t0, qt)
                except Exception as e:  # noqa: BLE001
                    with self._lock:
                        self.errors += 1
                    self._qtrace_drop(qt, error=True)
                    answer = {"id": rec_id, "error": str(e)}
                pending.popleft()
                emit(answer)

        # A dedicated reader thread blocks in readline and feeds a
        # queue, so the loop notices a SIGTERM within poll_s even while
        # idle.  (An fd-level select + buffered readline cannot do this
        # safely: readline reads ahead into the stream buffer, and lines
        # stranded there never make the fd readable again — the tail of
        # a burst would sit unanswered until EOF.)
        lines_q: queue.Queue = queue.Queue()
        _eof = object()

        def _read() -> None:
            try:
                for line in iter(in_stream.readline, ""):
                    lines_q.put(line)
            except Exception as e:  # noqa: BLE001 — surface as EOF
                log.warning("jsonl reader: %s", e)
            finally:
                lines_q.put(_eof)

        threading.Thread(target=_read, daemon=True,
                         name="serve-jsonl-reader").start()
        preempted = False
        try:
            eof = False
            while not eof:
                if self._preempted():
                    preempted = True
                    break
                try:
                    line = lines_q.get(timeout=self.cfg.poll_s)
                except queue.Empty:
                    flush_ready(block=False)
                    continue
                if line is _eof:
                    eof = True
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    with self._lock:
                        self.errors += 1
                        self.errors_refused += 1
                    emit({"id": None, "error": f"bad request JSON: {e}"})
                    continue
                if isinstance(rec, dict) and "ingest" in rec:
                    # Durable-ingest path: WAL + fsync barrier BEFORE
                    # the ack, never through the query pipeline (the
                    # drain invariant's population stays query-only).
                    emit(self._handle_ingest(rec))
                    self._maybe_checkpoint()
                    continue
                qt = self._qtrace_begin(rec)
                try:
                    fut, t0 = self.submit(rec)
                    pending.append((rec.get("id"), fut, t0, qt))
                except UnknownTenantError as e:
                    # Malformed request (never admitted): errors, not
                    # queries/rejected — the bad-JSON accounting.
                    with self._lock:
                        self.errors += 1
                        self.errors_refused += 1
                    self._qtrace_drop(qt, error=True)
                    emit({"id": rec.get("id"), "error": str(e)})
                except QueueFullError as e:
                    # counted in batcher.rejected, not errors (drain
                    # invariant: queries == answered + errors + rejected)
                    self._qtrace_drop(qt)
                    emit({"id": rec.get("id"), "error": str(e)})
                flush_ready(block=False)
        finally:
            # Graceful drain on EVERY exit: stop admitting, answer every
            # in-flight query, flush telemetry — zero drops.
            self.replicaset.close(drain=True)
            flush_ready(block=True)
            emit(self._drain())
        # A SIGTERM that lands while the reader is blocked can surface
        # as EOF first (the supervisor closes stdin as it signals);
        # any observed preemption request still means "preempted".
        return EXIT_PREEMPTED if (preempted or self._preempted()) else 0

    # -- localhost HTTP front end -----------------------------------------

    def run_http(self, port: int, host: str = "127.0.0.1") -> int:
        """Serve HTTP until preemption (the only exit path besides an
        error); each request thread batches through the shared core."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route through logging
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, obj) -> None:
                body = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str, ctype: str) -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, server_ref.healthz())
                elif self.path == "/metrics":
                    if server_ref.live is None:
                        self._send(404, {
                            "error": "live observatory not enabled "
                                     "(serve --live-obs)"})
                        return
                    from npairloss_tpu.obs.live import prometheus_text

                    self._send_text(
                        200, prometheus_text(server_ref.live.registry),
                        "text/plain; version=0.0.4")
                else:
                    self._send(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path != "/query":
                    self._send(404, {"error": "unknown path"})
                    return
                if server_ref._preempted():
                    self._send(503, {"error": "draining"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length).decode("utf-8", "replace")
                try:
                    lines = [ln for ln in raw.splitlines() if ln.strip()]
                    recs = [json.loads(ln) for ln in lines]
                except ValueError as e:
                    self._send(400, {"error": f"bad request JSON: {e}"})
                    return
                if not recs:
                    self._send(400, {"error": "empty request"})
                    return
                answers = server_ref.handle_many(recs)
                self._send(200, answers[0] if len(answers) == 1 else answers)

        self.replicaset.start()
        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.timeout = self.cfg.poll_s
        log.info("serving on http://%s:%d (POST /query, GET /healthz)",
                 host, httpd.server_address[1])
        try:
            while not self._preempted():
                httpd.handle_request()
        finally:
            with contextlib.suppress(Exception):
                httpd.server_close()
            self._drain()
        return EXIT_PREEMPTED
