"""SnapshotSwapper — zero-downtime model/index hot-swap under live traffic.

ROADMAP item 3's actuation half, and the flagship remediation action
(docs/RESILIENCE.md §Remediation): the serving tier watches the
training ``snapshot_prefix`` and/or the gallery ``index_prefix``; when
a staleness alert fires (or :meth:`SnapshotSwapper.swap` is called
directly), it

  1. scans for a STRICTLY newer committed artifact — snapshots via
     ``list_snapshots`` + ``validate_snapshot`` (torn/corrupt
     candidates skipped with a logged reason, the resume scan's
     contract), indexes via ``load_newest`` (same skip semantics; an
     incrementally ``add()``-ed gallery arrives as a new atomic commit,
     so the republish is a reference swap, never a half-updated slab);
  2. builds a FRESH engine tier against the new artifacts and warms
     every padding bucket OFF the serving path — the old tier keeps
     answering while the new one compiles (the drain machinery
     generalized to swap: traffic never stops, it just changes engines
     between batches);
  3. publishes atomically via :meth:`RetrievalServer.swap_engines` —
     replicas flip to the new engine at their next batch, in-flight
     batches finish where they started, and the per-answer
     model_age_s/index_age_s visibly drop (the staleness watchdog
     proving the swap is the ci.sh chaos scenario).

Raises :class:`NothingNewerError` when no newer valid artifact exists —
for the remediation engine that is an honest FAILED attempt (a stalled
trainer is an incident the actuator cannot fix), not a silent no-op.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Sequence

from npairloss_tpu.resilience.snapshot import (
    list_snapshots,
    validate_snapshot,
)
from npairloss_tpu.serve.engine import QueryEngine
from npairloss_tpu.serve.index import load_newest
from npairloss_tpu.serve.server import Freshness, RetrievalServer

log = logging.getLogger("npairloss_tpu.serve")


class NothingNewerError(RuntimeError):
    """No committed snapshot/index newer than what is being served."""


class SnapshotSwapper:
    """Watch ``snapshot_prefix``/``index_prefix`` and hot-swap the
    server's engine tier to the newest committed artifacts.

    ``model``/``input_shape`` mirror the engine construction in
    ``cmd_serve`` (None = embedding-only serving, no model to swap);
    the CURRENT identities are always read from ``server.freshness`` at
    swap time, so repeated swaps chain correctly.  ``index_transform``
    is cmd_serve's ``--index-kind`` reconciliation applied to every
    swapped-in index — without it a flat commit would silently demote
    an IVF-serving tier back to the exact scan at the first swap.
    ``swap(alert=None)`` is the remediation-action signature (the alert
    info is logged, not consumed).
    """

    def __init__(
        self,
        server: RetrievalServer,
        mesh=None,
        index_prefix: Optional[str] = None,
        snapshot_prefix: Optional[str] = None,
        model=None,
        input_shape: Optional[Sequence[int]] = None,
        telemetry=None,
        index_transform=None,
    ):
        if not index_prefix and not snapshot_prefix:
            raise ValueError(
                "SnapshotSwapper needs an index_prefix and/or a "
                "snapshot_prefix to watch")
        if snapshot_prefix and model is None:
            raise ValueError(
                "watching snapshot_prefix needs the model (the swap "
                "restores new params INTO it); embedding-only serving "
                "can only watch index_prefix")
        self.server = server
        self.mesh = mesh
        self.index_prefix = index_prefix
        self.snapshot_prefix = snapshot_prefix
        self.model = model
        self.input_shape = (tuple(input_shape)
                            if input_shape is not None else None)
        self.telemetry = telemetry
        self.index_transform = index_transform

    # -- discovery ---------------------------------------------------------

    def _restore_newer(self, fresh: Freshness):
        """(path, restored state) of the newest snapshot strictly newer
        (by step) than the served one that validates AND restores, or
        None.  A candidate whose manifest is fine but whose arrays are
        torn is skipped in favor of the next older still-newer one —
        the resume scan's skip contract, applied to serving (restore
        must happen INSIDE the scan, or one corrupt newest snapshot
        wedges every swap while a good newer-than-served one waits)."""
        if not self.snapshot_prefix:
            return None
        from npairloss_tpu.train import restore_for_inference

        current = fresh.snapshot_step
        for step, path in reversed(list_snapshots(self.snapshot_prefix)):
            if current is not None and step <= current:
                return None  # newest-first: nothing newer remains
            try:
                validate_snapshot(path)
                return path, restore_for_inference(path)
            except Exception as e:  # noqa: BLE001 — skip, try the next
                log.warning("hot-swap: skipping snapshot %s: %s", path, e)
        return None

    @staticmethod
    def _index_is_newer(candidate: str, current: Optional[str]) -> bool:
        # Index commits are named sortably (the build cadence's
        # contract, serve/index.load_newest); a different name that
        # sorts LATER is newer, anything else is not a swap target.
        if current is None:
            return True
        return os.path.basename(candidate) > os.path.basename(current)

    # -- the action --------------------------------------------------------

    def swap(self, alert: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        """Build + warm a new tier off the serving path, then publish.
        Returns the detail dict the remediation audit records; raises
        :class:`NothingNewerError` when there is nothing to swap to."""
        fresh = self.server.freshness or Freshness()
        new_index = None
        index_path = fresh.index_path
        if self.index_prefix:
            found = load_newest(self.index_prefix, mesh=self.mesh)
            if found is not None and self._index_is_newer(
                    found[0], fresh.index_path):
                index_path, new_index = found
                if self.index_transform is not None:
                    # The --index-kind reconciliation the startup path
                    # applied: the serving posture survives the swap.
                    new_index = self.index_transform(new_index)
        snapshot_path = fresh.snapshot_path
        new_state = None
        restored = self._restore_newer(fresh)
        if restored is not None:
            snapshot_path, new_state = restored
        if new_index is None and new_state is None:
            raise NothingNewerError(
                "no committed snapshot/index newer than the served one"
                + (f" (alert {alert.get('alert_id')})" if alert else ""))

        old = self.server.engine
        index = new_index if new_index is not None else old.index
        state = new_state if new_state is not None else old.state
        model = self.model if state is not None else None
        primary = QueryEngine(
            index, old.cfg, model=model, state=state,
            telemetry=self.telemetry,
        )
        warmup_s = primary.warmup(
            self.input_shape if model is not None else None)
        engines = [primary] + [
            QueryEngine(index, old.cfg, model=model, state=state,
                        telemetry=self.telemetry,
                        share_compiled_with=primary)
            for _ in range(len(self.server.engines) - 1)
        ]
        for e in engines[1:]:
            e.warmed = True
        freshness = Freshness.collect(
            index=index, index_path=index_path,
            snapshot_path=snapshot_path if model is not None else None,
        )
        old_wm = int(getattr(old.index, "ingest_watermark", 0))
        new_wm = int(getattr(index, "ingest_watermark", 0))

        def _prepare() -> None:
            # Runs under the server's ingest lock, at the flip itself:
            # the durability watermark the tier answers FROM changes
            # here, and the WAL records above ``new_wm`` stay pending
            # (replayed into the next checkpoint, not into this live
            # tier — a post-warmup in-place add would recompile on the
            # serving path).  Logged so a watermark REGRESSION at swap
            # time is visible evidence, never a silent rewind.
            if old_wm or new_wm:
                log.info(
                    "hot-swap: ingest watermark %d -> %d (WAL records "
                    "above %d remain pending for the next checkpoint)",
                    old_wm, new_wm, new_wm)

        self.server.swap_engines(engines, freshness, prepare=_prepare)
        detail: Dict[str, Any] = {
            "swapped": ([] + (["model"] if new_state is not None else [])
                        + (["index"] if new_index is not None else [])),
            "warmup_s": round(warmup_s, 3),
            **freshness.identity(),
        }
        if self.telemetry is not None:
            self.telemetry.instant("serve/hot_swap", **{
                k: v for k, v in detail.items() if k != "swapped"})
        return detail
