"""SLO-burn-driven admission control — the observatory acting on load.

PR 10's live observatory measures (SLO burn rates, alerts); this module
closes the loop (ROADMAP item 2's "shed load before the pager fires"):
an :class:`AdmissionController` registered as a
``LiveObservatory`` tick listener watches the committed burn state of
the configured SLOs (serve p99, queue saturation by default).  While
any of them burns, the front end SHEDS new queries — fast-reject with
backpressure (the existing ``QueueFullError`` answer path, counted in
the ``rejected`` invariant), so an overload ramp degrades into cheap
rejections instead of collapsing into unbounded queueing — and admits
again when the burn clears.

Hysteresis is the SLO engine's own burn/clear band
(:mod:`npairloss_tpu.obs.live.slo`): the controller adds no second
threshold, so shedding starts exactly when the alert would and stops
exactly when it resolves — one definition of "overloaded".

The one extra mechanism is the **probe trickle**: while shedding, every
``probe_every``-th query is still admitted.  Recovery is only
observable through served latencies — if shedding rejected everything,
the latency stream would go silent, and a silent window HOLDS a burning
SLO (silence is not recovery, by design); the tier would never
readmit.  The trickle keeps a measured pulse flowing so clearing is
reachable (docs/SERVING.md §Admission-control runbook).

Metrics (when built with a registry): gauge ``serve_shedding`` (0/1),
counters ``serve_shed_total`` / ``serve_probe_admitted_total`` — the
overload ci.sh scenario and OBSERVABILITY.md document the wiring.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Optional, Sequence, Tuple

log = logging.getLogger("npairloss_tpu.serve")

DEFAULT_ADMISSION_SLOS = ("serve_p99", "serve_queue_saturation")


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """``slo_names``: which SLOs' burn state gates admission (names
    from the active spec set — the serve watchdog presets by default);
    ``probe_every``: admit one query per this many sheds while
    shedding, so recovery stays observable (0 disables the trickle —
    only safe when another admitted traffic source feeds the SLO's
    metric)."""

    slo_names: Tuple[str, ...] = DEFAULT_ADMISSION_SLOS
    probe_every: int = 8

    def __post_init__(self):
        if not self.slo_names:
            raise ValueError("admission control needs >= 1 SLO name")
        if self.probe_every < 0:
            raise ValueError(
                f"probe_every must be >= 0, got {self.probe_every}")


class AdmissionController:
    """Tick-fed shed/admit gate; thread-safe (submits race ticks).

    Wire with ``live.add_listener(controller.on_statuses)`` and consult
    :meth:`admit` per submitted query.  The burn state only changes on
    COMMITTED evaluator ticks (the same stream that drives alerts), so
    shedding and the pager can never disagree about whether the tier is
    overloaded.
    """

    def __init__(self, cfg: AdmissionConfig = AdmissionConfig(),
                 registry=None):
        self.cfg = cfg
        self.registry = registry
        self.shedding = False
        # Remediation override (docs/RESILIENCE.md §Remediation): while
        # ``forced`` is set by engage(), the gate sheds regardless of
        # the listener-fed burn state — the audited load-shed action,
        # released by the remediation engine when its alert resolves.
        self.forced = False
        self.sheds = 0
        self.probes_admitted = 0
        self._since_probe = 0
        self._lock = threading.Lock()
        if registry is not None:
            registry.set("serve_shedding", 0.0)

    # -- tick listener -----------------------------------------------------

    def on_statuses(self, statuses: Sequence) -> None:
        """LiveObservatory tick listener: recompute the shed state from
        the committed burn flags of the watched SLOs."""
        watched = set(self.cfg.slo_names)
        burning = sorted(
            s.spec.name for s in statuses
            if s.burning and s.spec.name in watched)
        shed = bool(burning)
        with self._lock:
            changed = shed != self.shedding
            self.shedding = shed
            if changed:
                self._since_probe = 0
            gauge = 1.0 if (shed or self.forced) else 0.0
        if self.registry is not None:
            self.registry.set("serve_shedding", gauge)
        if changed and shed:
            log.warning(
                "admission control: SHEDDING load (burning SLOs: %s)",
                ", ".join(burning))
        elif changed:
            log.warning("admission control: burn cleared, admitting")

    # -- the remediation override ------------------------------------------

    def engage(self, _alert=None) -> dict:
        """Force shedding on (idempotent) — the audited ``load_shed``
        remediation action.  The probe trickle still applies, so
        recovery stays observable exactly as under listener-driven
        shedding."""
        with self._lock:
            changed = not self.forced
            self.forced = True
            if changed:
                self._since_probe = 0
        if self.registry is not None:
            self.registry.set("serve_shedding", 1.0)
        if changed:
            log.warning("admission control: load shed ENGAGED "
                        "(remediation)")
        return {"engaged": True}

    def release(self, _alert=None) -> None:
        """Stand the forced shed down — the remediation engine's undo,
        run when the triggering alert resolves.  Listener-driven burn
        shedding (if wired) keeps its own verdict."""
        with self._lock:
            changed = self.forced
            self.forced = False
            still = self.shedding
        if changed and not still and self.registry is not None:
            self.registry.set("serve_shedding", 0.0)
        if changed:
            log.warning("admission control: forced shed released "
                        "(remediation)")

    # -- the gate ----------------------------------------------------------

    def admit(self, trace=None) -> bool:
        """True = admit this query; False = shed it (the caller rejects
        with backpressure and counts it in ``rejected``).  ``trace``
        (optional) is the query's qtrace context: a probe-trickle
        admission stamps it, so an exemplar that was admitted WHILE
        shedding is readable as the deliberate measured pulse it is —
        its tail latency indicts the overload, not the gate."""
        with self._lock:
            if not (self.shedding or self.forced):
                return True
            self._since_probe += 1
            if self.cfg.probe_every and \
                    self._since_probe >= self.cfg.probe_every:
                self._since_probe = 0
                self.probes_admitted += 1
                if trace is not None:
                    trace.probe = True
                if self.registry is not None:
                    self.registry.inc("serve_probe_admitted")
                return True
            self.sheds += 1
        if self.registry is not None:
            self.registry.inc("serve_shed")
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "shedding": self.shedding or self.forced,
                "shed": self.sheds,
                "probes_admitted": self.probes_admitted,
                "slos": list(self.cfg.slo_names),
                **({"forced": True} if self.forced else {}),
            }


def controller_from_args(
    slo_csv: Optional[str],
    registry=None,
    probe_every: int = 8,
) -> AdmissionController:
    """CLI glue: ``--admission-slos "a,b"`` -> a wired controller."""
    names = tuple(
        n.strip() for n in (slo_csv or "").split(",") if n.strip()
    ) or DEFAULT_ADMISSION_SLOS
    return AdmissionController(
        AdmissionConfig(slo_names=names, probe_every=probe_every),
        registry=registry)
