"""IVFIndex — the clustered (inverted-file) approximate gallery index.

The flat :class:`~npairloss_tpu.serve.index.GalleryIndex` scan is
O(N·D) per query — exact, and untenable at the 10^8-row galleries the
ROADMAP north-star implies.  This module is the serving-side answer
(ROADMAP item 2; the TPU-v4 embedding-hardware thesis in PAPERS.md —
retrieval at scale is the workload the hardware exists for): k-means
centroids over the gallery (the SHARED ``ops.kmeans`` implementation —
farthest-point seeding + Lloyd's, identical math to the offline NMI
protocol), a cluster-packed layout, and a probe-top-C query path that
scores only the probed clusters:

  * **Build**: centroids from :func:`ops.kmeans.kmeans_fit` (trained on
    a bounded sample at gallery scale), full assignment streamed via
    :func:`ops.kmeans.assign_to_centroids`, then rows PACKED per
    cluster into a dense ``(KC, cap, D)`` slab (``cap`` = largest
    cluster; short clusters pad with row id -1) plus a parallel
    ``(KC, cap)`` table of ORIGINAL gallery row ids — answers keep the
    flat index's global row numbering, so labels/ids mapping and the
    recall-parity harness need no translation.
  * **Probe** (the engine's jitted path, serve/engine.py): one
    ``(B, KC)`` centroid matmul, ``top_k`` -> C probed clusters per
    query, then a ``lax.scan`` over probes gathering one ``(B, cap, D)``
    cluster slab per step and merging a running top-k — per-query work
    drops from O(N·D) to O((KC + C·cap)·D).
  * **Mesh**: clusters shard over the mesh axis (centroids replicate —
    they are KC·D, tiny); every shard computes the same global probe
    set, gathers only the probed clusters it owns (others mask to
    -inf), and the per-shard top-k candidates merge exactly like the
    flat engine's shard merge.
  * **Scoring dtype**: the cluster-scan matmul can run fp32 (HIGHEST —
    the flat oracle's precision), bf16 (the ~6.7x MXU headroom the ring
    bf16 bench row measured), or int8 with a per-cluster scale
    (max-abs symmetric quantization) — gated by the recall-parity
    harness (tests/test_ivf.py) against the brute-force oracle.
  * **add()**: new rows assign to their nearest EXISTING centroid (no
    re-clustering) and the whole packed layout republishes atomically —
    one reference swap of the :class:`IVFLayout` tuple, so an in-flight
    query reads either the old layout or the new one, never a mix.

Persistence rides the ``GalleryIndex`` commit path (atomic rename +
CRC manifest) under kind ``ivf-index`` with two extra arrays
(``centroids``, ``assign``); load rebuilds the packed layout
deterministically from the assignment instead of re-running k-means.
``--index-kind flat`` remains the recall oracle (docs/SERVING.md
§Approximate index).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.ops.kmeans import assign_to_centroids, kmeans_fit
from npairloss_tpu.serve.index import _KIND_REGISTRY, GalleryIndex

log = logging.getLogger("npairloss_tpu.serve")

IVF_KIND = "ivf-index"

SCORINGS = ("fp32", "bf16", "int8")


class IVFLayout(NamedTuple):
    """One immutable published generation of the device-resident index.

    ``packed``/``rows`` shard over the cluster axis; ``centroids``/
    ``cluster_valid`` replicate.  ``add()`` builds a whole new layout
    and swaps the index's reference — the atomic-republish contract.
    """

    packed: jax.Array        # (KC, cap, D) float32, cluster-sharded
    rows: jax.Array          # (KC, cap) int32 global row ids, -1 = pad
    centroids: jax.Array     # (KC, D) float32, replicated
    cluster_valid: jax.Array  # (KC,) bool, replicated (non-empty, real)
    n_clusters: int          # true (unpadded) cluster count
    cap: int                 # rows per packed cluster slab


@jax.jit
def _to_bf16(packed: jax.Array) -> jax.Array:
    return packed.astype(jnp.bfloat16)


@jax.jit
def _quantize_int8(packed: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-cluster max-abs quantization: (KC, cap, D) f32 ->
    ((KC, cap, D) int8, (KC,) f32 scale).  Sharding follows the input
    (elementwise + per-cluster reductions never cross the cluster
    axis), so the quantized slab lands exactly where the fp32 one
    lives."""
    scale = jnp.max(jnp.abs(packed), axis=(1, 2)) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(packed / scale[:, None, None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


@dataclasses.dataclass
class IVFIndex(GalleryIndex):
    """Clustered gallery index; see the module docstring.

    Build via :meth:`build_ivf` / :meth:`from_gallery` / :meth:`load`,
    never the raw constructor.  The flat device arrays the parent
    places (``emb``/``labels``/``valid``) stay ``None`` — the packed
    layout IS the device residency; host master copies are inherited.
    """

    KIND = IVF_KIND
    ARRAY_NAMES = ("emb", "labels", "ids", "centroids", "assign")

    centroids_host: Optional[np.ndarray] = None  # (kc, D) float32
    assign_host: Optional[np.ndarray] = None     # (N,) int32
    layout: Optional[IVFLayout] = None
    # scoring-dtype variants, keyed by scoring name and TAGGED with the
    # layout generation they derive from ("bf16" -> (layout, slab), ...)
    # — staleness is self-detecting (the tag is compared by identity
    # against the caller's captured layout), so a republish racing a
    # dispatch can never poison another generation's cache.
    _scored: Optional[Dict[str, tuple]] = None
    # The offline recall birth certificate (docs/OBSERVABILITY.md
    # §Quality observatory): :func:`measure_parity`'s recall@K-per-
    # scoring-mode numbers, stamped into the commit manifest at build
    # time so the LIVE shadow-recall gauge has a committed baseline.
    # Preserved through load/re-commit (an ``add()`` re-commit keeps
    # the measurement it was born with — the manifest records when).
    parity: Optional[dict] = None

    # -- construction -----------------------------------------------------

    @classmethod
    def build_ivf(
        cls,
        embeddings: np.ndarray,
        labels: np.ndarray,
        ids: Optional[np.ndarray] = None,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
        normalize: bool = True,
        clusters: int = 0,
        iters: int = 10,
        seed: int = 0,
        train_size: Optional[int] = 131072,
    ) -> "IVFIndex":
        """Cluster + pack extracted embeddings into a served IVF index.

        ``clusters=0`` picks ~sqrt(N) (the classical IVF balance point:
        centroid-scan and cluster-scan cost equalize).  ``train_size``
        bounds the k-means training set; the full gallery only pays the
        streamed assignment pass.
        """
        emb = np.asarray(embeddings, np.float32)
        lab = np.asarray(labels, np.int32).reshape(-1)
        if emb.ndim != 2 or emb.shape[0] != lab.shape[0]:
            raise ValueError(
                f"embeddings {emb.shape} / labels {lab.shape} mismatch"
            )
        if emb.shape[0] == 0:
            raise ValueError("cannot build an empty gallery")
        from npairloss_tpu.serve.index import l2_normalize_rows

        if normalize:
            emb = l2_normalize_rows(emb)
        if ids is None:
            ids = np.arange(emb.shape[0], dtype=np.int64)
        else:
            ids = np.asarray(ids, np.int64).reshape(-1)
            if ids.shape[0] != emb.shape[0]:
                raise ValueError(
                    f"ids {ids.shape} / embeddings {emb.shape} mismatch"
                )
        n = emb.shape[0]
        kc = int(clusters) or max(1, int(round(math.sqrt(n))))
        centroids = kmeans_fit(emb, kc, iters=iters, seed=seed,
                               train_size=train_size)
        assign = assign_to_centroids(emb, centroids)
        idx = cls(
            emb=None, labels=None, valid=None,  # type: ignore
            ids=ids, size=n, mesh=mesh, axis=axis, created=time.time(),
            _host_emb=emb, _host_labels=lab,
            centroids_host=centroids, assign_host=assign,
        )
        idx._place()
        log.info(
            "ivf index built: %d rows -> %d clusters (cap %d, dim %d)",
            n, idx.layout.n_clusters, idx.layout.cap, idx.dim)
        return idx

    @classmethod
    def from_gallery(cls, gallery: GalleryIndex, **build_kw) -> "IVFIndex":
        """Cluster an already-built/loaded flat gallery (shares its host
        arrays — rows are already unit-norm).  The ingest watermark
        rides along: the IVF rebuild contains exactly the rows the flat
        gallery did, so it covers the same WAL prefix — dropping it
        would force a full replay against the converted index."""
        out = cls.build_ivf(
            gallery._host_emb, gallery._host_labels, ids=gallery.ids,
            mesh=gallery.mesh, axis=gallery.axis, normalize=False,
            **build_kw)
        out.ingest_watermark = gallery.ingest_watermark
        return out

    # -- packing / placement ----------------------------------------------

    def _place(self) -> None:
        """Pack rows per cluster and publish a fresh :class:`IVFLayout`.

        The swap at the end is the atomic-republish point: everything
        is assembled off to the side first, then ONE reference
        assignment makes it live — a concurrently-dispatching engine
        (which reads ``self.layout`` exactly once per dispatch) sees
        the old generation or the new one, never halves of both.
        """
        emb = self._host_emb
        assign = self.assign_host
        n, d = emb.shape
        kc = int(self.centroids_host.shape[0])
        g = self.mesh.size if self.mesh is not None else 1
        kc_pad = kc + (-kc) % g
        sizes = np.bincount(assign, minlength=kc)
        # Cap rounds up to the fused probe kernel's sublane alignment
        # (lcm of the fp32/bf16/int8 min tiles), so the Pallas path's
        # per-dispatch tile re-pad is a width-zero no-op — the 1M-row
        # slab is never copied on the hot path.  The extra rows carry
        # the same -1 sentinel as ragged tails and mask identically in
        # both probe impls.
        from npairloss_tpu.ops.pallas_ivf import CAP_ALIGN

        cap = max(int(sizes.max()), 1)
        cap += (-cap) % CAP_ALIGN
        order = np.argsort(assign, kind="stable")
        offsets = np.zeros(kc + 1, np.int64)
        offsets[1:] = np.cumsum(sizes)
        packed = np.zeros((kc_pad, cap, d), np.float32)
        rows = np.full((kc_pad, cap), -1, np.int32)
        sa = assign[order]
        pos = np.arange(n) - offsets[sa]
        packed[sa, pos] = emb[order]
        rows[sa, pos] = order.astype(np.int32)
        cents = np.zeros((kc_pad, d), np.float32)
        cents[:kc] = self.centroids_host
        cvalid = np.zeros(kc_pad, bool)
        cvalid[:kc] = sizes > 0
        if self.mesh is not None:
            # Same declarative table as the flat gallery
            # (parallel.partition.gallery_rules): packed slabs shard
            # over the mesh axis on their cluster dim, centroid tables
            # replicate — one placement source of truth across serve.
            from npairloss_tpu.parallel.partition import (
                gallery_rules,
                match_partition_shardings,
                place_tree,
            )

            tree = {"packed": packed, "rows": rows,
                    "centroids": cents, "cluster_valid": cvalid}
            placed = place_tree(
                tree,
                match_partition_shardings(
                    gallery_rules(self.axis), tree, self.mesh),
            )
            layout = IVFLayout(
                packed=placed["packed"],
                rows=placed["rows"],
                centroids=placed["centroids"],
                cluster_valid=placed["cluster_valid"],
                n_clusters=kc, cap=cap,
            )
        else:
            layout = IVFLayout(
                packed=jax.device_put(jnp.asarray(packed)),
                rows=jax.device_put(jnp.asarray(rows)),
                centroids=jax.device_put(jnp.asarray(cents)),
                cluster_valid=jax.device_put(jnp.asarray(cvalid)),
                n_clusters=kc, cap=cap,
            )
        self.size = n
        if self._scored is None:
            self._scored = {}
        self.layout = layout  # the atomic republish

    def scored_arrays(self, scoring: str,
                      layout: Optional[IVFLayout] = None) -> tuple:
        """(slab, scale-or-None) for the requested scoring dtype against
        ``layout`` (default: the current one) — derived once per layout
        generation and cached.  A dispatch that captured its layout
        MUST pass it in, so every array it scores comes from ONE
        generation even when ``add()`` republishes mid-flight; a stale
        cache entry (tagged with a different generation) is recomputed,
        never served.  ``fp32`` returns the packed slab itself;
        ``bf16`` a half-width cast (the cluster-scan gather moves half
        the bytes); ``int8`` the symmetric per-cluster quantization."""
        if scoring not in SCORINGS:
            raise ValueError(
                f"scoring must be one of {SCORINGS}, got {scoring!r}")
        if layout is None:
            layout = self.layout
        if scoring == "fp32":
            return layout.packed, None
        cached = self._scored.get(scoring)
        if cached is not None and cached[0] is layout:
            return cached[1]
        if scoring == "bf16":
            out = (_to_bf16(layout.packed), None)
        else:
            out = _quantize_int8(layout.packed)
        self._scored[scoring] = (layout, out)
        return out

    # -- incremental add ---------------------------------------------------

    def add(
        self,
        embeddings: np.ndarray,
        labels: np.ndarray,
        ids: Optional[np.ndarray] = None,
        normalize: bool = True,
    ) -> int:
        """Append rows, assigning each to its nearest EXISTING centroid
        (refresh cadence keeps the trained cluster geometry; a drifted
        corpus warrants a rebuild), then republish the packed layout
        atomically.  A grown ``cap`` is a new program signature for the
        engine — one counted recompile, same as the flat path's padded-
        size growth."""
        emb, lab, ids = self._validate_added_rows(
            embeddings, labels, ids, normalize)
        new_assign = assign_to_centroids(emb, self.centroids_host)
        self._host_emb = np.concatenate([self._host_emb, emb])
        self._host_labels = np.concatenate([self._host_labels, lab])
        self.ids = np.concatenate([self.ids, ids])
        self.assign_host = np.concatenate([self.assign_host, new_assign])
        self._place()
        self.created = time.time()
        return self.size

    # -- persistence -------------------------------------------------------

    def _tree(self):
        return {
            "emb": self._host_emb,
            "labels": self._host_labels,
            "ids": self.ids,
            "centroids": self.centroids_host,
            "assign": self.assign_host,
        }

    def _manifest_extra(self) -> dict:
        # Merge the base extras (the ingest watermark) — an IVF commit
        # that dropped the watermark would force a full-WAL replay on
        # every cold restart and block segment GC forever.
        return {
            **super()._manifest_extra(),
            "n_clusters": int(self.centroids_host.shape[0]),
            **({"parity": self.parity} if self.parity else {}),
        }

    @classmethod
    def _from_tree(cls, tree, manifest, mesh, axis) -> "IVFIndex":
        idx = super()._from_tree(tree, manifest, mesh, axis)
        parity = manifest.get("parity")
        if isinstance(parity, dict):
            idx.parity = parity
        idx.centroids_host = np.asarray(tree["centroids"], np.float32)
        idx.assign_host = np.asarray(tree["assign"], np.int32)
        if idx.assign_host.shape[0] != idx.size:
            from npairloss_tpu.resilience.snapshot import (
                SnapshotValidationError,
            )

            raise SnapshotValidationError(
                f"ivf assignment length {idx.assign_host.shape[0]} != "
                f"gallery size {idx.size}")
        return idx

    # -- shape views -------------------------------------------------------

    @property
    def dim(self) -> int:
        return int(self._host_emb.shape[1])

    @property
    def padded_size(self) -> int:
        # The flat arrays are never placed; the meaningful extent is
        # the true row count (compile signatures key on the layout).
        return int(self.size)

    @property
    def n_clusters(self) -> int:
        return int(self.layout.n_clusters)


_KIND_REGISTRY[IVF_KIND] = IVFIndex


# -- recall-parity harness ----------------------------------------------------


def topk_recall(
    approx_rows: np.ndarray,
    exact_rows: np.ndarray,
    k: Optional[int] = None,
) -> float:
    """Recall@K of an approximate answer set against the exact oracle:
    mean over queries of |approx top-K ∩ exact top-K| / K.  ``rows``
    are (B, >=K) global gallery row ids (the engines' ``"rows"``
    output); this is the gate the bf16/int8 scoring modes and every
    probe count must clear (tests/test_ivf.py, the ``ivf_qps_1m``
    bench row's hard floor)."""
    a = np.asarray(approx_rows)
    e = np.asarray(exact_rows)
    if a.shape[0] != e.shape[0]:
        raise ValueError(
            f"query counts differ: {a.shape[0]} vs {e.shape[0]}")
    if a.shape[0] == 0:
        return 1.0
    k = int(k) if k is not None else int(e.shape[1])
    hits = 0
    for i in range(a.shape[0]):
        hits += len(set(a[i, :k].tolist()) & set(e[i, :k].tolist()))
    return hits / float(a.shape[0] * k)


def measure_parity(
    index: IVFIndex,
    probes: int = 8,
    ks: Tuple[int, ...] = (1, 5, 10),
    sample: int = 256,
    scorings: Tuple[str, ...] = SCORINGS,
    seed: int = 0,
) -> dict:
    """The build-time recall birth certificate: recall@K of the probe
    path vs the flat brute-force oracle, per scoring mode, on a bounded
    sample of gallery rows re-used as queries.  Stamped into the IVF
    commit manifest (``manifest["parity"]``) so the LIVE shadow-recall
    gauge (obs.quality.shadow) has a committed baseline to be compared
    against in /healthz and the quality report — the operating-target
    discipline applied to answer quality.

    Single-device and unwarmed engines throughout: one measurement at
    build time, never a serving-path compile."""
    from npairloss_tpu.serve.engine import EngineConfig, QueryEngine
    from npairloss_tpu.serve.index import GalleryIndex

    n = index.size
    ks = tuple(k for k in ks if k <= n)
    if not ks:
        raise ValueError(f"gallery of {n} rows supports none of ks")
    kmax = max(ks)
    m = min(int(sample), n)
    rng = np.random.default_rng(seed)
    rows = rng.choice(n, size=m, replace=False)
    queries = index._host_emb[rows]
    bucket = min(64, m)
    flat = GalleryIndex.build(
        index._host_emb, index._host_labels, ids=index.ids,
        normalize=False)
    oracle = QueryEngine(
        flat, EngineConfig(top_k=kmax, buckets=(bucket,), scoring="fp32"))
    exact = oracle.query(queries, normalize=False)["rows"]
    probes = max(1, min(int(probes), index.n_clusters))
    recall: Dict[str, Dict[str, float]] = {}
    for scoring in scorings:
        engine = QueryEngine(
            index, EngineConfig(top_k=kmax, buckets=(bucket,),
                                probes=probes, scoring=scoring))
        approx = engine.query(queries, normalize=False)["rows"]
        recall[scoring] = {
            f"at_{k}": round(topk_recall(approx, exact, k), 4) for k in ks
        }
    return {
        "probes": probes,
        "sample": m,
        "ks": list(ks),
        "recall": recall,
        "measured_at": time.time(),
    }
