"""MicroBatcher — deadline-bounded query coalescing with backpressure.

Serving traffic arrives one query at a time; the accelerator wants
fixed-shape micro-batches.  The batcher sits between them: callers
``submit()`` individual queries and get a ``Future``; a single
dispatcher thread coalesces queued queries until either the largest
padding bucket is full or the OLDEST queued query's latency deadline
expires, then hands the batch to ``dispatch_fn`` and distributes the
per-query results.

Admission is a BOUNDED queue, modeled on the training pipeline's
``DispatchController`` (pipeline/controller.py): when the engine falls
behind, ``submit`` raises :class:`QueueFullError` immediately —
reject-with-backpressure, never unbounded growth.  The caller (the
server front end) turns that into a rejected-request answer the client
can retry against another replica.

The deadline is measured from the first query's SUBMIT time, so queue
wait counts against it: a query never waits more than ``max_delay_ms``
for co-riders before its batch dispatches (dispatch+compute time is on
top — bound it by warming the engine, docs/SERVING.md).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from npairloss_tpu.resilience import failpoints

log = logging.getLogger("npairloss_tpu.serve")

_STOP = object()


class QueueFullError(RuntimeError):
    """Admission queue at capacity — backpressure, client should retry."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """``max_batch`` is the largest co-ridership (the engine's largest
    padding bucket); ``max_delay_ms`` the added-latency budget a query
    may spend waiting for co-riders; ``max_queue`` the admission bound
    beyond which submits are rejected."""

    max_batch: int = 32
    max_delay_ms: float = 5.0
    max_queue: int = 256

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")


class MicroBatcher:
    """``start()`` -> ``submit(item) -> Future`` -> ``close(drain=...)``.

    ``dispatch_fn(items)`` receives the coalesced list and must return
    one result per item, in order; an exception fails every future in
    the batch (the server answers each with an error record).
    ``on_batch`` (optional) receives a stats dict per dispatched batch;
    ``span_fn`` (optional) is a telemetry ``span(name, **args)``
    factory for ``serve/batch``/``serve/dispatch`` spans; ``on_pick``
    (optional) receives each item the instant the dispatcher pulls it
    off the queue into the forming batch — the queue-wait/assemble
    boundary per-query tracing needs (obs.qtrace), a no-op when unset.
    """

    def __init__(
        self,
        dispatch_fn: Callable[[List[Any]], Sequence[Any]],
        cfg: BatcherConfig = BatcherConfig(),
        span_fn=None,
        on_batch: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_pick: Optional[Callable[[Any], None]] = None,
    ):
        self.cfg = cfg
        self._dispatch_fn = dispatch_fn
        self._span_fn = span_fn
        self._on_batch = on_batch
        self._on_pick = on_pick
        self._q: queue.Queue = queue.Queue(maxsize=cfg.max_queue)
        self._thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        # Serializes the closed-check + enqueue in submit() against
        # close() setting the flag: without it a racing submit can land
        # its item BEHIND the _STOP sentinel, where the dispatcher never
        # sees it and the future hangs until the caller's timeout.
        self._admit_lock = threading.Lock()
        self.batches = 0
        self.dispatched = 0
        self.rejected = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting and shut the dispatcher down.

        ``drain=True`` (the SIGTERM contract): every already-admitted
        query is dispatched and answered before the thread exits — zero
        dropped in-flight queries.  ``drain=False`` fails pending
        futures with :class:`QueueFullError` instead.
        """
        with self._admit_lock:
            # Under the lock no submit is between its closed-check and
            # its enqueue, so every admitted item is already in the
            # queue and the sentinel below is guaranteed to land last.
            self._closed.set()
        if self._thread is None:
            return
        if not drain:
            # Fail whatever is still queued; the sentinel below stops
            # the loop before it can pick more work up.
            pending = []
            with contextlib.suppress(queue.Empty):
                while True:
                    pending.append(self._q.get_nowait())
            for item in pending:
                if item is not _STOP:
                    item[1].set_exception(
                        QueueFullError("batcher closed without drain")
                    )
        # The sentinel lands BEHIND any admitted work, so a draining
        # close processes the whole queue first.
        self._q.put(_STOP)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            log.error("batcher close: dispatcher did not drain in %.1fs",
                      timeout)
        self._thread = None

    # -- admission ---------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    def submit(self, item: Any) -> concurrent.futures.Future:
        """Admit one query; returns its Future.  Raises
        :class:`QueueFullError` when the admission queue is at capacity
        or the batcher is closing."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._admit_lock:
            if self._closed.is_set():
                raise QueueFullError("batcher is closed")
            try:
                self._q.put_nowait((item, fut, time.perf_counter()))
            except queue.Full:
                self.rejected += 1
                raise QueueFullError(
                    f"admission queue full ({self.cfg.max_queue}); retry"
                ) from None
        return fut

    # -- dispatcher --------------------------------------------------------

    def _span(self, name: str, **args):
        if self._span_fn is None:
            return contextlib.nullcontext()
        return self._span_fn(name, **args)

    def _loop(self) -> None:
        delay = max(self.cfg.max_delay_ms, 0.0) / 1e3
        while True:
            try:
                head = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if head is _STOP:
                return
            if failpoints.should_fire("serve.queue_stall"):
                # Deterministic dispatcher stall (docs/RESILIENCE.md):
                # admissions pile up behind the held queue, driving the
                # queue-saturation watchdog and, past max_queue, the
                # QueueFullError backpressure path — without touching
                # the dispatch math.
                time.sleep(failpoints.SERVE_QUEUE_STALL_S)
            if self._on_pick is not None:
                # After the stall, before coalescing: a stalled
                # dispatcher is queue wait, not assemble time.
                self._on_pick(head[0])
            batch = [head]
            deadline = head[2] + delay
            stop_after = False
            with self._span("serve/batch"):
                while len(batch) < self.cfg.max_batch:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        item = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if item is _STOP:
                        stop_after = True
                        break
                    if self._on_pick is not None:
                        self._on_pick(item[0])
                    batch.append(item)
            self._run_batch(batch)
            if stop_after:
                return

    def _run_batch(self, batch) -> None:
        items = [b[0] for b in batch]
        t0 = time.perf_counter()
        try:
            with self._span("serve/dispatch", size=len(items)):
                results = self._dispatch_fn(items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"dispatch_fn returned {len(results)} results for "
                    f"{len(items)} items"
                )
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            for _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
            log.error("batch dispatch failed (%d queries): %s",
                      len(items), e)
            return
        now = time.perf_counter()
        for (_, fut, _), res in zip(batch, results):
            fut.set_result(res)
        self.batches += 1
        self.dispatched += len(items)
        if self._on_batch is not None:
            self._on_batch({
                "size": len(items),
                "dispatch_ms": (now - t0) * 1e3,
                "oldest_wait_ms": (t0 - batch[0][2]) * 1e3,
                "queue_depth": self._q.qsize(),
            })
