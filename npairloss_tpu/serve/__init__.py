"""serve: the embedding-retrieval serving subsystem (docs/SERVING.md).

The online half of the deployment protocol: ``ops/eval_retrieval.py``
reproduces the offline full-gallery evaluation; this package answers
live queries against the same math.  A trained snapshot plus an
extracted gallery become a running service:

  * :mod:`.index` — :class:`GalleryIndex`, the mesh-resident gallery
    (L2-normalized embedding shards + labels/ids), persisted through the
    ``resilience.snapshot`` atomic-commit path (manifest + CRC, torn
    indexes skipped on load);
  * :mod:`.engine` — :class:`QueryEngine`, the jitted query path:
    encode -> normalize -> block-streamed sharded similarity matmul +
    merged ``lax.top_k``, warmed once per padding bucket;
  * :mod:`.ivf` — :class:`IVFIndex`, the clustered (inverted-file)
    approximate index: shared-``ops.kmeans`` centroids, cluster-packed
    layout, probe-top-C query path with fp32/bf16/int8 scoring, atomic
    add-republish — flat stays the recall oracle it is gated against;
  * :mod:`.batcher` — :class:`MicroBatcher`, deadline-bounded query
    coalescing into fixed padding buckets over a bounded admission
    queue (reject-with-backpressure);
  * :mod:`.replicas` — :class:`ReplicaSet`, N engines behind one front
    end: shared compiled programs, least-loaded routing, per-replica
    drain, ``serve.replica_crash`` containment;
  * :mod:`.admission` — :class:`AdmissionController`, SLO-burn-driven
    load shedding (the live observatory acting on load instead of just
    paging), counted in the ``rejected`` invariant;
  * :mod:`.server` — :class:`RetrievalServer`, the stdin/JSONL and
    localhost-HTTP front ends with graceful SIGTERM drain
    (``resilience.preempt`` semantics, exit 75) and per-request
    ``serve/*`` telemetry spans.
"""

from npairloss_tpu.serve.admission import (
    AdmissionConfig,
    AdmissionController,
)
from npairloss_tpu.serve.batcher import (
    BatcherConfig,
    MicroBatcher,
    QueueFullError,
)
from npairloss_tpu.serve.engine import EngineConfig, QueryEngine
from npairloss_tpu.serve.index import GalleryIndex
from npairloss_tpu.serve.ivf import IVFIndex
from npairloss_tpu.serve.replicas import ReplicaCrashError, ReplicaSet
from npairloss_tpu.serve.server import Freshness, RetrievalServer, ServerConfig

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "BatcherConfig",
    "EngineConfig",
    "Freshness",
    "GalleryIndex",
    "IVFIndex",
    "MicroBatcher",
    "QueryEngine",
    "QueueFullError",
    "ReplicaCrashError",
    "ReplicaSet",
    "RetrievalServer",
    "ServerConfig",
]
