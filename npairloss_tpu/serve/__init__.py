"""serve: the embedding-retrieval serving subsystem (docs/SERVING.md).

The online half of the deployment protocol: ``ops/eval_retrieval.py``
reproduces the offline full-gallery evaluation; this package answers
live queries against the same math.  A trained snapshot plus an
extracted gallery become a running service:

  * :mod:`.index` — :class:`GalleryIndex`, the mesh-resident gallery
    (L2-normalized embedding shards + labels/ids), persisted through the
    ``resilience.snapshot`` atomic-commit path (manifest + CRC, torn
    indexes skipped on load);
  * :mod:`.engine` — :class:`QueryEngine`, the jitted query path:
    encode -> normalize -> block-streamed sharded similarity matmul +
    merged ``lax.top_k``, warmed once per padding bucket;
  * :mod:`.batcher` — :class:`MicroBatcher`, deadline-bounded query
    coalescing into fixed padding buckets over a bounded admission
    queue (reject-with-backpressure);
  * :mod:`.server` — :class:`RetrievalServer`, the stdin/JSONL and
    localhost-HTTP front ends with graceful SIGTERM drain
    (``resilience.preempt`` semantics, exit 75) and per-request
    ``serve/*`` telemetry spans.
"""

from npairloss_tpu.serve.batcher import (
    BatcherConfig,
    MicroBatcher,
    QueueFullError,
)
from npairloss_tpu.serve.engine import EngineConfig, QueryEngine
from npairloss_tpu.serve.index import GalleryIndex
from npairloss_tpu.serve.server import Freshness, RetrievalServer, ServerConfig

__all__ = [
    "BatcherConfig",
    "EngineConfig",
    "Freshness",
    "GalleryIndex",
    "MicroBatcher",
    "QueryEngine",
    "QueueFullError",
    "RetrievalServer",
    "ServerConfig",
]
