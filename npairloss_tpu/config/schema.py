"""Typed views over parsed prototxt — the L5/L6 config surface.

Maps the text-format messages of the reference's three config layers onto
the framework's dataclasses:

  * ``NPairLossParameter`` (reference: caffe.proto:3-23, read at
    npair_multi_class_loss.cpp:32-42) -> :class:`NPairLossConfig`;
  * ``SolverParameter`` subset (usage/solver.prototxt:1-17) ->
    :class:`npairloss_tpu.train.solver.SolverConfig`;
  * the net prototxt's data/augmentation/loss layers
    (usage/def.prototxt) -> :class:`NetConfig` with per-phase
    :class:`DataLayerConfig`, :class:`TransformerConfig`, and the loss
    layer's mining config + top names.

Proto defaults are reproduced exactly (margin_ident 0, margin_diff 0,
identsn -1, diffsn -1, regions LOCAL, methods RAND — caffe.proto:4-22).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from npairloss_tpu.config.prototxt import Message, parse_file, parse
from npairloss_tpu.ops.npair_loss import (
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
)

# ---------------------------------------------------------------------------
# NPairLossParameter (caffe.proto:3-23)
# ---------------------------------------------------------------------------

_REGIONS = {e.name: e for e in MiningRegion}
_METHODS = {e.name: e for e in MiningMethod}


def npair_param_to_config(msg: Optional[Message]) -> NPairLossConfig:
    """``npair_loss_param { ... }`` block -> NPairLossConfig.

    Missing fields take the proto defaults (caffe.proto:4-22); enum values
    may appear as bare identifiers (GLOBAL) or their numeric tags (0).
    """
    if msg is None:
        msg = Message()

    def enum(key: str, table, default):
        v = msg.get(key, None)
        if v is None:
            return default
        if isinstance(v, int):
            return type(default)(v)
        try:
            return table[str(v)]
        except KeyError:
            raise ValueError(f"unknown {key} value {v!r}") from None

    return NPairLossConfig(
        margin_ident=float(msg.get("margin_ident", 0.0)),
        margin_diff=float(msg.get("margin_diff", 0.0)),
        identsn=float(msg.get("identsn", -1.0)),
        diffsn=float(msg.get("diffsn", -1.0)),
        ap_mining_region=enum("ap_mining_region", _REGIONS, MiningRegion.LOCAL),
        ap_mining_method=enum("ap_mining_method", _METHODS, MiningMethod.RAND),
        an_mining_region=enum("an_mining_region", _REGIONS, MiningRegion.LOCAL),
        an_mining_method=enum("an_mining_method", _METHODS, MiningMethod.RAND),
    )


# ---------------------------------------------------------------------------
# Solver (usage/solver.prototxt)
# ---------------------------------------------------------------------------


def solver_from_message(msg: Message):
    """SolverParameter text -> (SolverConfig, net_path or None).

    Field names/defaults mirror the Caffe solver contract the reference
    exercises (solver.prototxt:1-17); ``solver_mode`` is accepted and
    ignored (the accelerator is whatever JAX is running on).
    """
    from npairloss_tpu.train.solver import SolverConfig

    defaults = SolverConfig()
    cfg = SolverConfig(
        base_lr=float(msg.get("base_lr", defaults.base_lr)),
        lr_policy=str(msg.get("lr_policy", defaults.lr_policy)),
        gamma=float(msg.get("gamma", defaults.gamma)),
        stepsize=int(msg.get("stepsize", defaults.stepsize)),
        power=float(msg.get("power", defaults.power)),
        stepvalues=tuple(int(v) for v in msg.getlist("stepvalue")),
        momentum=float(msg.get("momentum", defaults.momentum)),
        weight_decay=float(msg.get("weight_decay", defaults.weight_decay)),
        max_iter=int(msg.get("max_iter", defaults.max_iter)),
        display=int(msg.get("display", defaults.display)),
        average_loss=int(msg.get("average_loss", defaults.average_loss)),
        test_iter=int(msg.get("test_iter", defaults.test_iter)),
        test_interval=int(msg.get("test_interval", defaults.test_interval)),
        test_initialization=bool(
            msg.get("test_initialization", defaults.test_initialization)
        ),
        snapshot=int(msg.get("snapshot", defaults.snapshot)),
        snapshot_prefix=str(msg.get("snapshot_prefix", defaults.snapshot_prefix)),
        random_seed=int(msg.get("random_seed", defaults.random_seed)),
    )
    net = msg.get("net", None)
    return cfg, (str(net) if net is not None else None)


def load_solver(path: str):
    return solver_from_message(parse_file(path))


# ---------------------------------------------------------------------------
# Net (usage/def.prototxt)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TransformParam:
    """Caffe ``transform_param`` (def.prototxt:10-16, 40-46)."""

    mirror: bool = False
    crop_size: int = 0
    mean_value: Tuple[float, ...] = ()
    scale: float = 1.0


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """``data_transformer_l_param`` of the DataTransformer layer
    (def.prototxt:69-83): geometric + photometric augmentation."""

    delta1_sigma: float = 0.0
    delta2_sigma: float = 0.0
    delta3_sigma: float = 0.0
    delta4_sigma: float = 0.0
    rotate_angle_scope: float = 0.0
    translation_w_scope: float = 0.0
    translation_h_scope: float = 0.0
    scale_w_scope: float = 1.0
    scale_h_scope: float = 1.0
    h_flip: bool = False
    elastic_transform: bool = False
    amplitude: float = 1.0
    radius: float = 1.0


@dataclasses.dataclass(frozen=True)
class DataLayerConfig:
    """``MultibatchData`` layer (def.prototxt:2-59): the identity-balanced
    batch contract — ids/batch x imgs/id — that the mining statistics
    depend on (SURVEY.md §3.5)."""

    phase: str = "TRAIN"
    root_folder: str = ""
    source: str = ""
    batch_size: int = 0
    shuffle: bool = False
    new_height: int = 0
    new_width: int = 0
    identity_num_per_batch: int = 0
    img_num_per_identity: int = 0
    rand_identity: bool = False
    transform: TransformParam = TransformParam()


@dataclasses.dataclass(frozen=True)
class LossLayerConfig:
    name: str = ""
    bottoms: Tuple[str, ...] = ()
    tops: Tuple[str, ...] = ()
    loss_weights: Tuple[float, ...] = ()
    loss: NPairLossConfig = NPairLossConfig()


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Everything the framework consumes from a def.prototxt."""

    name: str = ""
    data: Dict[str, DataLayerConfig] = dataclasses.field(default_factory=dict)
    transformer: Optional[TransformerConfig] = None
    loss: Optional[LossLayerConfig] = None
    l2_normalize: bool = False
    # Per-parameter ((w_lr_mult, w_decay_mult), (b_lr_mult,
    # b_decay_mult)) from the net's conv `param` blocks, or None when
    # the net declares none.  The reference template trains biases at
    # 2x lr with no decay (usage/def.prototxt:90-97); Caffe scopes this
    # per layer, but the template (like bvlc_googlenet) uses one recipe
    # throughout, so the first declaring layer defines it.
    param_mults: Optional[Tuple[Tuple[float, float],
                                Tuple[float, float]]] = None
    # Set (with param_mults=None) when the net declares two DIFFERENT
    # per-layer recipes (e.g. frozen layers at lr_mult 0 plus a
    # trainable head).  One net-wide recipe is all the solver honors,
    # so TRAINING such a net must fail loudly — but parse-time is too
    # early: inference-only commands (test/extract/parse/eval) don't
    # consume multipliers and must still load the net.  The train path
    # checks this field before stepping (cli.cmd_train).
    param_mults_conflict: Optional[str] = None
    # All layers in file order as raw Messages, for anything not modeled.
    layers: Tuple[Message, ...] = ()


def _phase_of(layer: Message) -> Optional[str]:
    inc = layer.get("include", None)
    if inc is None:
        return None
    phase = inc.get("phase", None)
    return str(phase) if phase is not None else None


def _transform_param(layer: Message) -> TransformParam:
    tp = layer.get("transform_param", None)
    if tp is None:
        return TransformParam()
    return TransformParam(
        mirror=bool(tp.get("mirror", False)),
        crop_size=int(tp.get("crop_size", 0)),
        mean_value=tuple(float(v) for v in tp.getlist("mean_value")),
        scale=float(tp.get("scale", 1.0)),
    )


def _data_layer(layer: Message) -> DataLayerConfig:
    mb = layer.get("multi_batch_data_param", Message())
    return DataLayerConfig(
        phase=_phase_of(layer) or "TRAIN",
        root_folder=str(mb.get("root_folder", "")),
        source=str(mb.get("source", "")),
        batch_size=int(mb.get("batch_size", 0)),
        shuffle=bool(mb.get("shuffle", False)),
        new_height=int(mb.get("new_height", 0)),
        new_width=int(mb.get("new_width", 0)),
        identity_num_per_batch=int(mb.get("identity_num_per_batch", 0)),
        img_num_per_identity=int(mb.get("img_num_per_identity", 0)),
        rand_identity=bool(mb.get("rand_identity", False)),
        transform=_transform_param(layer),
    )


def _transformer_layer(layer: Message) -> TransformerConfig:
    tp = layer.get("data_transformer_l_param", Message())
    return TransformerConfig(
        delta1_sigma=float(tp.get("delta1_sigma", 0.0)),
        delta2_sigma=float(tp.get("delta2_sigma", 0.0)),
        delta3_sigma=float(tp.get("delta3_sigma", 0.0)),
        delta4_sigma=float(tp.get("delta4_sigma", 0.0)),
        rotate_angle_scope=float(tp.get("rotate_angle_scope", 0.0)),
        translation_w_scope=float(tp.get("translation_w_scope", 0.0)),
        translation_h_scope=float(tp.get("translation_h_scope", 0.0)),
        scale_w_scope=float(tp.get("scale_w_scope", 1.0)),
        scale_h_scope=float(tp.get("scale_h_scope", 1.0)),
        h_flip=bool(tp.get("h_flip", False)),
        elastic_transform=bool(tp.get("elastic_transform", False)),
        amplitude=float(tp.get("amplitude", 1.0)),
        radius=float(tp.get("radius", 1.0)),
    )


def _loss_layer(layer: Message) -> LossLayerConfig:
    return LossLayerConfig(
        name=str(layer.get("name", "")),
        bottoms=tuple(str(b) for b in layer.getlist("bottom")),
        tops=tuple(str(t) for t in layer.getlist("top")),
        loss_weights=tuple(float(w) for w in layer.getlist("loss_weight")),
        loss=npair_param_to_config(layer.get("npair_loss_param", None)),
    )


def net_from_message(msg: Message) -> NetConfig:
    layers = tuple(msg.getlist("layer"))
    data: Dict[str, DataLayerConfig] = {}
    transformer: Optional[TransformerConfig] = None
    loss: Optional[LossLayerConfig] = None
    l2_normalize = False
    param_mults = None
    param_mults_conflict = None
    for layer in layers:
        ltype = str(layer.get("type", ""))
        if ltype == "MultibatchData":
            d = _data_layer(layer)
            data[d.phase] = d
        elif ltype == "DataTransformer":
            transformer = _transformer_layer(layer)
        elif ltype == "L2Normalize":
            l2_normalize = True
        elif ltype == "NPairMultiClassLoss":
            loss = _loss_layer(layer)
        lm = _layer_param_mults(layer)
        if lm is not None:
            if (param_mults_conflict is None and param_mults is not None
                    and lm != param_mults):
                # One net-wide recipe is an approximation (Caffe scopes
                # param blocks per layer); two DIFFERENT recipes in one
                # net (e.g. a frozen trunk + trainable head) cannot be
                # honored.  Recorded (not raised) so inference-only
                # commands still load the net; the train path fails
                # loudly on this field rather than train silently wrong.
                param_mults_conflict = (
                    "net declares conflicting param lr/decay multipliers"
                    f" ({param_mults} vs {lm} at layer "
                    f"{str(layer.get('name', '?'))!r}); per-layer "
                    "multipliers beyond one net-wide recipe are not "
                    "supported for training"
                )
            param_mults = lm
    if param_mults_conflict is not None:
        param_mults = None
    return NetConfig(
        name=str(msg.get("name", "")),
        data=data,
        transformer=transformer,
        loss=loss,
        l2_normalize=l2_normalize,
        param_mults=param_mults,
        param_mults_conflict=param_mults_conflict,
        layers=layers,
    )


def _layer_param_mults(layer: Message):
    """((w_lr, w_decay), (b_lr, b_decay)) from a layer's two ``param``
    blocks (weight blob then bias blob, Caffe's positional order —
    usage/def.prototxt:90-97), else None.  Legacy string-valued
    ``param`` entries (blob name sharing) are ignored."""
    blocks = [b for b in layer.getlist("param") if isinstance(b, Message)]
    if len(blocks) != 2:
        return None

    def mults(b: Message) -> Tuple[float, float]:
        return (float(b.get("lr_mult", 1.0)),
                float(b.get("decay_mult", 1.0)))

    return (mults(blocks[0]), mults(blocks[1]))


def load_net(path: str) -> NetConfig:
    return net_from_message(parse_file(path))


def net_from_text(text: str) -> NetConfig:
    return net_from_message(parse(text))
