"""Prototxt-compatible configuration front-end (SURVEY.md §5.6, §7.4)."""

from npairloss_tpu.config.prototxt import (
    Message,
    PrototxtParseError,
    dumps,
    parse,
    parse_file,
)
from npairloss_tpu.config.schema import (
    DataLayerConfig,
    LossLayerConfig,
    NetConfig,
    TransformParam,
    TransformerConfig,
    load_net,
    load_solver,
    net_from_message,
    net_from_text,
    npair_param_to_config,
    solver_from_message,
)

__all__ = [
    "Message",
    "PrototxtParseError",
    "dumps",
    "parse",
    "parse_file",
    "DataLayerConfig",
    "LossLayerConfig",
    "NetConfig",
    "TransformParam",
    "TransformerConfig",
    "load_net",
    "load_solver",
    "net_from_message",
    "net_from_text",
    "npair_param_to_config",
    "solver_from_message",
]
