"""Binary .caffemodel reader/writer (the weight-migration format).

A reference user's primary asset is a trained ``.caffemodel`` — a
binary-protobuf ``NetParameter`` holding per-layer weight blobs.  This
module implements the minimal wire-format subset needed to read and
write those files WITHOUT a protobuf runtime or the full caffe.proto
(the text-format front-end is ``config/prototxt.py``; this is its binary
sibling).

Supported schema subset (field numbers from the public caffe.proto):

    NetParameter:    name=1 (string), layer=100 (LayerParameter,
                     repeated), layers=2 (V1LayerParameter, repeated)
    LayerParameter:  name=1 (string), type=2 (string),
                     blobs=7 (BlobProto, repeated)
    V1LayerParameter:name=4 (string), blobs=6 (BlobProto, repeated)
    BlobProto:       num/channels/height/width=1..4 (old 4-D shape),
                     data=5 (repeated float, packed or unpacked),
                     shape=7 (BlobShape), double_data=9
    BlobShape:       dim=1 (repeated int64, packed or unpacked)

Unknown fields are skipped (a full caffemodel carries layer params,
phase rules, etc. — irrelevant for weight migration).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


# -- wire primitives --------------------------------------------------------


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        raise ValueError("negative varint unsupported")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _skip(buf: memoryview, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == _WIRE_I64:
        return pos + 8
    if wire == _WIRE_LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire == _WIRE_I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire}")


def _fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over a message buffer.

    value is an int for varint fields, a memoryview for LEN fields, and
    raw 4/8-byte memoryviews for fixed-width fields.
    """
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + n]
            pos += n
        elif wire == _WIRE_I32:
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        elif wire == _WIRE_I64:
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire} (field {field})")


# -- BlobProto --------------------------------------------------------------


def _parse_blob(buf: memoryview) -> np.ndarray:
    shape: List[int] = []
    old_shape = {}
    floats: List[np.ndarray] = []
    doubles: List[np.ndarray] = []
    for field, wire, val in _fields(buf):
        if field == 7 and wire == _WIRE_LEN:  # BlobShape
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == _WIRE_LEN:  # packed int64 dims
                    p = 0
                    while p < len(v2):
                        d, p = _read_varint(v2, p)
                        shape.append(d)
                elif f2 == 1 and w2 == _WIRE_VARINT:  # unpacked dim
                    shape.append(v2)
        elif field == 5:  # float data
            if wire == _WIRE_LEN:  # packed
                floats.append(np.frombuffer(bytes(val), dtype="<f4"))
            elif wire == _WIRE_I32:  # unpacked
                floats.append(np.frombuffer(bytes(val), dtype="<f4"))
        elif field == 9:  # double data
            if wire == _WIRE_LEN:
                doubles.append(np.frombuffer(bytes(val), dtype="<f8"))
            elif wire == _WIRE_I64:
                doubles.append(np.frombuffer(bytes(val), dtype="<f8"))
        elif field in (1, 2, 3, 4) and wire == _WIRE_VARINT:
            old_shape[field] = val
    if doubles:
        data = np.concatenate(doubles).astype(np.float32)
    elif floats:
        data = np.concatenate(floats)
    else:
        data = np.zeros((0,), np.float32)
    if not shape and old_shape:
        shape = [old_shape.get(k, 1) for k in (1, 2, 3, 4)]
    if shape:
        data = data.reshape(shape)
    return data


def _write_blob(arr: np.ndarray) -> bytes:
    out = bytearray()
    # shape = 7 (BlobShape with packed dims)
    dims = bytearray()
    for d in arr.shape:
        _write_varint(dims, int(d))
    inner = bytearray()
    _write_varint(inner, (1 << 3) | _WIRE_LEN)
    _write_varint(inner, len(dims))
    inner += dims
    _write_varint(out, (7 << 3) | _WIRE_LEN)
    _write_varint(out, len(inner))
    out += inner
    # data = 5 (packed floats)
    payload = np.ascontiguousarray(arr, dtype="<f4").tobytes()
    _write_varint(out, (5 << 3) | _WIRE_LEN)
    _write_varint(out, len(payload))
    out += payload
    return bytes(out)


# -- NetParameter -----------------------------------------------------------


def parse_caffemodel(data: bytes) -> Dict[str, List[np.ndarray]]:
    """{layer_name: [blob arrays]} from .caffemodel bytes.

    Reads both the modern ``layer`` (field 100) and legacy ``layers``
    (field 2, V1LayerParameter) encodings; layers without blobs (data,
    loss, pooling...) are omitted.
    """
    buf = memoryview(data)
    out: Dict[str, List[np.ndarray]] = {}
    for field, wire, val in _fields(buf):
        if wire != _WIRE_LEN or field not in (2, 100):
            continue
        name_field = 1 if field == 100 else 4
        blob_field = 7 if field == 100 else 6
        name = None
        blobs: List[np.ndarray] = []
        for f2, w2, v2 in _fields(val):
            if f2 == name_field and w2 == _WIRE_LEN:
                name = bytes(v2).decode("utf-8")
            elif f2 == blob_field and w2 == _WIRE_LEN:
                blobs.append(_parse_blob(v2))
        if name and blobs:
            out[name] = blobs
    return out


def parse_solverstate(data: bytes) -> Dict[str, object]:
    """Decode ``.solverstate`` bytes (Caffe's optimizer snapshot — the
    file ``caffe train --snapshot`` resumes from; the reference's solver
    writes one next to each .caffemodel, solver.prototxt:15-16).

    SolverState wire layout (public Caffe proto): ``iter``=1 (varint),
    ``learned_net``=2 (string path of the paired .caffemodel),
    ``history``=3 (repeated BlobProto — SGD momentum, one blob per
    learnable parameter in net order), ``current_step``=4 (varint).
    Returns {"iter", "learned_net", "history": [np.ndarray],
    "current_step"}.
    """
    buf = memoryview(data)
    out: Dict[str, object] = {
        "iter": 0, "learned_net": "", "history": [], "current_step": 0,
    }
    for field, wire, val in _fields(buf):
        if field == 1 and wire == _WIRE_VARINT:
            out["iter"] = int(val)
        elif field == 2 and wire == _WIRE_LEN:
            out["learned_net"] = bytes(val).decode("utf-8")
        elif field == 3 and wire == _WIRE_LEN:
            out["history"].append(_parse_blob(val))
        elif field == 4 and wire == _WIRE_VARINT:
            out["current_step"] = int(val)
    return out


def write_solverstate(
    iteration: int,
    history: List[np.ndarray],
    current_step: int = 0,
    learned_net: str = "",
) -> bytes:
    """Serialize optimizer state as ``.solverstate`` bytes — the inverse
    of :func:`parse_solverstate`, so a run trained here can be resumed
    by a Caffe stack (and for round-trip tests)."""
    out = bytearray()
    _write_varint(out, (1 << 3) | _WIRE_VARINT)
    _write_varint(out, int(iteration))
    if learned_net:
        nm = learned_net.encode("utf-8")
        _write_varint(out, (2 << 3) | _WIRE_LEN)
        _write_varint(out, len(nm))
        out += nm
    for arr in history:
        payload = _write_blob(np.asarray(arr))
        _write_varint(out, (3 << 3) | _WIRE_LEN)
        _write_varint(out, len(payload))
        out += payload
    _write_varint(out, (4 << 3) | _WIRE_VARINT)
    _write_varint(out, int(current_step))
    return bytes(out)


def write_caffemodel(
    layers: Dict[str, List[np.ndarray]], net_name: str = "npairloss_tpu"
) -> bytes:
    """Serialize {layer_name: [blobs]} as modern-layer caffemodel bytes.

    The inverse of :func:`parse_caffemodel` — used by the export tool
    (deploy a trunk trained here back into a Caffe stack) and by the
    round-trip tests.
    """
    out = bytearray()
    nm = net_name.encode("utf-8")
    _write_varint(out, (1 << 3) | _WIRE_LEN)
    _write_varint(out, len(nm))
    out += nm
    for name, blobs in layers.items():
        layer = bytearray()
        nb = name.encode("utf-8")
        _write_varint(layer, (1 << 3) | _WIRE_LEN)
        _write_varint(layer, len(nb))
        layer += nb
        for arr in blobs:
            payload = _write_blob(np.asarray(arr))
            _write_varint(layer, (7 << 3) | _WIRE_LEN)
            _write_varint(layer, len(payload))
            layer += payload
        _write_varint(out, (100 << 3) | _WIRE_LEN)
        _write_varint(out, len(layer))
        out += layer
    return bytes(out)
