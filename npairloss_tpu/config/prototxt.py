"""Protobuf text-format parser — the config front-end's foundation.

The reference is configured end-to-end in protobuf text format: layer
params via the ``NPairLossParameter`` extension field 8866720
(reference: caffe.proto:2), net topology in usage/def.prototxt, solver
hyperparameters in usage/solver.prototxt.  The north-star requirement is
that those existing prototxt entrypoints keep working, so this module
implements the text-format subset Caffe uses — hand-rolled, no protoc, no
compiled schema:

  * ``key: value`` scalar fields (ints, floats, booleans, quoted strings,
    bare enum identifiers);
  * ``key { ... }`` nested messages (with or without the optional colon);
  * repeated fields: the same key occurring multiple times accumulates
    (e.g. the five ``loss_weight: 1`` entries and three ``mean_value``
    entries of usage/def.prototxt);
  * ``#`` comments to end-of-line, including non-ASCII comment text
    (def.prototxt has Chinese comments);
  * the reference def.prototxt's literal ``.`` ellipsis lines (it is a
    truncated template, SURVEY.md C20) are tolerated at message scope.

The parse result is a :class:`Message`: an ordered multimap that keeps
first-class access to both single (`msg["key"]`) and repeated
(`msg.getlist("key")`) fields, mirroring proto2 semantics where a
singular field takes the LAST occurrence and a repeated field takes all.
"""

from __future__ import annotations

import re
from typing import Any, Iterator, List, Optional, Tuple, Union

Scalar = Union[bool, int, float, str]


class Message:
    """Ordered multimap of parsed fields; values are scalars or Messages."""

    __slots__ = ("_fields",)

    def __init__(self):
        self._fields: List[Tuple[str, Any]] = []

    # -- construction ------------------------------------------------------

    def add(self, key: str, value: Any) -> None:
        self._fields.append((key, value))

    # -- proto2-style access ----------------------------------------------

    def getlist(self, key: str) -> List[Any]:
        """All occurrences of ``key``, in file order (repeated semantics)."""
        return [v for k, v in self._fields if k == key]

    def get(self, key: str, default: Any = None) -> Any:
        """Last occurrence of ``key`` (singular proto2 semantics)."""
        vals = self.getlist(key)
        return vals[-1] if vals else default

    def __getitem__(self, key: str) -> Any:
        vals = self.getlist(key)
        if not vals:
            raise KeyError(key)
        return vals[-1]

    def __contains__(self, key: str) -> bool:
        return any(k == key for k, _ in self._fields)

    def keys(self) -> List[str]:
        seen, out = set(), []
        for k, _ in self._fields:
            if k not in seen:
                seen.add(k)
                out.append(k)
        return out

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(self._fields)

    def __iter__(self) -> Iterator[Tuple[str, Any]]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def to_dict(self) -> dict:
        """Lossy plain-dict view (repeated fields become lists)."""
        out: dict = {}
        for k in self.keys():
            vals = [
                v.to_dict() if isinstance(v, Message) else v
                for v in self.getlist(k)
            ]
            out[k] = vals[0] if len(vals) == 1 else vals
        return out

    def __repr__(self) -> str:
        return f"Message({self.to_dict()!r})"


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<brace>[{}])
  | (?P<colon>:)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_./-]*)
  | (?P<number>[-+]?(?:\.\d+|\d+\.?\d*)(?:[eE][-+]?\d+)?)
  | (?P<ellipsis>\.)
    """,
    re.VERBOSE,
)

_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    '"': '"',
    "'": "'",
    "\\": "\\",
}


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out, i = [], 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _tokenize(text: str) -> List[Tuple[str, str, int]]:
    """Yield (kind, token, line_number); comments stripped first."""
    tokens: List[Tuple[str, str, int]] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        # Strip # comments, but not inside quoted strings.
        stripped, in_str, quote = [], False, ""
        for ch in line:
            if in_str:
                stripped.append(ch)
                if ch == quote and (len(stripped) < 2 or stripped[-2] != "\\"):
                    in_str = False
            elif ch in "\"'":
                in_str, quote = True, ch
                stripped.append(ch)
            elif ch == "#":
                break
            else:
                stripped.append(ch)
        line = "".join(stripped)
        pos = 0
        while pos < len(line):
            if line[pos].isspace() or line[pos] == ",":
                pos += 1
                continue
            m = _TOKEN_RE.match(line, pos)
            if not m:
                raise PrototxtParseError(
                    f"line {lineno}: unexpected character {line[pos]!r}"
                )
            tokens.append((m.lastgroup, m.group(), lineno))
            pos = m.end()
    return tokens


class PrototxtParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


def _coerce_scalar(kind: str, tok: str) -> Scalar:
    if kind == "string":
        return _unquote(tok)
    if kind == "number":
        try:
            return int(tok)
        except ValueError:
            return float(tok)
    # identifier: true/false are proto booleans; anything else stays a
    # string (enum values like GLOBAL, RELATIVE_HARD, phase TRAIN, GPU).
    if tok == "true":
        return True
    if tok == "false":
        return False
    return tok


def parse(text: str) -> Message:
    """Parse prototxt ``text`` into a :class:`Message` tree."""
    tokens = _tokenize(text)
    pos = 0

    def parse_body(depth: int) -> Message:
        nonlocal pos
        msg = Message()
        while pos < len(tokens):
            kind, tok, lineno = tokens[pos]
            if kind == "brace" and tok == "}":
                if depth == 0:
                    raise PrototxtParseError(f"line {lineno}: unmatched '}}'")
                pos += 1
                return msg
            if kind == "ellipsis":
                # Template ellipsis (reference def.prototxt:112-114).
                pos += 1
                continue
            if kind != "ident":
                raise PrototxtParseError(
                    f"line {lineno}: expected field name, got {tok!r}"
                )
            key = tok
            pos += 1
            if pos >= len(tokens):
                raise PrototxtParseError(f"line {lineno}: dangling field {key!r}")
            kind, tok, lineno = tokens[pos]
            if kind == "colon":
                pos += 1
                if pos >= len(tokens):
                    raise PrototxtParseError(
                        f"line {lineno}: missing value for {key!r}"
                    )
                kind, tok, lineno = tokens[pos]
                if kind == "brace" and tok == "{":  # "key: { ... }" form
                    pos += 1
                    msg.add(key, parse_body(depth + 1))
                else:
                    if kind == "brace":
                        raise PrototxtParseError(
                            f"line {lineno}: missing value for {key!r}"
                        )
                    msg.add(key, _coerce_scalar(kind, tok))
                    pos += 1
            elif kind == "brace" and tok == "{":
                pos += 1
                msg.add(key, parse_body(depth + 1))
            else:
                raise PrototxtParseError(
                    f"line {lineno}: expected ':' or '{{' after {key!r}, "
                    f"got {tok!r}"
                )
        if depth != 0:
            raise PrototxtParseError("unexpected end of input: unclosed '{'")
        return msg

    return parse_body(0)


def parse_file(path: str) -> Message:
    with open(path, "r", encoding="utf-8") as f:
        return parse(f.read())


# ---------------------------------------------------------------------------
# Serialization (round-trip support)
# ---------------------------------------------------------------------------


def _format_scalar(v: Scalar) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        # Enum-like bare identifiers round-trip unquoted ONLY via
        # Message-aware callers; a plain string is always quoted here.
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    return repr(v) if isinstance(v, float) else str(v)


_ENUM_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


def dumps(msg: Message, indent: int = 0) -> str:
    """Serialize a Message back to prototxt text (enum heuristics: bare
    ALL_CAPS identifiers are emitted unquoted, matching Caffe style)."""
    pad = "    " * indent
    lines = []
    for key, value in msg.items():
        if isinstance(value, Message):
            lines.append(f"{pad}{key} {{")
            lines.append(dumps(value, indent + 1))
            lines.append(f"{pad}}}")
        elif isinstance(value, str) and _ENUM_RE.match(value):
            lines.append(f"{pad}{key}: {value}")
        else:
            lines.append(f"{pad}{key}: {_format_scalar(value)}")
    return "\n".join(line for line in lines if line)
