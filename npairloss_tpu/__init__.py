"""npairloss_tpu — TPU-native multi-class N-pair metric-learning framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of the
reference Caffe CUDA+MPI layer ``NPairMultiClassLossLayer`` (quziyan/NPairLoss)
and its implied host framework.  This top-level module exports the compute
core: the mined N-pair loss with cross-chip global negative pooling,
in-training retrieval metrics, and L2 normalization.  Subpackages:
``parallel`` (device-mesh plumbing), ``config`` (prototxt front-end),
``data`` (identity-balanced pipeline), ``models`` (embedding zoo),
``train`` (solver loop).
"""

from npairloss_tpu.ops.npair_loss import (
    REFERENCE_CONFIG,
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    npair_loss,
    npair_loss_with_aux,
)
from npairloss_tpu.ops.metrics import retrieval_metrics
from npairloss_tpu.ops.normalize import l2_normalize

__version__ = "0.1.0"

__all__ = [
    "REFERENCE_CONFIG",
    "MiningMethod",
    "MiningRegion",
    "NPairLossConfig",
    "npair_loss",
    "npair_loss_with_aux",
    "retrieval_metrics",
    "l2_normalize",
    "__version__",
]
