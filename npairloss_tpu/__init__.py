"""npairloss_tpu — TPU-native multi-class N-pair metric-learning framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of the
reference Caffe CUDA+MPI layer ``NPairMultiClassLossLayer`` (quziyan/NPairLoss)
and its implied host framework.  This top-level module exports the compute
core: the mined N-pair loss with cross-chip global negative pooling,
in-training retrieval metrics, and L2 normalization.  Subpackages:
``parallel`` (device-mesh plumbing + ring negative pooling), ``config``
(prototxt front-end), ``data`` (identity-balanced pipeline with the
native C++ runtime), ``models`` (embedding zoo), ``train`` (solver
loop), ``utils`` (profiling + numeric debug guards).
"""

import logging as _logging

# Library-logging etiquette: a package must never force output (or emit
# "No handlers could be found" warnings) in an embedding application
# that configured logging its own way.  The CLI adds a real handler only
# when the embedder has not (cli.cmd_train).
_logging.getLogger("npairloss_tpu").addHandler(_logging.NullHandler())

from npairloss_tpu.ops.npair_loss import (
    REFERENCE_CONFIG,
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    npair_loss,
    npair_loss_with_aux,
)
from npairloss_tpu.ops.eval_retrieval import (
    evaluate_embeddings,
    gallery_recall_at_k,
)
from npairloss_tpu.ops.metrics import retrieval_metrics
from npairloss_tpu.ops.normalize import l2_normalize
from npairloss_tpu.ops.pallas_npair import (
    blockwise_npair_loss,
    blockwise_npair_loss_with_aux,
    blockwise_retrieval_metrics,
)

__version__ = "0.1.0"

__all__ = [
    "REFERENCE_CONFIG",
    "MiningMethod",
    "MiningRegion",
    "NPairLossConfig",
    "npair_loss",
    "npair_loss_with_aux",
    "blockwise_npair_loss",
    "blockwise_npair_loss_with_aux",
    "blockwise_retrieval_metrics",
    "retrieval_metrics",
    "gallery_recall_at_k",
    "evaluate_embeddings",
    "l2_normalize",
    "__version__",
]
