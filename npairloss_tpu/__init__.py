"""npairloss_tpu — TPU-native multi-class N-pair metric-learning framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of the
reference Caffe CUDA+MPI layer ``NPairMultiClassLossLayer`` (quziyan/NPairLoss)
and its implied host framework.  This top-level module exports the compute
core: the mined N-pair loss with cross-chip global negative pooling,
in-training retrieval metrics, and L2 normalization.  Subpackages:
``parallel`` (device-mesh plumbing + ring negative pooling), ``config``
(prototxt front-end), ``data`` (identity-balanced pipeline with the
native C++ runtime), ``models`` (embedding zoo), ``train`` (solver
loop), ``utils`` (profiling + numeric debug guards), ``analysis``
(the jax-free staticcheck invariant linter).

The compute-core exports are LAZY (PEP 562): importing the package must
not import jax, so the jax-free entry points — ``python -m
npairloss_tpu staticcheck``, ``watch``, the bench parent, the
bench_check gates — run in a venv with no accelerator stack installed
at all (docs/STATICCHECK.md).  ``from npairloss_tpu import npair_loss``
works exactly as before; it just pays the jax import at first use
instead of at package import.
"""

import logging as _logging

# Library-logging etiquette: a package must never force output (or emit
# "No handlers could be found" warnings) in an embedding application
# that configured logging its own way.  The CLI adds a real handler only
# when the embedder has not (cli.cmd_train).
_logging.getLogger("npairloss_tpu").addHandler(_logging.NullHandler())

__version__ = "0.1.0"

# Export name -> defining submodule, resolved on first attribute access.
_LAZY_EXPORTS = {
    "REFERENCE_CONFIG": "npairloss_tpu.ops.npair_loss",
    "MiningMethod": "npairloss_tpu.ops.npair_loss",
    "MiningRegion": "npairloss_tpu.ops.npair_loss",
    "NPairLossConfig": "npairloss_tpu.ops.npair_loss",
    "npair_loss": "npairloss_tpu.ops.npair_loss",
    "npair_loss_with_aux": "npairloss_tpu.ops.npair_loss",
    "evaluate_embeddings": "npairloss_tpu.ops.eval_retrieval",
    "gallery_recall_at_k": "npairloss_tpu.ops.eval_retrieval",
    "retrieval_metrics": "npairloss_tpu.ops.metrics",
    "l2_normalize": "npairloss_tpu.ops.normalize",
    "blockwise_npair_loss": "npairloss_tpu.ops.pallas_npair",
    "blockwise_npair_loss_with_aux": "npairloss_tpu.ops.pallas_npair",
    "blockwise_retrieval_metrics": "npairloss_tpu.ops.pallas_npair",
}

__all__ = [
    "REFERENCE_CONFIG",
    "MiningMethod",
    "MiningRegion",
    "NPairLossConfig",
    "npair_loss",
    "npair_loss_with_aux",
    "blockwise_npair_loss",
    "blockwise_npair_loss_with_aux",
    "blockwise_retrieval_metrics",
    "retrieval_metrics",
    "gallery_recall_at_k",
    "evaluate_embeddings",
    "l2_normalize",
    "__version__",
]


def __getattr__(name):
    mod_name = _LAZY_EXPORTS.get(name)
    if mod_name is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(mod_name), name)
    globals()[name] = value  # cache: the next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
