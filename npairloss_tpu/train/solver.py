"""The solver loop — the TPU-native counterpart of the Caffe Solver contract.

Reproduces the behavior implied by usage/solver.prototxt (SURVEY.md C21):
step-decayed momentum SGD, ``display``/``average_loss`` sliding-window
monitoring, a TEST phase every ``test_interval`` iterations over
``test_iter`` batches (the reference has no separate eval path — the same
loss+metrics forward runs on eval batches, SURVEY.md §3.4), and
``snapshot``/``snapshot_prefix`` checkpoints (Orbax, async-capable, instead
of Caffe's .caffemodel writes).

The whole training step — model forward, loss with all_gather negative
pooling, backward, optimizer update, in-graph metrics — is ONE jitted
function; multi-chip runs shard the batch over a 1-D ``dp`` mesh with
parameters replicated, collectives compiled into the step by XLA.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import threading
import time
import warnings
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.obs.health import (
    HealthConfig,
    embedding_health,
    pair_hardness_health,
    update_health,
)
from npairloss_tpu.obs.run import RunTelemetry
from npairloss_tpu.ops.metrics import retrieval_metrics
from npairloss_tpu.parallel._compat import shard_map
from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.guard import (
    DivergenceConfig,
    DivergenceError,
    DivergenceGuard,
    RollbackRequest,
)
from npairloss_tpu.resilience.preempt import PreemptionSignal, TrainingPreempted
from npairloss_tpu.resilience.retrying import RetryPolicy, call_with_retry
from npairloss_tpu.resilience.snapshot import (
    SnapshotValidationError,
    commit_snapshot,
    gc_snapshots,
    list_snapshots,
    quarantine_snapshots,
    read_manifest,
    state_checksums,
    validate_snapshot,
    validate_snapshot_wait,
    verify_restored,
    write_manifest,
)
from npairloss_tpu.utils.debug import assert_all_finite, debug_checks_enabled
from npairloss_tpu.ops.npair_loss import NPairLossConfig, npair_loss_with_aux
from npairloss_tpu.train.optim import CaffeSGDState, caffe_sgd, lr_schedule

log = logging.getLogger("npairloss_tpu.solver")


@dataclasses.dataclass
class SolverConfig:
    """Mirror of the SolverParameter subset the reference uses
    (usage/solver.prototxt:1-17); defaults are the shipped values."""

    base_lr: float = 0.001
    lr_policy: str = "step"
    gamma: float = 0.5
    stepsize: int = 10000
    power: float = 1.0
    stepvalues: Sequence[int] = ()
    momentum: float = 0.9
    weight_decay: float = 0.00002
    max_iter: int = 2000000
    display: int = 100
    average_loss: int = 100
    test_iter: int = 2000
    test_interval: int = 2000
    test_initialization: bool = True
    snapshot: int = 5000
    snapshot_prefix: str = "./snap/model_"
    random_seed: int = 0
    # Retention GC (docs/RESILIENCE.md): committed snapshots beyond the
    # newest N are deleted after each successful commit; 0 keeps all
    # (Caffe's behavior — snapshot_max_keep is this framework's own
    # extension, not a SolverParameter field).
    snapshot_max_keep: int = 0
    # Sync-free stepping (docs/PIPELINE.md) — framework extensions, not
    # SolverParameter fields.  ``pipeline`` routes ``train`` through the
    # async loop: device-resident prefetch, per-step scalars accumulated
    # in a device-side ring read back only at display/test/snapshot
    # window boundaries, dispatch depth bounded by ``pipeline_depth``.
    # Default OFF; the pipelined loop is parity-pinned bit-identical to
    # the synchronous one (tests/test_pipeline.py).  ``pipeline_window``
    # caps the steps between host syncs (0 = auto: the smallest active
    # cadence, else 64) — it bounds the divergence guard's staleness.
    pipeline: bool = False
    pipeline_depth: int = 2
    pipeline_window: int = 0
    # Persistent XLA compilation cache directory ("" = off): no process
    # recompiles a program another process already compiled (CLI
    # ``--compile-cache``; pipeline.enable_compile_cache).
    compile_cache: str = ""


class Solver:
    """Train an embedding model with the N-pair loss.

    Args:
      model: a Flax module mapping (images, train=...) -> [N, D] embeddings.
      loss_cfg: mining/margin configuration.
      cfg: solver hyperparameters.
      train_iter/test_iter_fn: iterators yielding (inputs, labels) numpy
        batches (identity-balanced per the MultibatchData contract).
      mesh: optional 1-D device mesh; when given, batches are sharded over
        its axis and the loss pools negatives across all shards.
      top_ks: Recall@k list emitted every step (def.prototxt tops).
    """

    def __init__(
        self,
        model,
        loss_cfg: NPairLossConfig = NPairLossConfig(),
        cfg: Optional[SolverConfig] = None,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
        top_ks: Sequence[int] = (1, 5, 10),
        input_shape: Sequence[int] = (224, 224, 3),
        use_ring: bool = False,
        engine: Optional[str] = None,
        sim_cache: Optional[bool] = None,
        pos_topk: Optional[int] = None,
        matmul_precision: Optional[str] = None,
        precision: Optional[Any] = None,
        partition_rules: Optional[Sequence] = None,
        param_mults: Optional[tuple] = None,
        loss_weight: float = 1.0,
        health: Optional[HealthConfig] = None,
        telemetry: Optional[RunTelemetry] = None,
        divergence: Optional[DivergenceConfig] = None,
        preempt: Optional[PreemptionSignal] = None,
        snapshot_retry: Optional[RetryPolicy] = None,
        perf_metrics: bool = False,
    ):
        self.model = model
        self.loss_cfg = loss_cfg
        # Run-telemetry subsystem (docs/OBSERVABILITY.md): ``health``
        # folds in-graph training-health signals into the step's metric
        # dict (None = no extra ops, HLO identical to a health-free
        # build); ``telemetry`` routes per-step records + host spans
        # through obs.run.RunTelemetry.  Both are plain attributes —
        # assignable after construction; health changes take effect at
        # the next (re)compile.
        self.health = health
        self.telemetry = telemetry
        # Fault-tolerance subsystem (docs/RESILIENCE.md), all plain
        # attributes like health/telemetry: ``divergence`` arms the
        # non-finite-loss guard (costs one host sync per step when set),
        # ``preempt`` is the SIGTERM/SIGINT stop flag ``train`` polls
        # once per step, ``snapshot_retry`` bounds the backoff around
        # snapshot I/O (None = the default 3-attempt policy).
        self.divergence = divergence
        self.preempt = preempt
        self.snapshot_retry = (
            snapshot_retry if snapshot_retry is not None else RetryPolicy()
        )
        # Batch signatures already dispatched through the jitted step/
        # eval fns: a NEW signature means jit will trace+compile before
        # dispatching, so the telemetry span is named */compile and the
        # stall is a visible event, not a mystery (the dynamic-batch
        # path recompiles per shape).
        self._seen_step_shapes: set = set()
        self._seen_eval_shapes: set = set()
        # Latched on the first sink-write failure (disk full): telemetry
        # must never abort training, so further metric emission stops
        # (spans, which are in-memory, keep recording).
        self._telemetry_failed = False
        # Perf observatory hook (docs/OBSERVABILITY.md §Perf): when ON
        # and telemetry is attached, one ``phase="perf"`` row per
        # display window carries ms_per_step / emb_per_sec / MFU (from
        # XLA's analytic step FLOPs, obs.perf.costs).  OFF by default —
        # the rows carry wall-clock values, so the sync-vs-pipelined
        # byte-parity contract only covers them when both runs opt in.
        self.perf_metrics = bool(perf_metrics)
        self._step_flops: Optional[float] = None
        self._perf_last: Optional[Tuple[float, int]] = None
        self._last_batch_size: Optional[int] = None
        self._dev_kind: Optional[str] = None
        # Fleet observatory state (docs/OBSERVABILITY.md §Fleet): under
        # fleet-stamped telemetry on a mesh, the first dispatch prices
        # the step's collectives from its HLO (written to
        # fleet_comms.json for `prof --fleet`) and per-step comm marks
        # carry the per-kind payload bytes; ``_step_seq`` numbers the
        # dispatch spans so the offline aggregator can join the i-th
        # span across ranks without trusting ordinal position.
        self._comm_kinds: Optional[list] = None
        self._step_seq: int = 0
        # The loss top's `loss_weight` (reference: cu:435 scales the
        # whole backward by top[0]'s weight; Caffe's objective is the
        # weighted loss).  The shipped template uses 1.
        self.loss_weight = float(loss_weight)
        # Per-parameter lr/decay multipliers ((w_lr, w_decay), (b_lr,
        # b_decay)) — Caffe `param { lr_mult decay_mult }` semantics;
        # the reference template trains biases at 2x lr with no decay
        # (usage/def.prototxt:90-97).  Set BEFORE the cfg property
        # below builds the optimizer.
        self.param_mults = param_mults
        self.mesh = mesh
        self.axis = axis
        # Declarative state sharding (parallel.partition,
        # docs/DISTRIBUTED.md): ordered (regex, PartitionSpec) rules
        # over the flattened state-tree path, first match wins,
        # unmatched leaves LOUD.  None = the shipped replicated table —
        # byte-identical placement to the hand-written
        # NamedSharding(mesh, P()) calls this replaced (parity pinned
        # by tests/test_partition.py).  A 2-D mesh (build_mesh mp>1)
        # plus a table sharding kernels over "mp" is how params scale
        # past replicated.
        self.partition_rules = (tuple(partition_rules)
                                if partition_rules is not None else None)
        # The DCN-aware engine decision (parallel.plan.EnginePlan) the
        # CLI resolved for this run, if any — stamped into the run
        # manifest so "which engine and why" is provenance.
        self.engine_plan = None
        # Loss engine (see docs/DESIGN.md §2): "dense" materializes the
        # pair matrix, "ring" streams it over ppermute hops on a mesh,
        # "blockwise" streams Pallas tiles on a single device (the
        # engine for self-pools too large for the dense matrix).  All
        # three support every mining method (RELATIVE_* via exact
        # streamed radix selection).  ``use_ring`` is the historical
        # spelling of engine="ring".
        if engine is None:
            engine = "ring" if use_ring else "dense"
        elif use_ring and engine != "ring":
            raise ValueError(
                f'use_ring=True contradicts engine={engine!r}'
            )
        if engine not in ("dense", "ring", "blockwise"):
            raise ValueError(f"unknown engine {engine!r}")
        self.engine = engine
        # Streaming engines' fp32 similarity cache (None = auto by size;
        # False forces strict streaming memory) — see ops.pallas_npair /
        # parallel.ring ``sim_cache``.
        self.sim_cache = sim_cache
        # Streaming engines' sparse-positive buffer size (None = auto 8;
        # 0 forces radix selection) — see ``pos_topk`` there.
        self.pos_topk = pos_topk
        # Declarative mixed-precision policy (models.precision): a name
        # ("mxu"/"bf16"/"fp32_parity") or PrecisionPolicy.  The MODEL's
        # dtypes are the model's own business (get_model(policy=...));
        # here the policy supplies the loss engines' gemm precision when
        # ``matmul_precision`` isn't set explicitly, and is recorded so
        # telemetry/bench stamp which recipe a run trained under.
        if precision is not None:
            from npairloss_tpu.models.precision import get_policy

            self.precision_policy = get_policy(precision)
            if matmul_precision is None:
                matmul_precision = \
                    self.precision_policy.loss_matmul_precision
        else:
            self.precision_policy = None
        # Sim/backward gemm MXU precision: None/"highest" = oracle
        # bit-parity; "default" = the ~6x single-pass bf16 throughput
        # mode (ops.npair_loss.resolve_matmul_precision).
        self.matmul_precision = matmul_precision
        self.use_ring = engine == "ring"
        if engine == "ring" and mesh is None:
            raise ValueError('engine="ring" requires a mesh')
        if engine == "blockwise" and mesh is not None:
            raise ValueError(
                'engine="blockwise" is the single-device streaming path; '
                'use engine="ring" to stream across a mesh'
            )
        self.top_ks = tuple(top_ks)
        self.input_shape = tuple(input_shape)
        self.state: Optional[Dict[str, Any]] = None
        self._step_fn = None
        self._eval_fn = None
        # Pipelined-loop state (docs/PIPELINE.md): the ring-carrying
        # jitted step, its device-side reset, and the window's key/
        # capacity bookkeeping — rebuilt whenever cfg changes, like
        # _step_fn.  ``sync_monitor`` is a test/CI hook: an attached
        # pipeline.HostSyncMonitor counts (or, strict, forbids) host
        # transfers outside window boundaries.
        self._pipe_step_fn = None
        self._ring_reset_fn = None
        self._metric_window = None
        self.sync_monitor = None
        self._checkpointer = None
        # Externally requested rollback (the alert→actuation control
        # plane, docs/RESILIENCE.md §Remediation): any thread may set a
        # RollbackRequest via ``request_rollback``; the train loop
        # takes it at its next safe point (sync: per step; pipelined:
        # the window boundary) and restores a pre-incident snapshot.
        self._rollback_request: Optional[RollbackRequest] = None
        self._rollback_lock = threading.Lock()
        # A fresh config per solver: SolverConfig is mutable, so a shared
        # default instance would leak cfg edits across solvers.
        self.cfg = cfg if cfg is not None else SolverConfig()

    # -- config (schedule/optimizer/window are derived; keep them in sync) --

    @property
    def cfg(self) -> SolverConfig:
        return self._cfg

    @cfg.setter
    def cfg(self, cfg: SolverConfig):
        self._cfg = cfg
        self.rate_fn = lr_schedule(
            cfg.lr_policy, cfg.base_lr, cfg.gamma, cfg.stepsize, cfg.power,
            cfg.max_iter, cfg.stepvalues,
        )
        # Direct read: __init__ assigns param_mults before this setter
        # runs (constructor-only — assigning solver.param_mults later
        # does NOT rebuild the optimizer).
        self.tx = caffe_sgd(
            self.rate_fn, cfg.momentum, cfg.weight_decay,
            param_mults=self.param_mults,
        )
        self._loss_window: collections.deque = collections.deque(
            maxlen=max(cfg.average_loss, 1)
        )
        self._step_fn = None  # recompile with the new schedule
        self._eval_fn = None
        self._pipe_step_fn = None
        self._ring_reset_fn = None
        self._metric_window = None

    # -- state ------------------------------------------------------------

    def init(self, example_input: Optional[np.ndarray] = None):
        if example_input is None:
            example_input = np.zeros((2, *self.input_shape), np.float32)
        # One jitted program builds the WHOLE training state — flax init
        # plus the optimizer's zeros-like momentum tree.  Eagerly these
        # are hundreds of small dispatches, which through a tunneled
        # backend cost ~a round-trip each and have wedged the tunnel
        # (docs/DESIGN.md §6).
        def build_state(key, x):
            variables = self.model.init(key, x, train=False)
            return variables, self.tx.init(variables["params"])

        variables, opt = jax.jit(build_state)(
            jax.random.PRNGKey(self.cfg.random_seed),
            jnp.asarray(example_input),
        )
        self.state = self._place_state({
            "params": variables["params"],
            "batch_stats": variables.get("batch_stats", {}),
            "opt": opt,
        })
        return self.state

    # -- declarative state sharding (parallel.partition) -------------------

    def _rules(self):
        """The effective partition ruleset: the caller's table, or the
        shipped all-replicated one (the pre-partition behavior, by
        construction)."""
        if self.partition_rules is not None:
            return self.partition_rules
        from npairloss_tpu.parallel.partition import replicated_rules

        return replicated_rules()

    def _state_shardings(self, state=None):
        """The state tree's NamedShardings, resolved through the rule
        table — THE one source of placement truth: ``_place_state``
        puts with it, the jitted step/eval fns take it as their state
        ``in_shardings``, and ``--dump-partitions`` renders it.  Loud
        (PartitionRuleError) on an unmatched leaf or an axis the mesh
        lacks — at build time, not hours into a run."""
        from npairloss_tpu.parallel.partition import match_partition_shardings

        state = state if state is not None else self.state
        return match_partition_shardings(self._rules(), state, self.mesh)

    def _place_state(self, state):
        """Rule-resolved device placement of a (host or device) state
        tree.  Multi-controller processes each hold the full value
        (identical seeds / identical restores) and contribute their
        addressable shards; single-process is a plain sharded
        device_put.  No mesh: leave placement to jit."""
        if self.mesh is None:
            return state
        from npairloss_tpu.parallel.partition import place_tree

        return place_tree(state, self._state_shardings(state))

    def _abstract_state(self):
        """The state tree as ShapeDtypeStructs, no arrays materialized
        — lets ``partition_table``/``partition_summary`` run before
        ``init()`` (manifest stamping, ``--dump-partitions`` preflight)
        without paying device work."""
        if self.state is not None:
            return self.state

        def build(key, x):
            variables = self.model.init(key, x, train=False)
            return {
                "params": variables["params"],
                "batch_stats": variables.get("batch_stats", {}),
                "opt": self.tx.init(variables["params"]),
            }

        return jax.eval_shape(
            build, jax.random.PRNGKey(self.cfg.random_seed),
            jnp.zeros((2, *self.input_shape), jnp.float32),
        )

    def partition_table(self) -> Dict[str, Any]:
        """The resolved rule -> PartitionSpec table per state leaf,
        with per-rule match counts — zero-match (silent no-op) rules
        flagged.  ``train --dump-partitions`` prints this."""
        from npairloss_tpu.parallel.partition import partition_table

        return partition_table(self._rules(), self._abstract_state(),
                               mesh=self.mesh)

    def partition_summary(self) -> Dict[str, Any]:
        """Manifest-sized digest of :meth:`partition_table`."""
        from npairloss_tpu.parallel.partition import partition_summary

        return partition_summary(self._rules(), self._abstract_state(),
                                 mesh=self.mesh)

    # -- compiled step ----------------------------------------------------

    def apply_model(self, params, batch_stats, inputs, train: bool):
        """Trunk forward in the given mode; returns
        ``(embeddings, new_batch_stats)``.  The single home for the
        variables/mutable-collections plumbing — the jitted train/eval
        steps AND external timers (``cli.py cmd_time``) build on this, so
        a benchmarked graph is the trained graph."""
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            if train:
                emb, updates = self.model.apply(
                    variables, inputs, train=True, mutable=["batch_stats"]
                )
                return emb, updates["batch_stats"]
            return self.model.apply(variables, inputs, train=False), \
                batch_stats
        return self.model.apply(variables, inputs, train=train), batch_stats

    def compute_loss(self, emb, labels):
        """(loss, metrics) through the configured engine — sharded over
        the mesh when one is attached, single-device otherwise.  The
        loss is the OBJECTIVE: scaled by the loss top's ``loss_weight``
        (reference cu:435 semantics), so gradients and the displayed
        loss both carry it."""
        if self.mesh is not None:
            loss, metrics = self._sharded_loss(emb, labels)
        else:
            loss, metrics = self._loss_and_metrics(emb, labels)
        if self.loss_weight != 1.0:
            loss = loss * jnp.float32(self.loss_weight)
        return loss, metrics

    def _loss_and_metrics(self, emb, labels):
        if self.engine == "blockwise":
            from npairloss_tpu.ops.pallas_npair import (
                blockwise_npair_loss_with_aux,
                blockwise_retrieval_metrics,
            )

            loss, _ = blockwise_npair_loss_with_aux(
                emb, labels, self.loss_cfg, sim_cache=self.sim_cache,
                pos_topk=self.pos_topk,
                matmul_precision=self.matmul_precision,
            )
            metrics = blockwise_retrieval_metrics(
                jax.lax.stop_gradient(emb), labels, self.top_ks
            )
            return loss, metrics
        axis = self.axis if self.mesh is not None else None
        loss, aux = npair_loss_with_aux(
            emb, labels, self.loss_cfg, axis_name=axis,
            matmul_precision=self.matmul_precision)
        metrics = retrieval_metrics(
            jax.lax.stop_gradient(aux), labels, jax.lax.stop_gradient(emb),
            self.top_ks,
        )
        if self.health is not None and self.health.pair_hardness:
            # Mined-pair hardness summaries ride the dense engine's loss
            # aux (the streaming engines never materialize it — their
            # health coverage is the norm/magnitude signals).
            metrics.update(pair_hardness_health(
                aux, mining=self.health.mining_health))
        return loss, metrics

    def _sharded_loss(self, emb, labels):
        """Per-shard loss under shard_map; scalars come back stacked (G,)."""

        def per_shard(e, l):
            if self.use_ring:
                from npairloss_tpu.parallel.ring import (
                    ring_npair_loss_and_metrics,
                )

                loss, metrics = ring_npair_loss_and_metrics(
                    e, l, self.loss_cfg, self.axis, self.top_ks,
                    sim_cache=self.sim_cache, pos_topk=self.pos_topk,
                    matmul_precision=self.matmul_precision,
                )
                metrics = {
                    k: v for k, v in metrics.items()
                    if k not in ("ident_num", "diff_num")
                }
            else:
                loss, metrics = self._loss_and_metrics(e, l)
            out = {"loss": loss, **metrics}
            return jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], out)

        stacked = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=P(self.axis),
        )(emb, labels)
        loss = stacked["loss"].mean()
        metrics = {k: v.mean() for k, v in stacked.items() if k != "loss"}
        return loss, metrics

    def _train_step_body(self):
        def train_step(state, inputs, labels):
            def loss_fn(params):
                emb, new_bs = self.apply_model(
                    params, state["batch_stats"], inputs, train=True
                )
                loss, metrics = self.compute_loss(emb, labels)
                if self.health is not None and \
                        self.health.embedding_magnitude:
                    metrics = {
                        **metrics,
                        **embedding_health(jax.lax.stop_gradient(emb)),
                    }
                return loss, (metrics, new_bs)

            (loss, (metrics, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            # The lr reported and the lr applied both read the optimizer's
            # own step counter — a single source of truth.
            metrics["lr"] = self.rate_fn(state["opt"].step)
            # named_scope: the optimizer shows up as its own region in
            # the prof report (obs.perf) instead of bloating (unscoped);
            # metadata-only, the compiled program is unchanged.
            with jax.named_scope("optim/update"):
                upd, opt = self.tx.update(
                    grads, state["opt"], state["params"])
            if self.health is not None:
                # Optimizer-side health signals (obs.health): whole-tree
                # fp32 reductions folded into the same jitted graph.
                with jax.named_scope("health"):
                    metrics.update(
                        update_health(grads, state["params"], upd,
                                      self.health)
                    )
            with jax.named_scope("optim/apply"):
                params = jax.tree_util.tree_map(
                    lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
                    state["params"],
                    upd,
                )
            new_state = {
                "params": params,
                "batch_stats": new_bs,
                "opt": opt,
            }
            metrics["loss"] = loss
            return new_state, metrics

        return train_step

    def _eval_step_body(self):
        def eval_step(state, inputs, labels):
            emb, _ = self.apply_model(
                state["params"], state["batch_stats"], inputs, train=False
            )
            loss, metrics = self.compute_loss(emb, labels)
            metrics["loss"] = loss
            return metrics

        return eval_step

    def _make_step(self):
        train_step = self._train_step_body()
        eval_step = self._eval_step_body()
        donate = (0,)
        if self.mesh is not None:
            data_sharding = NamedSharding(self.mesh, P(self.axis))
            # State placement comes from the partition-rule table (one
            # source of truth with _place_state), not hand-placed specs;
            # None (state not built yet) defers to the arguments' own
            # shardings, which _place_state already resolved.
            state_sh = (self._state_shardings()
                        if self.state is not None else None)
            # out_shardings pins the NEW state to the same rule table:
            # without it XLA may propagate a sharded kernel's layout
            # onto e.g. its bias in the OUTPUT, and the next step's
            # input contract breaks (the rules are the invariant, for
            # inputs and outputs alike).
            self._step_fn = jax.jit(
                train_step,
                donate_argnums=donate,
                in_shardings=(state_sh, data_sharding, data_sharding),
                out_shardings=(state_sh, None),
            )
            self._eval_fn = jax.jit(
                eval_step,
                in_shardings=(state_sh, data_sharding, data_sharding),
            )
        else:
            self._step_fn = jax.jit(train_step, donate_argnums=donate)
            self._eval_fn = jax.jit(eval_step)
        # Fresh jitted fns compile every signature anew — reset the
        # compile-capture bookkeeping so telemetry reports them as such.
        self._seen_step_shapes = set()
        self._seen_eval_shapes = set()

    # -- pipelined step (docs/PIPELINE.md) ---------------------------------

    def _pipeline_window_capacity(self, test_active: bool) -> int:
        """Steps between host syncs: the smallest active cadence (a
        window read happens AT every display/test/snapshot step, so the
        ring never needs to span more than the smallest gap), capped by
        ``cfg.pipeline_window``; 64 when no cadence is active."""
        cfg = self.cfg
        cads = [c for c in (
            cfg.display,
            cfg.test_interval if test_active else 0,
            cfg.snapshot,
        ) if c]
        cap = min(cads) if cads else 0
        user = int(cfg.pipeline_window or 0)
        if user:
            cap = min(cap, user) if cap else user
        return max(int(cap) if cap else 64, 1)

    def _make_pipelined_step(self, x, lab, capacity: int):
        """Build the ring-carrying jitted step: the SAME train_step body
        as the synchronous path (parity by construction) plus the
        MetricWindow scatter and the in-graph non-finite streak counter.
        Donation covers state AND the ring AND the batch args — the
        prefetcher guarantees batch buffers are fresh per step, so the
        jitted step can reuse them in place (the sync path cannot make
        that promise: callers like bench.py redispatch one buffer)."""
        from npairloss_tpu.pipeline import MetricWindow

        train_step = self._train_step_body()
        _, metrics_shape = jax.eval_shape(train_step, self.state, x, lab)
        # Pytree dicts flatten key-sorted, so sorted() IS the jitted
        # output dict's iteration order — the key-stream parity anchor.
        window = MetricWindow(sorted(metrics_shape), capacity)

        def pipelined_step(state, ring, inputs, labels):
            new_state, metrics = train_step(state, inputs, labels)
            new_ring = window.update(ring, metrics)
            # ``tick`` is the dispatch controller's completion token:
            # the host holds it across dispatches, so it needs its OWN
            # buffer.  An identity (pos + 0) is folded by XLA and would
            # alias pos — the next step's ring donation then conflicts
            # with the held token on backends that honor donation
            # (TPU).  pos + 1 is a distinct value, hence a distinct
            # buffer, on every backend.
            tick = new_ring["pos"] + jnp.int32(1)
            return new_state, new_ring, tick

        donate = (0, 1, 2, 3)
        if self.mesh is not None:
            data_sharding = NamedSharding(self.mesh, P(self.axis))
            replicated = NamedSharding(self.mesh, P())
            state_sh = (self._state_shardings()
                        if self.state is not None else None)
            # Same out-pinning as _make_step: state stays on the rule
            # table, the ring stays replicated, across every step.
            self._pipe_step_fn = jax.jit(
                pipelined_step,
                donate_argnums=donate,
                in_shardings=(state_sh, replicated,
                              data_sharding, data_sharding),
                out_shardings=(state_sh, replicated, None),
            )
        else:
            self._pipe_step_fn = jax.jit(pipelined_step,
                                         donate_argnums=donate)
        self._ring_reset_fn = jax.jit(window.reset, donate_argnums=(0,))
        self._metric_window = window
        # A rebuilt pipelined step is a NEW program (same policy as
        # _make_step): without this reset, the real compile after a
        # rollback's set_config would be mislabeled step/dispatch and
        # skip the expected-donation-warning filter.
        self._seen_step_shapes = set()

    def _init_ring(self):
        ring = self._metric_window.init_ring()
        if self.mesh is not None:
            ring = jax.device_put(ring, NamedSharding(self.mesh, P()))
        return ring

    def _stage_batch(self, inputs, labels):
        """Device placement for the prefetcher's STAGING THREAD: an
        explicit ``jax.device_put`` with the step's input sharding (so
        the batch arrives resident and the put is visible to the
        syncguard counting shim).  Dtypes are canonicalized to match
        ``_put_batch``'s jnp.asarray semantics — the pipelined and
        synchronous paths must compile identical signatures."""
        if self.mesh is not None and jax.process_count() > 1:
            from npairloss_tpu.parallel.distributed import process_local_batch

            with self._span("comm/assemble", staged=True):
                return process_local_batch(
                    self.mesh, (np.asarray(inputs), np.asarray(labels)),
                    self.axis,
                )
        inputs = np.asarray(inputs)
        labels = np.asarray(labels)
        if inputs.dtype == np.float64:
            inputs = inputs.astype(np.float32)
        if labels.dtype == np.int64:
            labels = labels.astype(np.int32)
        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, P(self.axis))
            return jax.device_put((inputs, labels), sharding)
        return jax.device_put((inputs, labels))

    def warmup(self, batch_size: int) -> float:
        """AOT-compile the train step for ``batch_size`` without
        dispatching it (``.lower().compile()`` on shape structs — no
        data, no state mutation); returns the compile seconds.

        With ``cfg.compile_cache`` set this populates the persistent
        compilation cache, so the first REAL dispatch (and every other
        process compiling the same program) pays deserialization
        instead of a multi-minute XLA compile — run it before a tunnel
        window spends its minutes measuring."""
        import time as _time

        if self.cfg.compile_cache:
            from npairloss_tpu.pipeline import enable_compile_cache

            enable_compile_cache(self.cfg.compile_cache)
        if self.state is None:
            self.init()
        if self._step_fn is None:
            self._make_step()
        x_sds = jax.ShapeDtypeStruct(
            (int(batch_size), *self.input_shape), jnp.float32
        )
        lab_sds = jax.ShapeDtypeStruct((int(batch_size),), jnp.int32)
        t0 = _time.perf_counter()
        with self._span("step/compile", batch=int(batch_size), aot=True):
            self._step_fn.lower(self.state, x_sds, lab_sds).compile()
        return _time.perf_counter() - t0

    def _span(self, name: str, **args):
        """Telemetry span, or a no-op context when none is attached."""
        if self.telemetry is None:
            return contextlib.nullcontext()
        return self.telemetry.span(name, **args)

    def _tel_log(self, phase: str, step: int, metrics, **extra) -> None:
        """Metric emission that can never abort training: a sink-write
        failure (disk full/quota) is reported once, then per-step
        emission latches off for the rest of the run."""
        tel = self.telemetry
        if tel is None or not tel.metrics_enabled or self._telemetry_failed:
            return
        try:
            tel.log(phase, step, metrics, **extra)
        except Exception as e:  # noqa: BLE001 — telemetry is not the run
            self._telemetry_failed = True
            log.error(
                "telemetry metric emission failed (disabling for the "
                "rest of the run): %s", e,
            )

    def _want_perf(self) -> bool:
        tel = self.telemetry
        return (self.perf_metrics and tel is not None
                and tel.metrics_enabled and not self._telemetry_failed)

    def _capture_step_flops(self, fn, args):
        """XLA's analytic per-step FLOPs of the program about to
        dispatch (client-side lowering, no extra compile) — feeds the
        continuous ``perf`` rows' MFU.  Best-effort: a backend without
        cost analysis just means MFU-less rows.  Returns the Lowered
        (or None) so a same-signature fleet-comms capture can reuse it
        instead of paying a second re-trace."""
        from npairloss_tpu.obs.perf.costs import cost_flops

        try:
            # Spanned: the client-side lowering costs a full re-trace
            # (once per signature) and must show in the host timeline
            # as obs overhead, not as unattributed wall time.
            with self._span("step/cost_analysis"):
                lowered = fn.lower(*args)
                self._step_flops = cost_flops(lowered)
            return lowered
        except Exception as e:  # noqa: BLE001 — perf rows are optional
            log.debug("step flops estimate unavailable: %s", e)
            return None

    def _device_kind(self) -> str:
        if self._dev_kind is None:
            self._dev_kind = jax.devices()[0].device_kind
        return self._dev_kind

    # -- fleet observatory hooks (docs/OBSERVABILITY.md §Fleet) -----------

    def _fleet_stamp(self):
        """The attached telemetry's FleetStamp, or None — every fleet
        hook below gates on this, so non-fleet runs keep byte-identical
        telemetry streams and span timelines."""
        tel = self.telemetry
        return getattr(tel, "fleet", None) if tel is not None else None

    def _step_span_args(self, batch: int) -> Dict[str, Any]:
        """step/dispatch|compile span args: fleet runs additionally
        stamp the step number so the cross-rank aggregator can join the
        same step's spans across ranks."""
        args: Dict[str, Any] = {"batch": batch}
        if self._fleet_stamp() is not None:
            args["step"] = self._step_seq + 1
        return args

    def _capture_fleet_comms(self, fn, args, lowered=None) -> None:
        """Collective pricing at FIRST DISPATCH under fleet telemetry
        on a mesh (not first compile: telemetry attached after the
        step already compiled — a warmed solver, the mp harness — must
        still capture): extract the compiled step's HLO, price every
        collective per opcode
        (``obs.perf.hlo.collective_bytes_by_opcode``), add the
        analytic grad-sync claim for the SPMD-inserted parameter
        all-reduce, and leave ``fleet_comms.json`` in the run dir for
        ``prof --fleet`` (rank 0 writes; the pricing is identical on
        every rank of an SPMD program).  Costs one extra AOT compile
        of the step — spanned, fleet-opt-in only; ``lowered`` reuses a
        just-captured perf lowering instead of re-tracing.
        Best-effort: a backend that cannot re-lower just means a
        comms-less fleet report."""
        stamp = self._fleet_stamp()
        if stamp is None or self.mesh is None \
                or self._comm_kinds is not None:
            return
        try:
            from npairloss_tpu.obs.fleet import comms as comms_mod
            from npairloss_tpu.obs.fleet.aggregate import COMMS_FILENAME
            from npairloss_tpu.obs.perf.hlo import (
                collective_bytes_by_opcode,
                stage_hlo_text,
            )

            with self._span("comm/price", aot=True):
                per_opcode = collective_bytes_by_opcode(
                    stage_hlo_text(
                        lowered if lowered is not None
                        else fn.lower(*args)))
            param_bytes = float(sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(self.state["params"])
            ))
            extra = (comms_mod.grad_sync_claim_bytes(
                param_bytes, stamp.process_count)
                if self.mesh.size > 1 else {})
            payload = {
                "per_opcode": per_opcode,
                "extra_claims": extra,
                "device_kind": self._device_kind(),
                # Collectives crossing host processes ride DCN; a
                # single-process mesh keeps them on-chip/ICI.
                "link": "dcn" if stamp.process_count > 1 else "ici",
                "batch": self._last_batch_size,
                "engine": self.engine,
                "mesh_devices": int(self.mesh.size),
            }
            rows = comms_mod.comm_rows_from_hlo(per_opcode, extra)
            self._comm_kinds = [
                (k["kind"], k["bytes_per_step"], k["claimed"])
                for k in rows["kinds"]
            ]
            if stamp.process_index == 0 and self.telemetry is not None:
                import json as _json
                import os as _os

                path = _os.path.join(self.telemetry.run_dir,
                                     COMMS_FILENAME)
                tmp = path + f".tmp-{_os.getpid()}"
                with open(tmp, "w") as f:
                    _json.dump(payload, f)
                _os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 — comms rows are optional
            self._comm_kinds = []
            log.debug("fleet comm pricing unavailable: %s", e)

    def _emit_comm_marks(self, step_num: int) -> None:
        """Per-step ``comm/<kind>`` marks carrying the HLO-priced
        payload bytes — the host cannot time an in-graph collective, so
        these are zero-duration accounting marks on the timeline, not
        fabricated durations (the bandwidth math lives offline in
        ``obs.fleet.comms``)."""
        tel = self.telemetry
        if tel is None or not self._comm_kinds:
            return
        for kind, nbytes, claimed in self._comm_kinds:
            tel.instant(f"comm/{kind}", bytes=nbytes, claimed=claimed,
                        step=step_num)

    def _emit_perf_row(self, step_num: int) -> None:
        """One ``phase="perf"`` row per display window: wall clock
        between boundary emissions over the steps they cover (honest in
        BOTH loops — the pipelined window's deferred emission still
        spans the window's dispatched steps)."""
        now = time.perf_counter()
        prev = self._perf_last
        self._perf_last = (now, step_num)
        if prev is None:
            return
        t0, s0 = prev
        steps_n = step_num - s0
        if steps_n <= 0 or now <= t0:
            return
        sec = (now - t0) / steps_n
        row: Dict[str, Any] = {"ms_per_step": round(sec * 1e3, 3)}
        if self._last_batch_size:
            row["emb_per_sec"] = round(self._last_batch_size / sec, 1)
        from npairloss_tpu.obs.perf.costs import mfu_from_timing

        est = mfu_from_timing(flops=self._step_flops, seconds=sec,
                              steps=1, device_kind=self._device_kind())
        if est["mfu"] is not None:
            row["mfu"] = round(est["mfu"], 4)
        if self._step_flops is not None:
            row["step_flops"] = self._step_flops
        self._tel_log("perf", step_num, row)

    def _tel_event(self, kind: str, step: int, **extra) -> None:
        """Resilience events (``retry``/``rollback``/``preempt``/
        ``resume_skip``) through the telemetry pipeline: one metrics row
        with ``phase="event"`` plus an instant marker on the span
        timeline — both no-ops without telemetry attached."""
        tel = self.telemetry
        if tel is None:
            return
        args = {k: v for k, v in extra.items() if v is not None}
        tel.instant(f"resilience/{kind}", **args)
        self._tel_log("event", step, {"event": kind, **args})

    # -- public API -------------------------------------------------------

    def _put_batch(self, inputs, labels):
        """Device placement for one batch.  Multi-process meshes follow
        the reference's per-rank data model (each MPI rank loads its own
        N rows, cu:17-43): the local batch becomes this process's shard
        of the global batch, concatenated in process order."""
        if self.mesh is not None and jax.process_count() > 1:
            from npairloss_tpu.parallel.distributed import process_local_batch

            # The one HOST-side exchange path: assembling this
            # process's rows into the global batch.  Unlike the
            # in-graph collectives (accounting marks only), this has a
            # real host duration — spanned as comm/ so the fleet
            # decomposition sees it.
            with self._span("comm/assemble"):
                return process_local_batch(
                    self.mesh, (np.asarray(inputs), np.asarray(labels)),
                    self.axis,
                )
        return jnp.asarray(inputs), jnp.asarray(labels)

    def step(self, inputs: np.ndarray, labels: np.ndarray) -> Dict[str, float]:
        """One training iteration; returns the step's metric dict."""
        if self.state is None:
            # Shape-only init: two examples suffice (and avoid an eager,
            # unsharded full-batch forward on one device).
            self.init(np.asarray(inputs)[:2])
        if self._step_fn is None:
            self._make_step()
        x, lab = self._put_batch(inputs, labels)
        # First-dispatch compile capture: jit compiles synchronously on a
        # new argument signature before the async dispatch, so a span
        # around the call IS the compile time.  A signature seen after
        # the first one is a RECOMPILE (the dynamic-batch path) — marked
        # with an instant event so Perfetto shows it at a glance.
        sig = (tuple(np.shape(x)), tuple(np.shape(lab)))
        compiling = sig not in self._seen_step_shapes
        self._seen_step_shapes.add(sig)
        if self.telemetry is not None and compiling \
                and len(self._seen_step_shapes) > 1:
            self.telemetry.instant("step/recompile", batch=int(np.shape(x)[0]))
        self._last_batch_size = int(np.shape(x)[0])
        lowered = None
        if compiling and self._want_perf():
            lowered = self._capture_step_flops(
                self._step_fn, (self.state, x, lab))
        if compiling:
            # A new signature is a NEW program with new collective
            # payloads (the dynamic-batch tail step is smaller):
            # invalidate so the pricing below re-captures; marks then
            # always carry the CURRENT program's bytes.
            self._comm_kinds = None
        # Self-gated: fleet comms must also capture at the first
        # dispatch AFTER telemetry attaches, which need not be a
        # compile (a warmed solver re-dispatches the same signature).
        self._capture_fleet_comms(self._step_fn, (self.state, x, lab),
                                  lowered=lowered)
        with self._span(
            "step/compile" if compiling else "step/dispatch",
            **self._step_span_args(int(np.shape(x)[0])),
        ):
            self.state, metrics = self._step_fn(self.state, x, lab)
        self._step_seq += 1
        self._emit_comm_marks(self._step_seq)
        if debug_checks_enabled():
            # utils.debug switch: validate every step's scalars on host
            # (SURVEY.md §5.2 — the reference had no numeric checks).
            assert_all_finite(metrics, "step metrics")
        return metrics

    def evaluate(
        self, batches: Iterator[Tuple[np.ndarray, np.ndarray]], num_iters: int
    ) -> Dict[str, float]:
        """TEST phase: average loss+metrics over ``num_iters`` batches."""
        acc: Dict[str, float] = collections.defaultdict(float)
        n = 0
        with self._span("eval", num_iters=num_iters):
            for _ in range(num_iters):
                inputs, labels = next(batches)
                if self.state is None:
                    self.init(np.asarray(inputs)[:2])
                if self._eval_fn is None:
                    self._make_step()
                x, lab = self._put_batch(inputs, labels)
                sig = (tuple(np.shape(x)), tuple(np.shape(lab)))
                compiling = sig not in self._seen_eval_shapes
                self._seen_eval_shapes.add(sig)
                if compiling:
                    with self._span("eval/compile",
                                    batch=int(np.shape(x)[0])):
                        m = self._eval_fn(self.state, x, lab)
                else:
                    m = self._eval_fn(self.state, x, lab)
                for k, v in m.items():
                    acc[k] += float(v)
                n += 1
        out = {k: v / max(n, 1) for k, v in acc.items()}
        if n:
            self._tel_log("eval", self.iteration, out, eval_batches=n)
        return out

    @property
    def iteration(self) -> int:
        """Current solver iteration — the Caffe solverstate ``iter``.

        Reads the optimizer's step counter, which every snapshot persists
        and ``restore_snapshot`` brings back, so display/test/snapshot
        cadence AND the lr schedule resume from the same single source of
        truth (the reference resumes from ``.solverstate`` files the same
        way, solver.prototxt:15-16 semantics).
        """
        if self.state is None:
            return 0
        return int(jax.device_get(self.state["opt"].step))

    def train(
        self,
        train_batches: Iterator[Tuple[np.ndarray, np.ndarray]],
        num_iters: Optional[int] = None,
        test_batches: Optional[Iterator[Tuple[np.ndarray, np.ndarray]]] = None,
        log_fn: Callable[[str], None] = log.info,
        record_fn: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> Dict[str, float]:
        """The Caffe Solver::Solve loop: train/display/test/snapshot cadence.

        ``num_iters`` is the TOTAL iteration target (Caffe ``max_iter``):
        a solver restored from the iteration-k snapshot continues at k+1
        and runs ``num_iters - k`` more steps, keeping every cadence
        aligned (next snapshot lands at k + ``snapshot``).

        ``record_fn`` receives one structured dict per display/test/
        snapshot event (``{"event": ..., "iteration": ..., metrics...}``)
        — the machine-readable counterpart of ``log_fn``'s Caffe-style
        text lines (CLI ``--log-json`` writes them as JSONL).
        """
        cfg = self.cfg
        num_iters = num_iters if num_iters is not None else cfg.max_iter
        if cfg.compile_cache:
            from npairloss_tpu.pipeline import enable_compile_cache

            enable_compile_cache(cfg.compile_cache)
        if cfg.pipeline:
            return self._train_pipelined(
                train_batches, num_iters, test_batches, log_fn, record_fn
            )
        start = self._train_prologue(num_iters, test_batches, log_fn,
                                     record_fn)
        tel = self.telemetry
        last = {}
        guard = (DivergenceGuard(self.divergence)
                 if self.divergence is not None else None)
        try:
            it = start
            while it < num_iters:
                with self._span("data/next_batch"):
                    inputs, labels = next(train_batches)
                # Keep metrics as device scalars so the loop never blocks
                # on a host sync; floats are materialized only at display/
                # test/return boundaries (JAX async dispatch keeps the TPU
                # pipeline full) — UNLESS per-step telemetry or the
                # divergence guard is attached; both require materializing
                # here (the recorded cost; see docs/OBSERVABILITY.md).
                metrics = self.step(inputs, labels)
                step_num = int(it) + 1
                if failpoints.should_fire("step.nan_loss"):
                    metrics = dict(metrics)
                    metrics["loss"] = jnp.float32(float("nan"))
                self._loss_window.append(metrics["loss"])
                last = metrics
                if guard is not None and \
                        guard.observe(float(metrics["loss"])):
                    it = self._handle_divergence(
                        guard, step_num, log_fn, record_fn
                    )
                    continue
                req = self._take_rollback_request()
                if req is not None:
                    rolled = self._handle_requested_rollback(
                        req, step_num, log_fn, record_fn)
                    if rolled is not None:
                        it = rolled
                        continue
                self._emit_step_row(step_num, metrics, log_fn, record_fn)
                self._boundary_actions(step_num, test_batches, log_fn,
                                       record_fn)
                it = step_num
        finally:
            self._train_epilogue()
        return {k: float(v) for k, v in last.items()}

    def _train_prologue(self, num_iters, test_batches, log_fn,
                        record_fn) -> int:
        """Shared entry of both train loops: resume logging + the
        iteration-0 TEST pass.  Returns the start iteration."""
        cfg = self.cfg
        start = self.iteration
        # Fleet dispatch spans number steps from the resume point so
        # span step args and row step numbers agree across a restart.
        self._step_seq = start
        if start:
            log_fn(f"resuming from iteration {start}")
            if start >= num_iters:
                log_fn(
                    f"nothing to do: restored iteration {start} >= "
                    f"target {num_iters} (num_iters is the TOTAL "
                    "max_iter target, not an increment)"
                )
        if (
            start == 0
            and cfg.test_initialization
            and test_batches is not None
            and cfg.test_iter > 0
        ):
            m = self.evaluate(test_batches, cfg.test_iter)
            log_fn(f"iter 0 TEST {_fmt(m)}")
            if record_fn is not None:
                record_fn({"event": "test", "iteration": 0,
                           **{k: float(v) for k, v in m.items()}})
        return start

    def _emit_step_row(self, step_num: int, row, log_fn=None,
                       record_fn=None) -> None:
        """Post-guard per-step emission — telemetry row + display line —
        shared by the sync loop, the pipelined window replay, and the
        pending-window flush, so the byte-identical-stream parity
        contract (docs/PIPELINE.md) holds by construction instead of by
        keeping three copies in lockstep.  ``log_fn=None`` (flush path)
        skips display; a pending tail can never contain a display step
        anyway (boundary steps always flush in-loop)."""
        if failpoints.should_fire("train.collapse"):
            # Deterministic embedding-collapse signal
            # (docs/RESILIENCE.md): the health key the collapse
            # watchdog reads goes degenerate in THIS row only —
            # telemetry/display see a collapsing space, the actual
            # training state is untouched.
            row = {**row, "an_threshold_mean": 1.0}
        cfg = self.cfg
        tel = self.telemetry
        if tel is not None and tel.metrics_enabled \
                and not self._telemetry_failed:
            extra: Dict[str, Any] = {}
            if cfg.display and step_num % cfg.display == 0 \
                    and tel.tracer is not None and tel.tracer.dropped:
                # The tracer cap is eating spans: surface the drop
                # count in the display-window row (the serve window
                # rows' spans_dropped contract, uniform for training)
                # instead of letting the host timeline silently go
                # partial.  Absent unless drops happened, so ordinary
                # runs keep byte-identical streams.
                extra["spans_dropped"] = tel.tracer.dropped
            self._tel_log("train", step_num,
                          {k: float(v) for k, v in row.items()}, **extra)
        if self._want_perf() and cfg.display \
                and step_num % cfg.display == 0:
            # Continuous perf/mfu rows at display cadence (a pending-
            # window flush can never contain a display step, so the
            # log_fn=None path never reaches here).
            self._emit_perf_row(step_num)
        if log_fn is not None and cfg.display \
                and step_num % cfg.display == 0:
            host = {k: float(v) for k, v in row.items()}
            avg = float(jnp.stack(list(self._loss_window)).mean())
            log_fn(
                f"iter {step_num} lr={host.get('lr', 0):.6g} "
                f"loss={avg:.6g} (avg over {len(self._loss_window)}) "
                + _fmt({k: v for k, v in host.items()
                        if k not in ('loss', 'lr')})
            )
            if record_fn is not None:
                record_fn({"event": "display", "iteration": step_num,
                           "loss_avg": avg, **host})

    def _boundary_actions(self, step_num: int, test_batches, log_fn,
                          record_fn) -> None:
        """The test/snapshot/preempt cadence block shared by both train
        loops (the pipelined loop runs it only at window boundaries —
        which is no restriction, since those cadences force a boundary).
        Raises :class:`TrainingPreempted` on a requested preemption:
        the in-flight step finished above; commit an emergency snapshot
        (unless the cadence just did) and surface a typed stop the CLI
        maps to EXIT_PREEMPTED for the supervisor."""
        cfg = self.cfg
        if (
            test_batches is not None
            and cfg.test_interval
            and step_num % cfg.test_interval == 0
        ):
            m = self.evaluate(test_batches, cfg.test_iter)
            log_fn(f"iter {step_num} TEST {_fmt(m)}")
            if record_fn is not None:
                record_fn({"event": "test", "iteration": step_num,
                           **{k: float(v) for k, v in m.items()}})
        snapped = None
        if cfg.snapshot and step_num % cfg.snapshot == 0:
            snapped = self.save_snapshot(step_num)
            if record_fn is not None:
                record_fn({"event": "snapshot",
                           "iteration": step_num})
        if self.preempt is not None and self.preempt.requested:
            path = snapped or self.save_snapshot(step_num)
            log_fn(
                f"preempted at iter {step_num}: emergency "
                f"snapshot {path}; relaunch with --resume auto"
            )
            self._tel_event("preempt", step_num,
                            snapshot=path,
                            signum=self.preempt.signum)
            if record_fn is not None:
                record_fn({"event": "preempt",
                           "iteration": step_num,
                           "snapshot": path})
            raise TrainingPreempted(
                step_num, snapshot_path=path,
                signum=self.preempt.signum,
            )

    def _train_epilogue(self) -> None:
        """Shared exit of both train loops — EVERY exit path (normal
        completion, preemption, a raised step error) must land in-flight
        Orbax work before the process can exit, or the last snapshot is
        left as an .orbax-checkpoint-tmp dir.  Guarded: cleanup must not
        mask the in-flight exception."""
        if self._checkpointer is not None:
            try:
                self._checkpointer.wait_until_finished()
            except Exception as e:  # noqa: BLE001
                log.error("checkpointer drain failed: %s", e)
        if self.telemetry is not None:
            # Land metrics.jsonl/trace.json even when the owner forgets
            # close() — flush is idempotent and the owner may keep
            # logging.  Guarded like _tel_log: a full disk must not
            # swallow a completed run's final metrics.
            try:
                self.telemetry.flush()
            except Exception as e:  # noqa: BLE001
                log.error("telemetry flush failed: %s", e)

    def _train_pipelined(self, train_batches, num_iters, test_batches,
                         log_fn, record_fn) -> Dict[str, float]:
        """The sync-free counterpart of the loop above (docs/PIPELINE.md).

        Steady state does NO host transfers: batches arrive device-
        resident from the prefetcher's staging thread, the jitted step
        scatters its scalars into a device-side ring, and the host reads
        the whole window back in one ``device_get`` only at display/
        test/snapshot boundaries (``step/window_sync`` span).  Per-step
        records (telemetry rows, the loss window, display lines, the
        divergence guard's observations) are reconstructed from the ring
        at the boundary with IDENTICAL keys/values to the synchronous
        loop — only their wall-clock emission time is deferred (bounded
        staleness: at most ``_pipeline_window_capacity()`` steps).
        Dispatch depth is bounded by ``cfg.pipeline_depth`` so async
        dispatch cannot queue unboundedly against a wedging backend.
        """
        from npairloss_tpu.pipeline import (
            DevicePrefetcher,
            DispatchController,
            monitor_from_env,
        )

        cfg = self.cfg
        if self.state is None:
            self.init()
        if self._eval_fn is None:
            # Build the sync/eval fns up front: a lazy _make_step inside
            # a mid-run evaluate() would reset the compile-capture
            # bookkeeping and mislabel the next dispatch as a compile.
            self._make_step()
        start = self._train_prologue(num_iters, test_batches, log_fn,
                                     record_fn)
        tel = self.telemetry
        guard = (DivergenceGuard(self.divergence)
                 if self.divergence is not None else None)
        mon = (self.sync_monitor if self.sync_monitor is not None
               else monitor_from_env())

        def allowed():
            return (mon.allowed() if mon is not None
                    else contextlib.nullcontext())

        depth = max(int(cfg.pipeline_depth), 1)
        window_cap = self._pipeline_window_capacity(test_batches is not None)
        controller = DispatchController(depth)
        prefetcher = DevicePrefetcher(
            train_batches, self._stage_batch, depth=depth, span=self._span
        )
        last: Dict[str, Any] = {}
        ring = None
        it = start
        window_start = it + 1
        poisoned: list = []  # step.nan_loss fires, host-side
        try:
            with warnings.catch_warnings(), \
                    (mon if mon is not None else contextlib.nullcontext()):
                # Batch-arg donation is best-effort: backends that
                # cannot alias the batch buffers (CPU) fall back to
                # copies, and XLA's per-compile warning about it is
                # expected, not a bug.  ONE filter for the whole loop
                # (a per-step catch_warnings would copy global filter
                # state on the hot path), covering sharding-keyed
                # recompiles the shape heuristic cannot predict.
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable",
                )
                while it < num_iters:
                    with self._span("data/next_batch", staged=True):
                        x, lab = prefetcher.get()
                    if self._pipe_step_fn is None:
                        with allowed():
                            self._make_pipelined_step(x, lab, window_cap)
                    if ring is None:
                        with allowed():
                            ring = self._init_ring()
                    controller.reserve()
                    sig = (tuple(np.shape(x)), tuple(np.shape(lab)))
                    compiling = sig not in self._seen_step_shapes
                    self._seen_step_shapes.add(sig)
                    if tel is not None and compiling \
                            and len(self._seen_step_shapes) > 1:
                        tel.instant("step/recompile",
                                    batch=int(np.shape(x)[0]))
                    self._last_batch_size = int(np.shape(x)[0])
                    lowered = None
                    if compiling and self._want_perf():
                        lowered = self._capture_step_flops(
                            self._pipe_step_fn, (self.state, ring, x, lab))
                    if compiling:
                        # New signature = new collective payloads;
                        # re-price (see the sync loop).
                        self._comm_kinds = None
                    self._capture_fleet_comms(
                        self._pipe_step_fn, (self.state, ring, x, lab),
                        lowered=lowered)
                    cache_size = getattr(self._pipe_step_fn,
                                         "_cache_size", lambda: None)
                    n_before = cache_size()
                    with self._span(
                        "step/compile" if compiling else "step/dispatch",
                        pipeline=True,
                        **self._step_span_args(int(np.shape(x)[0])),
                    ):
                        self.state, ring, tick = self._pipe_step_fn(
                            self.state, ring, x, lab
                        )
                    if (tel is not None and not compiling
                            and n_before is not None
                            and cache_size() != n_before):
                        # The executable cache grew under an already-
                        # seen shape: a sharding/aval-keyed recompile
                        # the heuristic mislabeled step/dispatch —
                        # surface the stall in the trace anyway.
                        tel.instant("step/recompile",
                                    batch=int(np.shape(x)[0]),
                                    keyed="sharding")
                    controller.admit(tick)
                    step_num = int(it) + 1
                    self._step_seq = step_num
                    self._emit_comm_marks(step_num)
                    if failpoints.should_fire("step.nan_loss"):
                        # The sync loop poisons the OBSERVED loss on
                        # host (state untouched); here the observation
                        # lives in the ring, so remember the step and
                        # poison the row at window-read time.
                        poisoned.append(step_num)
                    it = step_num
                    preempt_now = (self.preempt is not None
                                   and self.preempt.requested)
                    boundary = (
                        (cfg.display and step_num % cfg.display == 0)
                        or (test_batches is not None and cfg.test_interval
                            and step_num % cfg.test_interval == 0)
                        or (cfg.snapshot and step_num % cfg.snapshot == 0)
                        or (step_num - window_start + 1 >= window_cap)
                        or step_num >= num_iters
                        or preempt_now
                    )
                    if not boundary:
                        continue
                    # ---- window boundary: the ONE host sync ----------
                    with allowed():
                        with self._span(
                            "step/window_sync",
                            steps=step_num - window_start + 1,
                        ):
                            host_ring = jax.device_get(ring)
                            ring = self._ring_reset_fn(ring)
                        rows = self._metric_window.read(host_ring)
                        for s in poisoned:
                            rows[s - window_start]["loss"] = \
                                np.float32("nan")
                        # The in-graph counter IS the window-edge trip
                        # check: max_streak == 0 proves every loss in
                        # (or carried into) this window was finite, so
                        # the guard's per-row replay below can be
                        # skipped wholesale.  Host-side poison
                        # (step.nan_loss) is invisible to the device
                        # counter, hence the OR on ``poisoned`` — and
                        # on guard.streak, so an all-finite window
                        # still replays to RESET a streak a previous
                        # window's poison left in flight.
                        nonfinite_seen = bool(poisoned) or \
                            int(host_ring["max_streak"]) > 0 or \
                            (guard is not None and guard.streak > 0)
                        tripped = None
                        for off, row in enumerate(rows):
                            s = window_start + off
                            self._loss_window.append(row["loss"])
                            last = row
                            if guard is not None and nonfinite_seen and \
                                    guard.observe(float(row["loss"])):
                                tripped = s
                                break
                            self._emit_step_row(s, row, log_fn, record_fn)
                        if tripped is not None:
                            # In-graph counter + window replay agreed the
                            # streak crossed patience; the steps already
                            # dispatched past the trip are discarded (the
                            # documented bounded-staleness cost) and the
                            # rollback machinery runs unchanged.
                            controller.drain()
                            it = self._handle_divergence(
                                guard, tripped, log_fn, record_fn
                            )
                            ring = None  # cfg may have been replaced
                            window_start = it + 1
                            poisoned = []
                            continue
                        req = self._take_rollback_request()
                        if req is not None:
                            # Same safe point as the divergence trip:
                            # drain in-flight dispatches, then restore.
                            controller.drain()
                            rolled = self._handle_requested_rollback(
                                req, step_num, log_fn, record_fn)
                            if rolled is not None:
                                it = rolled
                                ring = None  # cfg may have been replaced
                                window_start = it + 1
                                poisoned = []
                                continue
                        self._boundary_actions(step_num, test_batches,
                                               log_fn, record_fn)
                    window_start = step_num + 1
                    poisoned = []
        finally:
            prefetcher.close()
            last = self._flush_pending_window(ring, window_start,
                                              poisoned, last)
            self._train_epilogue()
        return {k: float(v) for k, v in last.items()}

    def _flush_pending_window(self, ring, window_start: int, poisoned,
                              last):
        """Salvage the un-flushed tail of a window on an abnormal exit
        (data exhaustion, a staging-thread error, a raised step error)
        — the synchronous loop would already have emitted these rows,
        and the deferred-emission contract (docs/PIPELINE.md) promises
        only their TIMING differs.  Boundary steps always flush
        in-loop, so a pending tail can never contain a display/test/
        snapshot step: telemetry rows + the loss window are the whole
        debt.  Best-effort — teardown must not mask the in-flight
        exception."""
        if ring is None or self._metric_window is None:
            return last
        try:
            rows = self._metric_window.read(jax.device_get(ring))
            for s in poisoned:
                if 0 <= s - window_start < len(rows):
                    rows[s - window_start]["loss"] = np.float32("nan")
            for off, row in enumerate(rows):
                s = window_start + off
                self._loss_window.append(row["loss"])
                last = row
                self._emit_step_row(s, row)
        except Exception as e:  # noqa: BLE001
            log.error("pending-window flush failed: %s", e)
        return last

    def _handle_divergence(self, guard, step_num: int, log_fn,
                           record_fn) -> int:
        """Guard tripped at ``step_num``: roll back to the newest valid
        snapshot (optionally lr-scaled) or halt.  Returns the iteration
        to continue from."""
        dcfg = self.divergence
        reason = (f"{guard.streak} consecutive non-finite losses "
                  f"at iteration {step_num}")
        if dcfg.action != "rollback" or guard.rollbacks >= dcfg.max_rollbacks:
            why = (reason if dcfg.action != "rollback"
                   else f"{reason} (rollback budget "
                        f"{dcfg.max_rollbacks} exhausted)")
            self._tel_event("divergence_halt", step_num, reason=why)
            raise DivergenceError(f"training diverged: {why}")
        guard.rollbacks += 1
        # A snapshot taken during the non-finite streak captured poisoned
        # params — and so may the one right before it: the first NaN loss
        # at step f implicates the update of step f-1 (finite loss does
        # not guarantee finite grads).  Only snapshots strictly older
        # than f-1 are trustworthy rollback targets.
        max_step = step_num - guard.streak - 1
        guard.streak = 0
        restored = self.restore_auto(max_step=max_step)
        if restored is None:
            raise DivergenceError(
                f"training diverged ({reason}) and no valid snapshot "
                f"at iteration <= {max_step} under "
                f"{self.cfg.snapshot_prefix!r} to roll back to"
            )
        # The excluded snapshots are checksum-valid but NaN-poisoned:
        # left in place, a later crash + --resume auto would restore
        # them newest-first and dive straight back into divergence.
        quarantine_snapshots(self.cfg.snapshot_prefix, max_step)
        resumed = self._post_restore(dcfg.lr_scale)
        msg = (f"divergence: {reason}; rolled back to iteration {resumed} "
               f"({restored}), lr={self.cfg.base_lr:.6g} "
               f"[rollback {guard.rollbacks}/{dcfg.max_rollbacks}]")
        log.warning(msg)
        log_fn(msg)
        self._tel_event("rollback", step_num, to_iteration=resumed,
                        snapshot=restored, base_lr=float(self.cfg.base_lr),
                        rollback=guard.rollbacks)
        if record_fn is not None:
            record_fn({"event": "rollback", "iteration": step_num,
                       "to_iteration": resumed, "snapshot": restored})
        return resumed

    def _post_restore(self, lr_scale: float) -> int:
        """Shared tail of BOTH rollback paths (divergence + requested):
        apply the lr damp — the cfg setter rebuilds schedule + optimizer
        and drops the jitted step, so the scaled lr takes effect at
        recompile — or, cfg unchanged, clear the poisoned loss window by
        hand; then re-anchor fleet span numbering at the restored
        iteration (the next dispatch is resumed+1 again).  One copy, so
        a future field that must reset after a restore cannot miss a
        path."""
        if lr_scale != 1.0:
            self.cfg = dataclasses.replace(
                self.cfg, base_lr=self.cfg.base_lr * lr_scale
            )
        else:
            self._loss_window.clear()
        resumed = self.iteration
        self._step_seq = resumed
        return resumed

    # -- requested rollback (alert→actuation, docs/RESILIENCE.md) ----------

    def request_rollback(self, request: RollbackRequest) -> None:
        """Ask the train loop to roll back at its next safe point — the
        remediation action for health-signal alerts (embedding
        collapse).  Thread-safe: the live-obs tick thread sets it, the
        loop takes it.  A second request before the first is taken
        replaces it (the newer alert context wins)."""
        with self._rollback_lock:
            self._rollback_request = request

    def _take_rollback_request(self) -> Optional[RollbackRequest]:
        if self._rollback_request is None:  # cheap pre-check, hot path
            return None
        with self._rollback_lock:
            req, self._rollback_request = self._rollback_request, None
            return req

    def _handle_requested_rollback(self, req: RollbackRequest,
                                   step_num: int, log_fn,
                                   record_fn) -> Optional[int]:
        """Execute a requested rollback: restore the newest valid
        snapshot COMMITTED before ``req.before_wall_time`` (a snapshot
        captured mid-incident is not a recovery target).  Unlike the
        divergence path this never quarantines (a health-signal
        collapse leaves finite, checksum-honest params — post-mortem
        wants them restorable) and SKIPS gracefully when no qualifying
        snapshot exists: the remediation engine's budget owns retries,
        so a skip is a telemetry event and training continues, never a
        halt.  Returns the resumed iteration, or None on a skip."""
        max_step = step_num - 1
        if req.before_wall_time is not None:
            qualifying = []
            for step, path in list_snapshots(self.cfg.snapshot_prefix):
                if step > max_step:
                    continue
                created = snapshot_info(path)["created"]
                if created is not None and created < req.before_wall_time:
                    qualifying.append(step)
            if not qualifying:
                msg = (f"rollback request ({req.reason}) skipped: no "
                       f"snapshot under {self.cfg.snapshot_prefix!r} "
                       f"predates the incident")
                log.warning(msg)
                log_fn(msg)
                self._tel_event("rollback_skip", step_num,
                                reason=req.reason)
                return None
            max_step = max(qualifying)
        restored = self.restore_auto(max_step=max_step)
        if restored is None:
            msg = (f"rollback request ({req.reason}) skipped: no valid "
                   f"snapshot at iteration <= {max_step}")
            log.warning(msg)
            log_fn(msg)
            self._tel_event("rollback_skip", step_num, reason=req.reason)
            return None
        resumed = self._post_restore(req.lr_scale)
        msg = (f"remediation rollback ({req.reason}): rolled back to "
               f"iteration {resumed} ({restored}), "
               f"lr={self.cfg.base_lr:.6g}")
        log.warning(msg)
        log_fn(msg)
        self._tel_event("rollback", step_num, to_iteration=resumed,
                        snapshot=restored,
                        base_lr=float(self.cfg.base_lr),
                        requested=True, reason=req.reason)
        if record_fn is not None:
            record_fn({"event": "rollback", "iteration": step_num,
                       "to_iteration": resumed, "snapshot": restored,
                       "requested": True})
        return resumed

    # -- checkpointing (Orbax; Caffe snapshot contract) --------------------

    def _ckpt(self):
        if self._checkpointer is None:
            import orbax.checkpoint as ocp

            self._checkpointer = ocp.StandardCheckpointer()
        return self._checkpointer

    def snapshot_path(self, step: int) -> str:
        import os

        prefix = self.cfg.snapshot_prefix
        parent = os.path.dirname(os.path.abspath(prefix))
        os.makedirs(parent, exist_ok=True)
        return os.path.abspath(f"{prefix}iter_{step}.ckpt")

    def save_snapshot(self, step: int) -> str:
        """Commit the snapshot for ``step`` atomically (tmp dir +
        checksum manifest + rename — resilience.snapshot), retrying
        transient I/O under ``snapshot_retry``, then apply retention GC
        (``cfg.snapshot_max_keep``).

        Multi-controller runs cannot use the tmp-dir commit (Orbax's
        ``save`` is a collective every rank must enter with the SAME
        path, and per-rank tmp dirs would race the rename): they rely
        on Orbax's own multihost tmp/rename atomicity on the final
        path, with rank 0 adding the manifest after the save lands — a
        crash in that window leaves a committed-but-manifest-less dir,
        which auto-resume conservatively skips.
        """
        path = self.snapshot_path(step)
        if jax.process_count() > 1:
            with self._span("snapshot", step=step):
                self._ckpt().save(path, self.state, force=True)
                self._ckpt().wait_until_finished()
                if jax.process_index() == 0:
                    write_manifest(path, step, state_checksums(self.state))
                    gc_snapshots(self.cfg.snapshot_prefix,
                                 self.cfg.snapshot_max_keep)
            log.info("snapshot -> %s", path)
            return path

        def on_retry(attempt, delay, exc):
            self._tel_event("retry", step, op="snapshot.save",
                            attempt=attempt, delay_s=round(delay, 3),
                            error=str(exc))

        with self._span("snapshot", step=step):
            commit_snapshot(
                self._ckpt(), path, self.state, step,
                policy=self.snapshot_retry, on_retry=on_retry,
            )
        log.info("snapshot -> %s", path)
        gc_snapshots(self.cfg.snapshot_prefix, self.cfg.snapshot_max_keep)
        return path

    def load_params(self, params, batch_stats=None):
        """Start from externally-loaded parameters (the pretrained-weights
        finetune workflow — e.g. a migrated .caffemodel trunk).

        Structure/shape must match the model's own init tree (enforced by
        the tree_map below — a silent partial load corrupts finetunes);
        values are cast to the model's dtypes.  The optimizer state
        re-initializes (fresh momentum); ``batch_stats`` (BN trunks:
        migrated running mean/var) replace the init stats when given.
        """
        if self.state is None:
            self.init()
        cur = self.state["params"]
        new = jax.tree_util.tree_map(
            lambda c, n: jnp.asarray(np.asarray(n), dtype=c.dtype),
            cur,
            params,
        )
        state = dict(self.state)
        state["params"] = new
        state["opt"] = self.tx.init(new)
        if batch_stats is not None:
            state["batch_stats"] = jax.tree_util.tree_map(
                lambda c, n: jnp.asarray(np.asarray(n), dtype=c.dtype),
                self.state["batch_stats"],
                batch_stats,
            )
        self.state = self._place_state(state)
        return self.state

    def load_caffe_solverstate(self, path: str, model_name: str = "googlenet"):
        """Resume the OPTIMIZER from a Caffe ``.solverstate`` — momentum
        history + iteration, the ``caffe train --snapshot`` semantics
        (solver.prototxt:15-16).  Weights come separately (the paired
        .caffemodel via ``load_params``/--weights); call this after
        them, since ``load_params`` re-initializes the optimizer.

        GoogLeNet trunks only (the reference's flagship,
        def.prototxt:1): history blobs are unnamed and ordered by net
        parameter order, which the GoogLeNet layer map pins down.
        """
        if model_name.lower() != "googlenet":
            # Exactly the plain trunk: the MXU variants (s2d/fused/mxu)
            # and the BN trunk have different param trees the unnamed
            # positional history cannot map onto — and a genuine Caffe
            # solverstate only ever comes from the reference's plain
            # def.prototxt net anyway.  Resume on plain `googlenet`,
            # then switch variants via the weight converters.
            raise NotImplementedError(
                "solverstate migration is defined for the plain "
                f"GoogLeNet trunk only (got model {model_name!r}): "
                "Caffe history blobs are unnamed and positional; resume "
                "with --model googlenet"
            )
        from npairloss_tpu.config.caffemodel import parse_solverstate
        from npairloss_tpu.models.caffe_import import (
            googlenet_momentum_from_history,
        )

        if self.state is None:
            self.init()
        with open(path, "rb") as f:
            st = parse_solverstate(f.read())
        mom, skipped = googlenet_momentum_from_history(
            st["history"], self.state["opt"].momentum_buf
        )
        if skipped:
            log.info(
                "solverstate: skipped %d non-trunk history blobs "
                "(aux-classifier params of the full training net)",
                skipped,
            )
        mom = jax.tree_util.tree_map(
            lambda c, n: jnp.asarray(np.asarray(n), dtype=c.dtype),
            self.state["opt"].momentum_buf,
            mom,
        )
        state = dict(self.state)
        state["opt"] = CaffeSGDState(
            momentum_buf=mom, step=jnp.asarray(int(st["iter"]), jnp.int32)
        )
        self.state = self._place_state(state)
        return int(st["iter"])

    def _resume_rank(self) -> int:
        """This process's rank for multi-writer snapshot coordination:
        jax's own when a multi-controller runtime is up, else the
        declared harness rank (``NPAIRLOSS_FLEET_PROCESS``), else 0.
        Non-zero ranks WAIT on rank 0's manifest instead of reading a
        just-committed multihost save as torn (docs/DISTRIBUTED.md)."""
        from npairloss_tpu.obs.fleet.stamp import resolved_process

        return resolved_process()[0]

    def restore_snapshot(self, path: str):
        """Restore an explicit snapshot path (retrying transient I/O).

        When the snapshot carries a commit manifest, the restored tree
        is checksum-verified against it — a corrupt snapshot raises
        ``SnapshotValidationError`` instead of silently resuming from
        garbage.  Manifest-less dirs (pre-resilience snapshots, raw
        Orbax trees) restore unverified, preserving the old contract —
        but a NON-ZERO rank first waits out the multihost commit race
        (rank 0 writes the manifest after the collective save lands)
        before concluding the dir is legacy.
        """
        if self.state is None:
            self.init()
        self._ckpt().wait_until_finished()
        if self._resume_rank() != 0:
            try:
                validate_snapshot_wait(path, self.snapshot_retry)
            except Exception:  # noqa: BLE001 — verdict below, per contract
                pass

        def do_restore():
            failpoints.fire("snapshot.restore.io")
            return self._ckpt().restore(path, self.state)

        def on_retry(attempt, delay, exc):
            self._tel_event("retry", 0, op="snapshot.restore",
                            attempt=attempt, delay_s=round(delay, 3),
                            error=str(exc))

        state = call_with_retry(
            do_restore, self.snapshot_retry,
            describe=f"snapshot restore ({path})", on_retry=on_retry,
        )
        try:
            manifest = read_manifest(path)
        except FileNotFoundError:
            # Legacy contract: manifest-less dirs (pre-resilience
            # snapshots, raw Orbax trees) restore unverified.
            log.info("restored %s without checksum verification "
                     "(no commit manifest)", path)
        except (OSError, ValueError) as e:
            # A manifest that EXISTS but cannot be read/parsed is
            # corruption — exactly what verification exists to catch.
            raise SnapshotValidationError(
                f"unreadable manifest in {path}: {e}"
            ) from e
        else:
            verify_restored(state, manifest)
        self.state = state
        return self.state

    def restore_auto(self, max_step: Optional[int] = None) -> Optional[str]:
        """Scan ``cfg.snapshot_prefix`` and restore the newest *valid*
        snapshot: manifests are validated newest-first, the restored
        tree checksum-verified, and torn/corrupt candidates skipped with
        a logged reason.  ``max_step`` bounds the candidates (divergence
        rollback must not restore a snapshot captured during the
        non-finite streak).  Returns the restored path, or None (fresh
        start) when no valid snapshot exists."""
        if self.state is None:
            self.init()
        self._ckpt().wait_until_finished()
        prefix = self.cfg.snapshot_prefix
        rank = self._resume_rank()
        for step, path in reversed(list_snapshots(prefix)):
            if max_step is not None and step > max_step:
                continue
            try:
                # A non-zero rank can scan this dir BETWEEN the
                # collective Orbax save landing and rank 0 writing
                # manifest.json; waiting (the shared retry/backoff)
                # turns that race into a pause instead of skipping a
                # perfectly valid snapshot as torn.  Rank 0 never
                # waits: for it a missing manifest IS a torn commit.
                manifest = (validate_snapshot_wait(path,
                                                   self.snapshot_retry)
                            if rank != 0 else validate_snapshot(path))

                def do_restore(path=path):
                    failpoints.fire("snapshot.restore.io")
                    return self._ckpt().restore(path, self.state)

                state = call_with_retry(
                    do_restore, self.snapshot_retry,
                    describe=f"snapshot restore ({path})",
                )
                verify_restored(state, manifest)
            except Exception as e:  # noqa: BLE001 — skip, try the next
                log.warning("resume: skipping snapshot %s: %s", path, e)
                self._tel_event("resume_skip", step, snapshot=path,
                                reason=str(e))
                continue
            self.state = state
            log.info("resume: restored %s (iteration %d)", path, step)
            return path
        log.info("resume: no valid snapshot under prefix %r — starting "
                 "fresh", prefix)
        return None


def restore_for_inference(
    path: str,
    retry: Optional[RetryPolicy] = None,
) -> Dict[str, Any]:
    """Snapshot -> ``{"params", "batch_stats"}`` for the serving path.

    The snapshot->inference direction, split out of the Solver: serving
    (``serve.QueryEngine``) needs the model variables from a committed
    training snapshot but must not drag in the optimizer rebuild, the
    schedule, or a Solver instance.  Raw Orbax restore (no target tree),
    retried like ``Solver.restore_snapshot``, with the params/batch_stats
    SUBSET checksum-verified against the commit manifest — the optimizer
    leaves are skipped both because inference never touches them and
    because the raw restore rehydrates the opt NamedTuple as a plain
    dict, which would shift every keystr.  Manifest-less dirs restore
    unverified (the legacy contract).
    """
    import os

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckpt = ocp.StandardCheckpointer()

    def do_restore():
        failpoints.fire("snapshot.restore.io")
        return ckpt.restore(path)

    state = call_with_retry(
        do_restore, retry if retry is not None else RetryPolicy(),
        describe=f"inference restore ({path})",
    )
    if not isinstance(state, dict) or "params" not in state:
        raise SnapshotValidationError(
            f"{path} does not look like a training snapshot "
            "(no 'params' subtree)"
        )
    infer = {
        "params": state["params"],
        "batch_stats": state.get("batch_stats") or {},
    }
    try:
        manifest = read_manifest(path)
    except FileNotFoundError:
        log.info("restored %s for inference without checksum "
                 "verification (no commit manifest)", path)
    except (OSError, ValueError) as e:
        raise SnapshotValidationError(
            f"unreadable manifest in {path}: {e}"
        ) from e
    else:
        prefixes = ("['params']", "['batch_stats']")
        subset = {
            k: v for k, v in manifest.get("arrays", {}).items()
            if k.startswith(prefixes)
        }
        verify_restored(infer, {"arrays": subset})
    return infer


def snapshot_info(path: str) -> Dict[str, Any]:
    """Freshness identity of a committed snapshot (docs/OBSERVABILITY.md
    §Live observatory): ``{"path", "step", "created"}`` from the commit
    manifest — no array loads, no Solver.  ``step``/``created`` are
    None for manifest-less dirs (pre-resilience snapshots), so the
    serving path can still report WHICH snapshot it restored even when
    it cannot date it."""
    import os

    out: Dict[str, Any] = {
        "path": os.path.abspath(path), "step": None, "created": None,
    }
    try:
        manifest = read_manifest(path)
    except (OSError, ValueError):
        return out
    step = manifest.get("step")
    created = manifest.get("created")
    if isinstance(step, int):
        out["step"] = step
    if isinstance(created, (int, float)):
        out["created"] = float(created)
    return out


def _fmt(metrics: Dict[str, float]) -> str:
    return " ".join(f"{k}={float(v):.4g}" for k, v in sorted(metrics.items()))
