from npairloss_tpu.train.optim import caffe_sgd, lr_schedule
from npairloss_tpu.train.solver import (
    Solver,
    SolverConfig,
    restore_for_inference,
    snapshot_info,
)
