"""Caffe-semantics SGD and learning-rate policies.

The reference trains with the Caffe solver (usage/solver.prototxt): SGD with
momentum where the learning rate is folded in BEFORE momentum accumulation —
    v <- momentum * v + lr * (grad + weight_decay * w);   w <- w - v
which differs from torch/optax SGD (lr applied after the momentum buffer)
whenever the schedule changes lr mid-run.  ``caffe_sgd`` reproduces the
Caffe trajectory exactly; the full Caffe lr-policy family is implemented.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax


def lr_schedule(
    policy: str,
    base_lr: float,
    gamma: float = 0.1,
    stepsize: int = 100000,
    power: float = 1.0,
    max_iter: int = 0,
    stepvalues: Sequence[int] = (),
) -> Callable[[jax.Array], jax.Array]:
    """Caffe lr_policy -> rate(step).  Policies: fixed, step, exp, inv,
    multistep, poly, sigmoid (the documented Caffe set; solver.prototxt:8-10
    uses ``step`` with stepsize 10000, gamma 0.5)."""
    base = jnp.float32(base_lr)
    g = jnp.float32(gamma)

    if policy == "fixed":
        return lambda step: jnp.broadcast_to(base, ())
    if policy == "step":
        return lambda step: base * g ** jnp.floor(step / stepsize)
    if policy == "exp":
        return lambda step: base * g**step
    if policy == "inv":
        return lambda step: base * (1.0 + g * step) ** (-power)
    if policy == "multistep":
        sv = jnp.asarray(list(stepvalues) or [jnp.iinfo(jnp.int32).max], jnp.int32)
        return lambda step: base * g ** (step >= sv).sum().astype(jnp.float32)
    if policy == "poly":
        if max_iter <= 0:
            raise ValueError("lr_policy 'poly' requires max_iter > 0")
        # Clamp like Caffe so steps past max_iter don't go negative/NaN.
        return lambda step: base * (
            1.0 - jnp.minimum(jnp.float32(step), max_iter) / max_iter
        ) ** power
    if policy == "sigmoid":
        return lambda step: base / (1.0 + jnp.exp(-g * (step - stepsize)))
    raise ValueError(f"unknown lr_policy {policy!r}")


class CaffeSGDState(NamedTuple):
    momentum_buf: optax.Updates
    step: jax.Array


def caffe_sgd(
    rate_fn: Callable[[jax.Array], jax.Array],
    momentum: float = 0.9,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """SGD with lr-inside-momentum semantics (see module docstring)."""

    def init(params):
        return CaffeSGDState(
            momentum_buf=jax.tree_util.tree_map(jnp.zeros_like, params),
            step=jnp.zeros((), jnp.int32),
        )

    def update(grads, state, params=None):
        lr = rate_fn(state.step)
        mu = jnp.float32(momentum)
        wd = jnp.float32(weight_decay)

        def upd(v, grad, w):
            grad = grad.astype(jnp.float32)
            if params is not None and weight_decay:
                grad = grad + wd * w.astype(jnp.float32)
            return mu * v + lr * grad

        if params is not None:
            new_buf = jax.tree_util.tree_map(upd, state.momentum_buf, grads, params)
        else:
            new_buf = jax.tree_util.tree_map(
                lambda v, grad: mu * v + lr * grad.astype(jnp.float32),
                state.momentum_buf,
                grads,
            )
        updates = jax.tree_util.tree_map(lambda v: -v, new_buf)
        return updates, CaffeSGDState(new_buf, state.step + 1)

    return optax.GradientTransformation(init, update)
