"""Caffe-semantics SGD and learning-rate policies.

The reference trains with the Caffe solver (usage/solver.prototxt): SGD with
momentum where the learning rate is folded in BEFORE momentum accumulation —
    v <- momentum * v + lr * (grad + weight_decay * w);   w <- w - v
which differs from torch/optax SGD (lr applied after the momentum buffer)
whenever the schedule changes lr mid-run.  ``caffe_sgd`` reproduces the
Caffe trajectory exactly; the full Caffe lr-policy family is implemented.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax


def lr_schedule(
    policy: str,
    base_lr: float,
    gamma: float = 0.1,
    stepsize: int = 100000,
    power: float = 1.0,
    max_iter: int = 0,
    stepvalues: Sequence[int] = (),
) -> Callable[[jax.Array], jax.Array]:
    """Caffe lr_policy -> rate(step).  Policies: fixed, step, exp, inv,
    multistep, poly, sigmoid (the documented Caffe set; solver.prototxt:8-10
    uses ``step`` with stepsize 10000, gamma 0.5)."""
    base = jnp.float32(base_lr)
    g = jnp.float32(gamma)

    if policy == "fixed":
        return lambda step: jnp.broadcast_to(base, ())
    if policy == "step":
        return lambda step: base * g ** jnp.floor(step / stepsize)
    if policy == "exp":
        return lambda step: base * g**step
    if policy == "inv":
        return lambda step: base * (1.0 + g * step) ** (-power)
    if policy == "multistep":
        sv = jnp.asarray(list(stepvalues) or [jnp.iinfo(jnp.int32).max], jnp.int32)
        return lambda step: base * g ** (step >= sv).sum().astype(jnp.float32)
    if policy == "poly":
        if max_iter <= 0:
            raise ValueError("lr_policy 'poly' requires max_iter > 0")
        # Clamp like Caffe so steps past max_iter don't go negative/NaN.
        return lambda step: base * (
            1.0 - jnp.minimum(jnp.float32(step), max_iter) / max_iter
        ) ** power
    if policy == "sigmoid":
        return lambda step: base / (1.0 + jnp.exp(-g * (step - stepsize)))
    raise ValueError(f"unknown lr_policy {policy!r}")


class CaffeSGDState(NamedTuple):
    momentum_buf: optax.Updates
    step: jax.Array


def _conv_bias_mask(tree):
    """Matching-structure pytree of bools marking Caffe 'second blob'
    biases: leaves keyed ``bias`` whose PARENT also holds a ``kernel``
    — true for conv/dense layers under any module name, false for
    BatchNorm/LayerNorm beta (bias + scale, no kernel), which Caffe's
    BN/Scale layers cover with their own param blocks (typically
    lr_mult 1) — the conv recipe must not leak onto normalization
    parameters.  (A name-prefix check was tried first and silently
    missed custom module names.)
    """
    from collections.abc import Mapping

    if not isinstance(tree, Mapping):
        return False  # bare-array "tree": nothing to classify
    has_kernel = "kernel" in tree
    out = {}
    for k, v in tree.items():
        if isinstance(v, Mapping):
            out[k] = _conv_bias_mask(v)
        else:
            out[k] = bool(has_kernel and k == "bias")
    return out


def caffe_sgd(
    rate_fn: Callable[[jax.Array], jax.Array],
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    param_mults: Optional[tuple] = None,
) -> optax.GradientTransformation:
    """SGD with lr-inside-momentum semantics (see module docstring).

    ``param_mults`` = ``((w_lr_mult, w_decay_mult), (b_lr_mult,
    b_decay_mult))`` reproduces Caffe's per-parameter ``param { lr_mult
    decay_mult }`` blocks: each blob's local rate is ``lr * lr_mult``
    and local decay ``weight_decay * decay_mult``, with the weight/bias
    split by tree key (Caffe's positional blob 0/blob 1).  The
    reference's template uses 1/1 for weights and 2/0 for biases —
    double bias lr, no bias decay (usage/def.prototxt:90-97).  ``None``
    (default) keeps uniform treatment.
    """

    def init(params):
        return CaffeSGDState(
            momentum_buf=jax.tree_util.tree_map(jnp.zeros_like, params),
            step=jnp.zeros((), jnp.int32),
        )

    if param_mults is not None:
        (w_lr, w_dk), (b_lr, b_dk) = (
            (float(param_mults[0][0]), float(param_mults[0][1])),
            (float(param_mults[1][0]), float(param_mults[1][1])),
        )
    else:
        (w_lr, w_dk), (b_lr, b_dk) = (1.0, 1.0), (1.0, 1.0)

    def update(grads, state, params=None):
        lr = rate_fn(state.step)
        mu = jnp.float32(momentum)
        wd = jnp.float32(weight_decay)
        mask = _conv_bias_mask(state.momentum_buf)

        def upd(v, grad, w, is_bias):
            lmul, dmul = (b_lr, b_dk) if is_bias else (w_lr, w_dk)
            grad = grad.astype(jnp.float32)
            if w is not None and weight_decay and dmul:
                grad = grad + wd * jnp.float32(dmul) * w.astype(
                    jnp.float32)
            return mu * v + lr * jnp.float32(lmul) * grad

        if params is not None:
            new_buf = jax.tree_util.tree_map(
                upd, state.momentum_buf, grads, params, mask
            )
        else:
            new_buf = jax.tree_util.tree_map(
                lambda v, grad, is_bias: upd(v, grad, None, is_bias),
                state.momentum_buf,
                grads,
                mask,
            )
        updates = jax.tree_util.tree_map(lambda v: -v, new_buf)
        return updates, CaffeSGDState(new_buf, state.step + 1)

    return optax.GradientTransformation(init, update)
