"""Deterministic traffic generator — a compressed production "day".

The gameday (docs/RESILIENCE.md §8) drives the composed serving system
with load that looks like production, compressed into a CI-sized
window, and — because every chaos run must be replayable — the whole
plan is a pure function of the seed: ``generate(cfg)`` with the same
:class:`TrafficConfig` yields the same events byte-for-byte
(``plan_digest`` pins it; tests/test_gameday.py asserts identity).

Shape of the day:

  * **diurnal ramp** — Poisson arrivals whose instantaneous rate
    follows ``base_qps + (peak_qps - base_qps) * sin^2(pi * t / D)``:
    quiet at the window's edges, peak mid-window;
  * **bursts** — ``bursts`` short windows at ``burst_qps``, sized past
    the admission tier's capacity so load shedding MUST engage (the
    sheds land in ``rejected``, never in drops);
  * **Zipf hot-query skew** — query keys drawn from a Zipf law
    (weight ``1/k**zipf_s``) over the catalog: a few keys dominate,
    the tail is long — the realistic cache/batching shape;
  * **gallery-growth ingest** — a scripted stream of ``add()``
    batches, one every ``ingest_every_s`` seconds, each meant to be
    committed as a new index snapshot for ``--watch-snapshots`` to
    hot-swap in.

Stdlib-only on purpose: the generator must import (and the determinism
tests must run) without jax/numpy, and the verdict contract records the
plan digest, so this module is part of the jax-free audit surface.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import math
import random
from typing import Any, Dict, List, Tuple


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """Knobs for one compressed day.  Validation is loud: a silently
    clamped rate would change the plan a seed reproduces."""

    seed: int = 0
    duration_s: float = 60.0
    base_qps: float = 4.0
    peak_qps: float = 16.0
    burst_qps: float = 60.0
    bursts: int = 2
    burst_s: float = 2.0
    catalog: int = 256
    zipf_s: float = 1.1
    ingest_every_s: float = 0.0  # 0 = no ingest stream
    ingest_rows: int = 16
    # Multi-tenant skew (docs/SERVING.md §Multi-tenant): () = the
    # single-tenant day, unchanged byte for byte.  With a weight table,
    # every query also draws a tenant id — from a SEPARATE rng stream,
    # so the arrival times and keys of a tenantless plan at the same
    # seed are untouched.  Inside burst windows the hot tenant's weight
    # is multiplied by ``hot_burst_factor``: the noisy-neighbor shape
    # (one tenant surges, the others keep their baseline rates).
    tenants: Tuple[Tuple[str, float], ...] = ()
    hot_tenant: str = ""
    hot_burst_factor: float = 1.0

    def __post_init__(self):
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s}")
        if not (0 < self.base_qps <= self.peak_qps):
            raise ValueError(
                "need 0 < base_qps <= peak_qps, got "
                f"{self.base_qps}/{self.peak_qps}")
        if self.bursts and self.burst_qps < self.peak_qps:
            raise ValueError(
                "burst_qps must exceed peak_qps (a burst below the "
                f"diurnal peak is not a burst), got {self.burst_qps} "
                f"< {self.peak_qps}")
        if self.bursts < 0 or self.burst_s <= 0:
            raise ValueError(
                f"bad burst spec: bursts={self.bursts} "
                f"burst_s={self.burst_s}")
        if self.bursts * self.burst_s >= self.duration_s:
            raise ValueError(
                "bursts cover the whole window "
                f"({self.bursts} x {self.burst_s}s >= "
                f"{self.duration_s}s) — nothing left to be the day")
        if self.catalog < 2:
            raise ValueError(f"catalog must be >= 2, got {self.catalog}")
        if self.zipf_s <= 0:
            raise ValueError(f"zipf_s must be > 0, got {self.zipf_s}")
        if self.ingest_every_s < 0 or self.ingest_rows <= 0:
            raise ValueError(
                f"bad ingest spec: every={self.ingest_every_s} "
                f"rows={self.ingest_rows}")
        if self.hot_tenant and not self.tenants:
            raise ValueError(
                f"hot_tenant {self.hot_tenant!r} needs a tenants "
                "weight table")
        if self.tenants:
            names = [t for t, _ in self.tenants]
            if len(set(names)) != len(names) or not all(names):
                raise ValueError(
                    f"tenant ids must be distinct and non-empty, "
                    f"got {names}")
            if any(w <= 0 for _, w in self.tenants):
                raise ValueError(
                    f"tenant weights must be > 0, got {self.tenants}")
            if self.hot_tenant and self.hot_tenant not in names:
                raise ValueError(
                    f"hot_tenant {self.hot_tenant!r} not in the "
                    f"weight table {names}")
        if self.hot_burst_factor < 1.0:
            raise ValueError(
                f"hot_burst_factor must be >= 1 (a burst that SHRINKS "
                f"the hot tenant is not a burst), got "
                f"{self.hot_burst_factor}")
        if self.hot_burst_factor > 1.0 and not self.hot_tenant:
            raise ValueError("hot_burst_factor needs hot_tenant")


@dataclasses.dataclass(frozen=True)
class QueryEvent:
    """One query arrival: ``t`` seconds into the window, a stable qid,
    the Zipf-drawn catalog key it asks about, and (multi-tenant plans
    only) the tenant the query belongs to."""

    t: float
    qid: int
    key: int
    tenant: Any = None  # Optional[str]; None on single-tenant plans


@dataclasses.dataclass(frozen=True)
class IngestEvent:
    """One gallery-growth batch: ``rows`` new vectors to ``add()`` and
    commit as index snapshot ``commit_id``."""

    t: float
    rows: int
    commit_id: int


@dataclasses.dataclass(frozen=True)
class TrafficPlan:
    cfg: TrafficConfig
    queries: Tuple[QueryEvent, ...]
    ingest: Tuple[IngestEvent, ...]
    burst_windows: Tuple[Tuple[float, float], ...]

    def in_burst(self, t: float) -> bool:
        return any(a <= t < b for a, b in self.burst_windows)


def _burst_windows(cfg: TrafficConfig) -> Tuple[Tuple[float, float], ...]:
    """Evenly spaced burst centers, clear of the window edges."""
    out = []
    for i in range(cfg.bursts):
        center = cfg.duration_s * (i + 1) / (cfg.bursts + 1)
        out.append((center - cfg.burst_s / 2.0,
                    center + cfg.burst_s / 2.0))
    return tuple(out)


def _rate(cfg: TrafficConfig, windows, t: float) -> float:
    for a, b in windows:
        if a <= t < b:
            return cfg.burst_qps
    x = math.sin(math.pi * t / cfg.duration_s)
    return cfg.base_qps + (cfg.peak_qps - cfg.base_qps) * x * x


class _ZipfSampler:
    """Zipf draw via bisect on the cumulative harmonic weights —
    O(log catalog) per draw, exact, and deterministic under the plan's
    ``random.Random``."""

    def __init__(self, catalog: int, s: float):
        acc, cum = 0.0, []
        for k in range(1, catalog + 1):
            acc += 1.0 / (k ** s)
            cum.append(acc)
        self._cum = cum
        self._total = acc

    def draw(self, rng: random.Random) -> int:
        return bisect.bisect_left(self._cum, rng.random() * self._total)


def generate(cfg: TrafficConfig) -> TrafficPlan:
    """The whole day, as a pure function of ``cfg`` (seed included)."""
    rng = random.Random(cfg.seed)
    windows = _burst_windows(cfg)
    zipf = _ZipfSampler(cfg.catalog, cfg.zipf_s)
    queries: List[QueryEvent] = []
    t, qid = 0.0, 0
    while True:
        # Inhomogeneous Poisson by stepping at the current local rate;
        # the rate changes slowly relative to the inter-arrival gaps
        # (bursts are whole windows, the diurnal curve is smooth), so
        # the local-rate approximation keeps the window statistics the
        # tests pin.
        t += rng.expovariate(_rate(cfg, windows, t))
        if t >= cfg.duration_s:
            break
        queries.append(QueryEvent(t=t, qid=qid, key=zipf.draw(rng)))
        qid += 1
    if cfg.tenants:
        # Tenant draws ride their OWN rng stream (seed + 2): adding or
        # removing the weight table never perturbs the arrival times
        # and keys above, and a tenantless plan at the same seed stays
        # byte-identical.
        trng = random.Random(cfg.seed + 2)
        names = [t for t, _ in cfg.tenants]
        base_w = [w for _, w in cfg.tenants]
        burst_w = [w * (cfg.hot_burst_factor if name == cfg.hot_tenant
                        else 1.0)
                   for name, w in cfg.tenants]
        queries = [
            dataclasses.replace(
                q, tenant=trng.choices(
                    names,
                    weights=(burst_w
                             if any(a <= q.t < b for a, b in windows)
                             else base_w))[0])
            for q in queries]
    ingest: List[IngestEvent] = []
    if cfg.ingest_every_s > 0:
        commit_id, t = 0, cfg.ingest_every_s
        while t < cfg.duration_s:
            ingest.append(IngestEvent(t=t, rows=cfg.ingest_rows,
                                      commit_id=commit_id))
            commit_id += 1
            t += cfg.ingest_every_s
    return TrafficPlan(cfg=cfg, queries=tuple(queries),
                       ingest=tuple(ingest), burst_windows=windows)


# -- canonical serialization (the determinism contract) ----------------------


def plan_lines(plan: TrafficPlan) -> List[str]:
    """Canonical JSON lines for the plan — sorted keys, fixed float
    formatting via json's repr, one event per line.  Two runs of the
    same seed produce the same list, byte for byte."""
    cfg_d = dataclasses.asdict(plan.cfg)
    if not cfg_d.get("tenants"):
        # A tenantless plan serializes (and so digests) exactly as it
        # did before the tenant fields existed — replayability of the
        # recorded single-tenant days is part of the contract.
        for key in ("tenants", "hot_tenant", "hot_burst_factor"):
            cfg_d.pop(key, None)
    else:
        cfg_d["tenants"] = [list(t) for t in cfg_d["tenants"]]
    lines = [json.dumps(
        {"cfg": cfg_d,
         "bursts": [list(w) for w in plan.burst_windows]},
        sort_keys=True)]
    lines += [json.dumps(
        {k: v for k, v in dataclasses.asdict(q).items()
         if not (k == "tenant" and v is None)}, sort_keys=True)
        for q in plan.queries]
    lines += [json.dumps(dataclasses.asdict(i), sort_keys=True)
              for i in plan.ingest]
    return lines


def plan_digest(plan: TrafficPlan) -> str:
    """sha256 over the canonical lines — the identity the verdict
    records, so a replay can prove it drove the same day."""
    h = hashlib.sha256()
    for line in plan_lines(plan):
        h.update(line.encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()


def plan_stats(plan: TrafficPlan) -> Dict[str, Any]:
    """Summary statistics (for the verdict's traffic block and the
    statistical pins): totals, burst-window rate, hot-key share."""
    cfg = plan.cfg
    n_burst = sum(1 for q in plan.queries if plan.in_burst(q.t))
    burst_span = sum(b - a for a, b in plan.burst_windows)
    counts: Dict[int, int] = {}
    for q in plan.queries:
        counts[q.key] = counts.get(q.key, 0) + 1
    top_key, top_n = (max(counts.items(), key=lambda kv: kv[1])
                      if counts else (0, 0))
    by_tenant: Dict[str, Dict[str, int]] = {}
    for q in plan.queries:
        if q.tenant is None:
            continue
        row = by_tenant.setdefault(q.tenant, {"queries": 0, "burst": 0})
        row["queries"] += 1
        if plan.in_burst(q.t):
            row["burst"] += 1
    return {
        **({"tenants": by_tenant} if by_tenant else {}),
        "queries": len(plan.queries),
        "ingest_commits": len(plan.ingest),
        "burst_queries": n_burst,
        "burst_rate_qps": (n_burst / burst_span) if burst_span else 0.0,
        "top_key": top_key,
        "top_key_share": (top_n / len(plan.queries)
                          if plan.queries else 0.0),
        "distinct_keys": len(counts),
        "sha256": plan_digest(plan),
        "seed": cfg.seed,
        "duration_s": cfg.duration_s,
    }
