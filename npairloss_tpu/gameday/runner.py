"""Gameday runner — one supervised chaos window over the composed stack.

Launches the production shape as one process group — a trainer
snapshotting continuously (``--resume auto``, the supervisor-relaunch
contract), a replicated serving tier (``--live-obs --remediate
--watch-snapshots --index-prefix --explicit-drops``, SLO admission,
shadow scoring), and the offline watch evaluator following the same
telemetry — then drives the deterministic traffic plan
(gameday/traffic.py) through it while the chaos schedule
(gameday/schedule.py) injects faults: failpoints armed via
``NPAIRLOSS_FAILPOINTS`` in each child's environment, signals delivered
at their scripted offsets (SIGTERM mid-stream relaunches the trainer;
SIGKILL cold-restarts the serving tier from its published artifacts +
WAL, the durable-ingest drill of docs/RESILIENCE.md §Durability).

At the end it collects every artifact — answers, alert logs,
remediation audits, quality windows, metric rows, the fleet report,
the drain summary — and hands them to gameday/verdict.py, writing the
``npairloss-gameday-v1`` report to ``<out>/gameday.json``.

This module runs the composed system, so unlike the verdict it may
import numpy and the package freely; everything it feeds the verdict
is plain dicts/lists.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from npairloss_tpu.gameday import schedule as chaos
from npairloss_tpu.gameday import traffic as tg
from npairloss_tpu.gameday import verdict as gv

log = logging.getLogger("npairloss_tpu.gameday")

# SLO targets the run arms; the verdict judges against the SAME numbers
# (one source of truth — runner passes them through to the report).
P99_TARGET_MS = 150.0
RECALL_FLOOR = 0.9
MODEL_STALENESS_S = 6.0
INDEX_STALENESS_S = 30.0
MIN_HOT_SWAPS = 3


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f)


def _child_env(failpoints_spec: str = "") -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("NPAIRLOSS_FAILPOINTS", None)
    if failpoints_spec:
        env["NPAIRLOSS_FAILPOINTS"] = failpoints_spec
    return env


def _jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail — the writer was SIGTERMed
    return out


def _count_fires(paths: Sequence[str]) -> Dict[str, int]:
    """``failpoint fired: <name>`` occurrences across the child logs —
    the injection evidence the verdict reconciles declarations
    against."""
    fires: Dict[str, int] = {}
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                marker = "failpoint fired: "
                idx = line.find(marker)
                if idx >= 0:
                    name = line[idx + len(marker):].strip()
                    fires[name] = fires.get(name, 0) + 1
    return fires


class GamedayError(RuntimeError):
    """The run itself broke (a child died wrong, setup failed) — as
    opposed to a clean run whose verdict failed."""


class _Supervisor:
    """The process group: launch, signal, drain, never leak."""

    def __init__(self):
        self.procs: Dict[str, subprocess.Popen] = {}
        self.files: List[Any] = []

    def open(self, path: str, mode: str = "wb"):
        f = open(path, mode)
        self.files.append(f)
        return f

    def launch(self, name: str, cmd: List[str], *, env: Dict[str, str],
               stdout, stderr, stdin=None) -> subprocess.Popen:
        log.info("gameday: launching %s: %s", name, " ".join(cmd))
        proc = subprocess.Popen(cmd, env=env, stdin=stdin,
                                stdout=stdout, stderr=stderr,
                                cwd=_repo_root())
        self.procs[name] = proc
        return proc

    def cleanup(self):
        for name, proc in self.procs.items():
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        for f in self.files:
            try:
                f.close()
            except OSError:
                pass


def _python() -> List[str]:
    return [sys.executable, "-m", "npairloss_tpu"]


def _setup_workspace(out: str, cfg: tg.TrafficConfig):
    """Gallery, initial index commit, solver config, SLO/policy
    tables.  Returns (emb, labels, solver_path)."""
    for sub in ("idx", "snap", "serve_tel", "train_tel"):
        os.makedirs(os.path.join(out, sub), exist_ok=True)
    rng = np.random.default_rng(cfg.seed)
    emb = rng.standard_normal((cfg.catalog, 64)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    labels = (np.arange(cfg.catalog) % 16).astype(np.int32)

    from npairloss_tpu.serve.index import GalleryIndex

    index = GalleryIndex.build(emb, labels, normalize=False)
    index.save(os.path.join(out, "idx", "g_0000.gidx"))

    solver = os.path.join(out, "solver.prototxt")
    with open(solver, "w", encoding="utf-8") as f:
        f.write(
            'net: "examples/tiny_net.prototxt"\n'
            "base_lr: 0.05\n"
            'lr_policy: "fixed"\n'
            "momentum: 0.9\n"
            "max_iter: 100000\n"
            "display: 0\n"
            "test_interval: 0\n"
            "test_iter: 0\n"
            "snapshot: 40\n"
            f'snapshot_prefix: "{out}/snap/m_"\n'
        )

    _write_json(os.path.join(out, "slo.json"), {"slos": [
        {"name": "model_staleness", "metric": "serve_model_age_s",
         "op": "<=", "target": MODEL_STALENESS_S, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "warning"},
        {"name": "index_staleness", "metric": "serve_index_age_s",
         "op": "<=", "target": INDEX_STALENESS_S, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "warning"},
        {"name": "serve_p99", "metric": "serve_p99_ms", "op": "<=",
         "target": P99_TARGET_MS, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "critical"},
        {"name": "serve_recall_floor", "metric": "serve_recall_at_10",
         "op": ">=", "target": RECALL_FLOOR, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "critical"},
    ]})
    # Generous budgets: early hot-swap attempts legitimately fail with
    # NothingNewer while the freshly-launched trainer is still
    # importing — the policy must retry past that window.
    _write_json(os.path.join(out, "rem.json"), {"policies": [
        {"name": "hotswap_model", "slo": "model_staleness",
         "action": "snapshot_hotswap", "cooldown_s": 3.0,
         "max_attempts": 10},
        {"name": "hotswap_index", "slo": "index_staleness",
         "action": "snapshot_hotswap", "cooldown_s": 3.0,
         "max_attempts": 10},
        {"name": "load_shed", "slo": "serve_p99", "action": "load_shed",
         "cooldown_s": 6.0, "max_attempts": 4},
    ]})
    _write_json(os.path.join(out, "train_slo.json"), {"slos": [
        {"name": "embedding_collapse",
         "metric": "train_an_threshold_mean", "op": "<=",
         "target": 0.98, "window_s": 2.0, "burn_threshold": 0.5,
         "min_samples": 3, "severity": "warning"},
    ]})
    _write_json(os.path.join(out, "train_rem.json"), {"policies": [
        {"name": "trainer_rollback", "slo": "embedding_collapse",
         "action": "trainer_rollback", "cooldown_s": 6.0,
         "max_attempts": 5},
    ]})
    return emb, labels, solver


def _train_cmd(solver: str, out: str) -> List[str]:
    return _python() + [
        "train", "--solver", solver, "--model", "mlp", "--synthetic",
        "--resume", "auto", "--health-metrics",
        # Retention GC is a CLI knob, not a Caffe solver field — the
        # prototxt parser would silently drop it, and a 75s compressed
        # day at CPU step rates commits hundreds of snapshots.
        "--snapshot-keep", "10",
        "--telemetry-dir", os.path.join(out, "train_tel"),
        "--live-obs", "--slo-config", os.path.join(out, "train_slo.json"),
        "--slo-tick", "0.2", "--remediate",
        "--remediation-config", os.path.join(out, "train_rem.json"),
    ]


def _serve_cmd(out: str, replicas: int) -> List[str]:
    return _python() + [
        "serve", "--index-prefix", os.path.join(out, "idx", "g_"),
        "--snapshot", os.path.join(out, "boot", "m_iter_40.ckpt"),
        "--model", "mlp", "--input-size", "8",
        "--watch-snapshots", os.path.join(out, "snap", "m_"),
        "--compile-cache", os.path.join(out, "xla_cache"),
        "--top-k", "10", "--buckets", "1", "--deadline-ms", "1",
        "--max-queue", "64", "--replicas", str(replicas),
        "--admission", "slo", "--admission-slos", "serve_p99",
        "--explicit-drops", "--metrics-window", "4",
        "--shadow-rate", "1", "--shadow-window", "4",
        "--telemetry-dir", os.path.join(out, "serve_tel"),
        "--live-obs", "--slo-config", os.path.join(out, "slo.json"),
        "--slo-tick", "0.2", "--remediate",
        "--remediation-config", os.path.join(out, "rem.json"),
        # Per-query tracing: the p99-attribution verdict check reads
        # the qtrace_dominant window rows and the qtrace.json reroute
        # counters this arms (docs/OBSERVABILITY.md §Query tracing).
        "--qtrace", "--qtrace-slo-ms", str(P99_TARGET_MS),
        # Durable ingest (docs/RESILIENCE.md §Durability): the gallery
        # growth stream rides stdin through the WAL, and the SIGKILL
        # drill's cold restart recovers from this directory + the
        # published checkpoints alone.  Checkpoints land under the
        # same watched prefix, so hot-swap feeds on them too.
        "--wal-dir", os.path.join(out, "wal"),
        "--wal-flush-ms", "2", "--wal-checkpoint-every", "4",
    ]


def _send(io: Dict[str, Any], line: bytes,
          deadline_s: float = 20.0) -> bool:
    """Write one line to the serve stdin currently installed in ``io``
    — shared by the feeder and the ingester, so the lock also keeps
    their lines whole.  A broken pipe means the tier was SIGKILLed;
    retry against whatever stdin the supervisor installs at relaunch
    (the host-crash drill's client-side contract: the stream resumes,
    it does not abort).  False when the gap outlives the deadline."""
    t_end = time.monotonic() + deadline_s
    while True:
        try:
            with io["lock"]:
                stdin = io["stdin"]
                stdin.write(line)
                stdin.flush()
            return True
        except (BrokenPipeError, ValueError, OSError):
            if time.monotonic() >= t_end:
                return False
            time.sleep(0.2)


def _feed(plan: tg.TrafficPlan, emb: np.ndarray, io: Dict[str, Any],
          t0: float, state: Dict[str, Any],
          tenant_embs: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Pace the plan's query events against the monotonic clock and
    write them to the tier's stdin.  Writes may block on pipe
    backpressure while the tier warms or degrades — that only delays
    later events, it never reorders or drops them.  Multi-tenant plans
    stamp each record with its tenant and draw the query vector from
    THAT tenant's gallery (``tenant_embs``); tenantless plans keep the
    pre-tenant line shape byte for byte."""
    for ev in plan.queries:
        wait = (t0 + ev.t) - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        src = (emb if ev.tenant is None
               else (tenant_embs or {})[ev.tenant])
        req = {"id": ev.qid,
               "embedding": src[ev.key % src.shape[0]].tolist()}
        if ev.tenant is not None:
            req["tenant"] = ev.tenant
        line = json.dumps(req)
        if not _send(io, line.encode("utf-8") + b"\n"):
            state["feed_error"] = f"serve stdin broke at qid {ev.qid}"
            return
        state["fed"] = state.get("fed", 0) + 1


def _ingest(plan: tg.TrafficPlan, emb: np.ndarray,
            labels: np.ndarray, io: Dict[str, Any], t0: float,
            state: Dict[str, Any]) -> None:
    """The gallery-growth stream, riding the DURABLE ingest path: each
    scripted event becomes a stdin ingest record the tier must
    WAL-append + fsync before acking; the vectors reach the served
    index via published checkpoints + hot-swap (the remediation's food
    supply, same as the old out-of-band commits).  Every batch sent is
    kept in ``state["ingest_sent"]`` — the oracle the host-crash
    verdict replays the final artifacts against."""
    cfg = plan.cfg
    rng = np.random.default_rng(cfg.seed + 1)
    dim = emb.shape[1]
    for ev in plan.ingest:
        wait = (t0 + ev.t) - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        new = rng.standard_normal((ev.rows, dim)).astype(np.float32)
        new /= np.linalg.norm(new, axis=1, keepdims=True)
        new_labels = (np.arange(ev.rows) % 16).astype(np.int32)
        # Ids far above the catalog range, strided so batches can
        # never collide — replay determinism needs the CLIENT to own
        # identity (the WAL forbids auto-assignment).
        ids = [10_000_000 + ev.commit_id * 10_000 + j
               for j in range(ev.rows)]
        rid = f"ing-{ev.commit_id}"
        line = json.dumps({"id": rid, "ingest": {
            "ids": ids, "labels": new_labels.tolist(),
            "embeddings": new.tolist()}})
        if not _send(io, line.encode("utf-8") + b"\n"):
            state["ingest_error"] = f"serve stdin broke at {rid}"
            return
        state.setdefault("ingest_sent", {})[rid] = {"ids": ids,
                                                    "emb": new}
        state["ingest_commits"] = state.get("ingest_commits", 0) + 1


def run_gameday(out: str, *, seed: int = 0, duration_s: float = 75.0,
                schedule_path: Optional[str] = None,
                replicas: int = 2) -> Dict[str, Any]:
    """The whole gameday: setup, launch, drive, drain, verdict.
    Returns the ``npairloss-gameday-v1`` report (also written to
    ``<out>/gameday.json``)."""
    out = os.path.abspath(out)
    os.makedirs(out, exist_ok=True)
    entries = (chaos.load_schedule(schedule_path) if schedule_path
               else chaos.default_schedule(duration_s))
    cfg = tg.TrafficConfig(seed=seed, duration_s=duration_s,
                           base_qps=6.0, peak_qps=14.0, burst_qps=45.0,
                           bursts=2, burst_s=3.0, catalog=256,
                           zipf_s=1.1, ingest_every_s=10.0,
                           ingest_rows=16)
    plan = tg.generate(cfg)
    with open(os.path.join(out, "traffic.jsonl"), "w",
              encoding="utf-8") as f:
        f.write("\n".join(tg.plan_lines(plan)) + "\n")
    emb, labels, solver = _setup_workspace(out, cfg)

    sup = _Supervisor()
    state: Dict[str, Any] = {"fed": 0}
    trainer_exits: List[int] = []
    try:
        # Phase 0: one short run commits the INITIAL snapshot the
        # server restores (and the freshness clock starts from).
        seed_log = os.path.join(out, "seed.log")
        with open(seed_log, "wb") as f:
            rc = subprocess.call(
                _python() + ["train", "--solver", solver, "--model",
                             "mlp", "--synthetic", "--max_iter", "40"],
                env=_child_env(), stdout=f, stderr=subprocess.STDOUT,
                cwd=_repo_root())
        seed_snap = os.path.join(out, "snap", "m_iter_40.ckpt",
                                 "manifest.json")
        if rc != 0 or not os.path.exists(seed_snap):
            raise GamedayError(
                f"seed training failed (rc={rc}); see {seed_log}")
        # The chaos trainer's retention GC (--snapshot-keep) will delete
        # m_iter_40 within seconds at CPU step rates — copy it outside
        # the GC'd prefix so the server's initial --snapshot load can
        # never race the deletion.
        boot_snap = os.path.join(out, "boot", "m_iter_40.ckpt")
        shutil.copytree(os.path.dirname(seed_snap), boot_snap)

        # Launch the group: trainer (chaos-armed), serving tier
        # (chaos-armed), watch evaluator.
        trainer = sup.launch(
            "train", _train_cmd(solver, out),
            env=_child_env(chaos.env_spec(entries, "train")),
            stdout=sup.open(os.path.join(out, "train1.log")),
            stderr=subprocess.STDOUT)
        serve = sup.launch(
            "serve", _serve_cmd(out, replicas),
            env=_child_env(chaos.env_spec(entries, "serve")),
            stdin=subprocess.PIPE,
            stdout=sup.open(os.path.join(out, "answers.jsonl")),
            stderr=sup.open(os.path.join(out, "serve.log")))
        t0 = time.monotonic()
        io: Dict[str, Any] = {"stdin": serve.stdin,
                              "lock": threading.Lock()}

        feeder = threading.Thread(
            target=_feed, args=(plan, emb, io, t0, state),
            name="gameday-feed", daemon=True)
        feeder.start()
        ingester = threading.Thread(
            target=_ingest, args=(plan, emb, labels, io, t0, state),
            name="gameday-ingest", daemon=True)
        ingester.start()

        # Watch follows the serve telemetry once it exists.
        serve_metrics = os.path.join(out, "serve_tel", "metrics.jsonl")
        watch = None
        observed_signals: Dict[str, int] = {}
        sigs = chaos.signals(entries, "train")
        serve_sigs = chaos.signals(entries, "serve")
        while time.monotonic() - t0 < duration_s:
            now = time.monotonic() - t0
            if watch is None and os.path.exists(serve_metrics):
                watch = sup.launch(
                    "watch",
                    _python() + ["watch", os.path.join(out, "serve_tel"),
                                 "--slo-config",
                                 os.path.join(out, "slo.json"),
                                 "--follow", "--poll-s", "0.5",
                                 "--for", str(duration_s + 30.0)],
                    env=_child_env(),
                    stdout=sup.open(os.path.join(out, "watch.log")),
                    stderr=subprocess.STDOUT)
            if sigs and now >= sigs[0].at_s:
                entry = sigs.pop(0)
                signum = getattr(signal, entry.name, signal.SIGTERM)
                log.info("gameday: delivering %s to trainer at %.1fs",
                         entry.name, now)
                trainer.send_signal(signum)
                rc = trainer.wait(timeout=60)
                trainer_exits.append(rc)
                observed_signals[entry.name] = (
                    observed_signals.get(entry.name, 0) + 1)
                if rc != 75:
                    raise GamedayError(
                        f"trainer {entry.name} expected exit 75, "
                        f"got {rc}; see {out}/train1.log")
                # Relaunch the SAME command — the auto-resume
                # contract; the consumed chaos env is NOT re-armed.
                trainer = sup.launch(
                    "train", _train_cmd(solver, out),
                    env=_child_env(),
                    stdout=sup.open(os.path.join(out, "train2.log")),
                    stderr=subprocess.STDOUT)
            if serve_sigs and now >= serve_sigs[0].at_s:
                entry = serve_sigs.pop(0)
                signum = getattr(signal, entry.name, signal.SIGKILL)
                log.info("gameday: delivering %s to serve at %.1fs",
                         entry.name, now)
                serve.send_signal(signum)
                serve.wait(timeout=60)
                observed_signals[entry.name] = (
                    observed_signals.get(entry.name, 0) + 1)
                state.setdefault("kill_walls", []).append(time.time())
                # A SIGKILL ran no handler: no drain, no final qtrace
                # write.  Preserve the periodically-checkpointed
                # artifact before the relaunched tier overwrites it —
                # reconcile merges its marker totals back in.
                qt = os.path.join(out, "serve_tel", "qtrace.json")
                if os.path.exists(qt):
                    os.replace(qt, os.path.join(
                        out, "serve_tel",
                        f"qtrace.pre{len(state['kill_walls'])}.json"))
                # Cold restart from the published artifacts + WAL
                # alone — same command, consumed chaos NOT re-armed;
                # answers APPEND so the dead tier's acks stay evidence.
                serve = sup.launch(
                    "serve", _serve_cmd(out, replicas),
                    env=_child_env(),
                    stdin=subprocess.PIPE,
                    stdout=sup.open(
                        os.path.join(out, "answers.jsonl"), "ab"),
                    stderr=sup.open(
                        os.path.join(out, "serve2.log"), "ab"))
                with io["lock"]:
                    old_stdin, io["stdin"] = io["stdin"], serve.stdin
                try:
                    old_stdin.close()
                except OSError:
                    pass
            if serve.poll() is not None:
                raise GamedayError(
                    f"serve died mid-window (rc={serve.returncode}); "
                    f"see {out}/serve.log")
            if trainer.poll() is not None:
                raise GamedayError(
                    f"trainer died mid-window (rc={trainer.returncode})"
                    f"; see {out}/train1.log")
            time.sleep(0.25)

        feeder.join(timeout=30.0)
        time.sleep(3.0)  # let the last swap's resolution land

        # Drain: SIGTERM first (rc 75, the preemption contract), then
        # EOF on stdin so the reader unblocks.
        serve.send_signal(signal.SIGTERM)
        time.sleep(0.2)
        serve.stdin.close()
        serve_rc = serve.wait(timeout=120)
        if serve_rc != 75:
            raise GamedayError(
                f"serve drain expected exit 75, got {serve_rc}; "
                f"see {out}/serve.log")
        trainer.send_signal(signal.SIGTERM)
        rc = trainer.wait(timeout=60)
        trainer_exits.append(rc)
        if rc != 75:
            raise GamedayError(
                f"trainer drain expected exit 75, got {rc}; "
                f"see {out}/train2.log")
        if watch is not None:
            try:
                watch.wait(timeout=45)
            except subprocess.TimeoutExpired:
                watch.terminate()
                watch.wait(timeout=15)
        ingester.join(timeout=15.0)
    finally:
        sup.cleanup()

    if state.get("feed_error"):
        raise GamedayError(state["feed_error"])
    if state.get("ingest_error"):
        raise GamedayError(f"ingest failed: {state['ingest_error']}")

    return _reconcile(out, entries, plan, state, trainer_exits,
                      observed_signals, duration_s=duration_s,
                      seed=seed)


def _host_crash_evidence(out: str, answers: List[Dict[str, Any]],
                         state: Dict[str, Any],
                         drain: Dict[str, Any]) -> Dict[str, Any]:
    """The durable-ingest oracle: replay every ACKED ingest batch
    against the artifacts the cold restart actually published.  The
    ingester kept each batch's ids + vectors in memory; an ack in
    answers.jsonl means the tier claimed durability BEFORE the
    SIGKILL — so every acked id must be in the final index exactly
    once, and every acked vector must retrieve ITSELF from it
    (recall parity after replay, recomputed, not trusted)."""
    kills = state.get("kill_walls") or []
    if not kills:
        return {"available": False,
                "reason": "no serve SIGKILL delivered"}
    sent = state.get("ingest_sent") or {}
    acked: Dict[str, Dict[str, Any]] = {}
    for a in answers:
        rid = a.get("id")
        if (rid in sent and isinstance(a.get("ingested"), int)
                and a["ingested"] > 0):
            acked[rid] = sent[rid]
    from npairloss_tpu.serve.index import load_newest

    found = load_newest(os.path.join(out, "idx", "g_"))
    if found is None:
        return {"available": False,
                "reason": "no loadable index commit"}
    final_path, final = found
    final_ids = np.asarray(final.ids).astype(np.int64)
    id_set = set(int(i) for i in final_ids.tolist())
    lost = acked_vectors = 0
    hits = total = 0
    final_emb = np.asarray(final._host_emb, dtype=np.float32)
    for rid, batch in acked.items():
        ids = batch["ids"]
        acked_vectors += len(ids)
        lost += sum(1 for i in ids if int(i) not in id_set)
        top = np.argmax(batch["emb"] @ final_emb.T, axis=1)
        hits += int(np.sum(final_ids[top]
                           == np.asarray(ids, dtype=np.int64)))
        total += len(ids)
    wal_stats = (drain.get("ingest") or {}).get("wal") or {}
    return {
        "available": True,
        "kills": len(kills),
        "acked_batches": len(acked),
        "acked_vectors": int(acked_vectors),
        "lost": int(lost),
        "duplicates": int(final_ids.shape[0] - len(id_set)),
        "torn_records": int(wal_stats.get("torn_records", 0)),
        "self_recall": round(hits / total, 4) if total else 0.0,
        "final_index": os.path.basename(final_path),
    }


def _reconcile(out: str, entries, plan: tg.TrafficPlan,
               state: Dict[str, Any], trainer_exits: List[int],
               observed_signals: Dict[str, int], *,
               duration_s: float, seed: int) -> Dict[str, Any]:
    """Load every artifact and build the verdict."""
    answers = _jsonl(os.path.join(out, "answers.jsonl"))
    drains = [a for a in answers if a.get("event") == "serve_drain"]
    if not drains:
        raise GamedayError("no serve_drain summary in answers.jsonl")
    drain = drains[-1]

    serve_tel = os.path.join(out, "serve_tel")
    train_tel = os.path.join(out, "train_tel")
    serve_alerts = _jsonl(os.path.join(serve_tel, "alerts.jsonl"))
    train_alerts = _jsonl(os.path.join(train_tel, "alerts.jsonl"))
    serve_rem = _jsonl(os.path.join(serve_tel, "remediation.jsonl"))
    train_rem = _jsonl(os.path.join(train_tel, "remediation.jsonl"))
    serve_rows = [r for r in _jsonl(os.path.join(serve_tel,
                                                 "metrics.jsonl"))
                  if "p99_ms" in r and "wall_time" in r]
    quality = [r for r in _jsonl(os.path.join(serve_tel,
                                              "quality.jsonl"))
               if r.get("kind") == "window"]

    # Synthetic incident per SIGKILL: no in-process pager can observe
    # its own SIGKILL, so the RUNNER contributes the alert pair that
    # excuses the restart's SLO turbulence — firing at the kill wall,
    # resolved at the first metric window the reborn tier published
    # (the backlog it inherits lands inside the padded window).
    for i, t_kill in enumerate(state.get("kill_walls") or []):
        after = sorted(float(r["wall_time"]) for r in serve_rows
                       if float(r.get("wall_time", 0.0)) >= t_kill)
        t_rec = after[0] if after else t_kill + 30.0
        serve_alerts.append({"state": "firing",
                             "alert_id": f"host_crash_{i}",
                             "slo": "host_crash", "fired_at": t_kill})
        serve_alerts.append({"state": "resolved",
                             "alert_id": f"host_crash_{i}",
                             "slo": "host_crash", "ts": t_rec})

    # Qtrace evidence for the p99-attribution check: totals (reroute /
    # hot-swap markers) + the rolling budget decomposition.  A missing
    # or torn artifact is a reportable fact — the stage-declaring
    # faults will fail their attribution gate, which is the point.
    qtrace_block: Dict[str, Any] = {"available": False}
    try:
        with open(os.path.join(serve_tel, "qtrace.json"), "r",
                  encoding="utf-8") as f:
            qt = json.load(f)
        if isinstance(qt, dict) and isinstance(qt.get("totals"), dict):
            qtrace_block = {"available": True,
                            "totals": qt["totals"],
                            "budget": qt.get("budget", {}),
                            "slo_ms": qt.get("slo_ms")}
    except (OSError, ValueError) as e:
        qtrace_block = {"available": False, "reason": str(e)}
    # Marker totals from SIGKILLed instances: their periodically
    # checkpointed artifacts were preserved as qtrace.preN.json before
    # the relaunch overwrote the live one — a reroute counted by a
    # tier that later died is still injection evidence.
    for name in sorted(os.listdir(serve_tel)):
        if not (name.startswith("qtrace.pre") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(serve_tel, name), "r",
                      encoding="utf-8") as f:
                pre = json.load(f)
        except (OSError, ValueError):
            continue
        totals = (pre.get("totals") if isinstance(pre, dict)
                  else None)
        if not isinstance(totals, dict):
            continue
        if not qtrace_block.get("available"):
            qtrace_block = {"available": True, "totals": {},
                            "budget": pre.get("budget", {}),
                            "slo_ms": pre.get("slo_ms")}
        merged = qtrace_block.setdefault("totals", {})
        for key, val in totals.items():
            if isinstance(val, int) and not isinstance(val, bool):
                merged[key] = int(merged.get(key, 0)) + val

    from npairloss_tpu.obs.fleet.aggregate import build_fleet_report

    try:
        fleet = build_fleet_report(train_tel)
        comms = fleet.get("comms", {"available": False})
    except Exception as e:  # noqa: BLE001 — a missing fleet report is
        # a reportable fact, not a crash
        comms = {"available": False, "reason": f"fleet report: {e}"}

    fires = _count_fires([os.path.join(out, name) for name in
                          ("serve.log", "serve2.log", "train1.log",
                           "train2.log")])
    for name, count in observed_signals.items():
        fires[name] = fires.get(name, 0) + count

    train2 = os.path.join(out, "train2.log")
    resumed = False
    if os.path.exists(train2):
        with open(train2, "r", encoding="utf-8",
                  errors="replace") as f:
            resumed = "resuming from iteration" in f.read()

    host_crash = _host_crash_evidence(out, answers, state, drain)

    report = gv.build_gameday_report(
        chaos.entry_dicts(entries),
        traffic={
            "planned": len(plan.queries),
            "fed": state.get("fed", 0),
            "answered": drain.get("answered"),
            "errors": drain.get("errors"),
            "rejected": drain.get("rejected"),
            "sha256": tg.plan_digest(plan),
        },
        serve_alerts=serve_alerts, train_alerts=train_alerts,
        serve_remediation=serve_rem, train_remediation=train_rem,
        serve_rows=serve_rows, quality_windows=quality,
        drain=drain, comms=comms,
        trainer={"segments": len(trainer_exits),
                 "exit_codes": trainer_exits, "resumed": resumed},
        observed_fires=fires,
        client_errors=int(drain.get("errors", 0)),
        window_s=duration_s, seed=seed,
        p99_target_ms=P99_TARGET_MS, recall_floor=RECALL_FLOOR,
        min_hot_swaps=MIN_HOT_SWAPS, qtrace=qtrace_block,
        host_crash=host_crash,
    )
    _write_json(os.path.join(out, "gameday.json"), report)
    try:
        # One Perfetto file for the whole day: trainer rank lanes,
        # serve spans + exemplar query trees, chaos/alert/remediation
        # instants (obs/fleet/merge_traces.py).  Evidence, not a gate —
        # a failed merge is logged, never fatal.
        from npairloss_tpu.obs.fleet.merge_traces import merge_timeline

        tl_path, _ = merge_timeline(out)
        if tl_path:
            log.info("gameday: merged timeline at %s", tl_path)
    except Exception as e:  # noqa: BLE001 — the timeline is evidence
        log.error("gameday: timeline merge failed: %s", e)
    log.info("gameday: verdict=%s (%d fault(s), %d hot-swap(s), "
             "%d/%d answered)",
             report["verdict"], len(report["faults"]),
             report["zero_drop"]["hot_swaps"],
             drain.get("answered", 0), state.get("fed", 0))
    return report


# -- tenant_skew scenario ----------------------------------------------------

TENANT_IDS = ("acme", "bcorp", "ccorp")
# The hot tenant's quota: above its steady share of the diurnal peak
# (no shedding on a quiet day) and far below its burst arrival rate
# (the burst MUST shed).  burst_s=1 keeps the token bucket shallow so
# the quota alert's evidence is unambiguous.
HOT_QUOTA_QPS = 6.0


def _tenant_workspace(out: str, cfg: tg.TrafficConfig,
                      hot_tenant: str) -> Dict[str, np.ndarray]:
    """Per-tenant galleries — SAME geometry on purpose, so the shared
    ProgramCache proves tenant count never multiplies compiles — plus
    the ``npairloss-tenants-v1`` manifest: the hot tenant gets the
    quota the burst must exhaust, every neighbor gets the p99/recall
    SLOs whose survival the verdict gates."""
    for sub in ("idx", "serve_tel"):
        os.makedirs(os.path.join(out, sub), exist_ok=True)
    from npairloss_tpu.serve.index import GalleryIndex
    from npairloss_tpu.serve.tenants import TENANTS_SCHEMA

    embs: Dict[str, np.ndarray] = {}
    tenants: List[Dict[str, Any]] = []
    for i, tid in enumerate(TENANT_IDS):
        rng = np.random.default_rng(cfg.seed + 101 + i)
        emb = rng.standard_normal((cfg.catalog, 64)).astype(np.float32)
        emb /= np.linalg.norm(emb, axis=1, keepdims=True)
        labels = (np.arange(cfg.catalog) % 16).astype(np.int32)
        index = GalleryIndex.build(emb, labels, normalize=False)
        index.save(os.path.join(out, "idx", f"{tid}-0000.gidx"))
        embs[tid] = emb
        spec: Dict[str, Any] = {
            "tenant_id": tid,
            "index_prefix": os.path.join(out, "idx", f"{tid}-"),
        }
        if tid == hot_tenant:
            spec.update(quota_qps=HOT_QUOTA_QPS, quota_burst_s=1.0)
        else:
            spec.update(p99_ms=P99_TARGET_MS, recall_floor=RECALL_FLOOR,
                        recall_k=10)
        tenants.append(spec)
    _write_json(os.path.join(out, "tenants.json"),
                {"schema": TENANTS_SCHEMA, "tenants": tenants})
    return embs


def _tenant_serve_cmd(out: str, replicas: int) -> List[str]:
    return _python() + [
        "serve", "--tenant-config", os.path.join(out, "tenants.json"),
        "--compile-cache", os.path.join(out, "xla_cache"),
        "--top-k", "10", "--buckets", "1", "--deadline-ms", "2",
        "--poll-s", "0.02",
        "--max-queue", "64", "--replicas", str(replicas),
        "--explicit-drops", "--metrics-window", "4",
        "--shadow-rate", "1", "--shadow-window", "4",
        "--telemetry-dir", os.path.join(out, "serve_tel"),
        "--live-obs", "--slo-tick", "0.2",
    ]


_SERVE_READY_MARKER = "shadow scoring armed"


def _wait_serve_ready(log_path: str, proc,
                      timeout_s: float = 180.0) -> None:
    """Block until the serve log shows the post-warmup marker (the
    shadow-scorer arming line is the last thing cmd_serve logs before
    entering the stdin loop).  Feeding a still-importing server piles
    the whole early schedule into the pipe; the catch-up replay then
    pollutes the first latency windows with a flood the plan never
    scripted."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if proc.poll() is not None:
            raise GamedayError(
                f"serve died during startup (rc={proc.returncode}); "
                f"see {log_path}")
        try:
            with open(log_path, "r", encoding="utf-8",
                      errors="replace") as f:
                if _SERVE_READY_MARKER in f.read():
                    return
        except OSError:
            pass
        time.sleep(0.2)
    raise GamedayError(
        f"serve not ready after {timeout_s:.0f}s (no "
        f"{_SERVE_READY_MARKER!r} in {log_path})")


def run_tenant_skew(out: str, *, seed: int = 0,
                    duration_s: float = 75.0,
                    replicas: int = 2,
                    hot_tenant: str = "acme") -> Dict[str, Any]:
    """The noisy-neighbor gameday (docs/SERVING.md §Multi-tenant): ONE
    serving tier, three tenant galleries, and a traffic plan whose
    single mid-window burst lands ~8x of its load on ``hot_tenant``.
    The scripted chaos is the plan itself (schedule kind "traffic") —
    the verdict must see the hot tenant quota-shed AND paged by its
    tenant-scoped alert, which must also RESOLVE before drain, while
    every other tenant kept zero errors/rejects, its whole-run p99
    under the target, and its shadow recall over the floor.

    Timing is load-bearing: the quota SLO's 30s rolling window means
    the burst's bad samples age out ~30s after the burst ends, so the
    window needs the burst mid-run with a >=30s quiet tail for the
    alert pair to complete (one burst at duration/2 with
    duration_s >= ~65)."""
    out = os.path.abspath(out)
    os.makedirs(out, exist_ok=True)
    if hot_tenant not in TENANT_IDS:
        raise GamedayError(
            f"hot_tenant must be one of {TENANT_IDS}, got {hot_tenant!r}")
    entries = chaos.tenant_skew_schedule(hot_tenant, duration_s)
    cfg = tg.TrafficConfig(
        seed=seed, duration_s=duration_s, base_qps=4.0, peak_qps=8.0,
        burst_qps=40.0, bursts=1, burst_s=6.0, catalog=256, zipf_s=1.1,
        tenants=tuple((tid, 1.0) for tid in TENANT_IDS),
        hot_tenant=hot_tenant, hot_burst_factor=8.0)
    plan = tg.generate(cfg)
    with open(os.path.join(out, "traffic.jsonl"), "w",
              encoding="utf-8") as f:
        f.write("\n".join(tg.plan_lines(plan)) + "\n")
    embs = _tenant_workspace(out, cfg, hot_tenant)

    sup = _Supervisor()
    state: Dict[str, Any] = {"fed": 0}
    try:
        serve = sup.launch(
            "serve", _tenant_serve_cmd(out, replicas),
            env=_child_env(), stdin=subprocess.PIPE,
            stdout=sup.open(os.path.join(out, "answers.jsonl")),
            stderr=sup.open(os.path.join(out, "serve.log")))
        _wait_serve_ready(os.path.join(out, "serve.log"), serve)
        t0 = time.monotonic()
        io: Dict[str, Any] = {"stdin": serve.stdin,
                              "lock": threading.Lock()}
        feeder = threading.Thread(
            target=_feed, args=(plan, embs[hot_tenant], io, t0, state),
            kwargs={"tenant_embs": embs},
            name="gameday-feed", daemon=True)
        feeder.start()
        while time.monotonic() - t0 < duration_s:
            if serve.poll() is not None:
                raise GamedayError(
                    f"serve died mid-window (rc={serve.returncode}); "
                    f"see {out}/serve.log")
            time.sleep(0.25)
        feeder.join(timeout=30.0)
        # The quota alert resolves ~30s after the burst's bad samples
        # start aging out — the drain must not beat the resolution.
        time.sleep(3.0)
        serve.send_signal(signal.SIGTERM)
        time.sleep(0.2)
        serve.stdin.close()
        serve_rc = serve.wait(timeout=120)
        if serve_rc != 75:
            raise GamedayError(
                f"serve drain expected exit 75, got {serve_rc}; "
                f"see {out}/serve.log")
    finally:
        sup.cleanup()
    if state.get("feed_error"):
        raise GamedayError(state["feed_error"])

    answers = _jsonl(os.path.join(out, "answers.jsonl"))
    drains = [a for a in answers if a.get("event") == "serve_drain"]
    if not drains:
        raise GamedayError("no serve_drain summary in answers.jsonl")
    drain = drains[-1]
    serve_tel = os.path.join(out, "serve_tel")
    serve_alerts = _jsonl(os.path.join(serve_tel, "alerts.jsonl"))
    # The tier-wide p99 gate judges the AGGREGATE window rows only; a
    # tenant-stamped row is that tenant's own evidence and already
    # gated per-tenant (counting it twice would let one tenant's
    # in-quota latency fail the tier).
    serve_rows = [r for r in _jsonl(os.path.join(serve_tel,
                                                 "metrics.jsonl"))
                  if "p99_ms" in r and "wall_time" in r
                  and "tenant" not in r]
    tenant_quality = {
        tid: [r for r in _jsonl(os.path.join(serve_tel,
                                             f"quality.{tid}.jsonl"))
              if r.get("kind") == "window"]
        for tid in TENANT_IDS}

    report = gv.build_gameday_report(
        chaos.entry_dicts(entries),
        traffic={
            "planned": len(plan.queries),
            "fed": state.get("fed", 0),
            "answered": drain.get("answered"),
            "errors": drain.get("errors"),
            "rejected": drain.get("rejected"),
            "sha256": tg.plan_digest(plan),
        },
        serve_alerts=serve_alerts, train_alerts=[],
        serve_remediation=_jsonl(
            os.path.join(serve_tel, "remediation.jsonl")),
        train_remediation=[],
        serve_rows=serve_rows,
        quality_windows=[],  # recall is judged per tenant below
        drain=drain,
        comms={"available": False,
               "reason": "no trainer in the tenant_skew scenario"},
        trainer={"segments": 0, "exit_codes": [], "resumed": False},
        observed_fires={},
        client_errors=int(drain.get("errors", 0)),
        window_s=duration_s, seed=seed,
        p99_target_ms=P99_TARGET_MS, recall_floor=RECALL_FLOOR,
        # The burst is traffic, not a failpoint — there is no stall to
        # pad around, so tight pads keep real pre-burst evidence
        # outside the incident window (recall_worst must be a number,
        # not None-because-everything-was-excused).
        pad_before_s=5.0, pad_after_s=5.0,
        min_hot_swaps=0,
        tenant_hot=hot_tenant, tenant_quality=tenant_quality,
    )
    _write_json(os.path.join(out, "gameday.json"), report)
    tb = report.get("tenants") or {}
    log.info("gameday[tenant_skew]: verdict=%s (hot=%s shed+rejected=%s"
             " alerted=%s)",
             report["verdict"], hot_tenant,
             (tb.get("tenants", {}).get(hot_tenant) or {}).get(
                 "rejected"),
             (tb.get("tenants", {}).get(hot_tenant) or {}).get(
                 "alerted"))
    return report
