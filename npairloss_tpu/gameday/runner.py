"""Gameday runner — one supervised chaos window over the composed stack.

Launches the production shape as one process group — a trainer
snapshotting continuously (``--resume auto``, the supervisor-relaunch
contract), a replicated serving tier (``--live-obs --remediate
--watch-snapshots --index-prefix --explicit-drops``, SLO admission,
shadow scoring), and the offline watch evaluator following the same
telemetry — then drives the deterministic traffic plan
(gameday/traffic.py) through it while the chaos schedule
(gameday/schedule.py) injects faults: failpoints armed via
``NPAIRLOSS_FAILPOINTS`` in each child's environment, signals delivered
at their scripted offsets (SIGTERM mid-stream, relaunch same command).

At the end it collects every artifact — answers, alert logs,
remediation audits, quality windows, metric rows, the fleet report,
the drain summary — and hands them to gameday/verdict.py, writing the
``npairloss-gameday-v1`` report to ``<out>/gameday.json``.

This module runs the composed system, so unlike the verdict it may
import numpy and the package freely; everything it feeds the verdict
is plain dicts/lists.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from npairloss_tpu.gameday import schedule as chaos
from npairloss_tpu.gameday import traffic as tg
from npairloss_tpu.gameday import verdict as gv

log = logging.getLogger("npairloss_tpu.gameday")

# SLO targets the run arms; the verdict judges against the SAME numbers
# (one source of truth — runner passes them through to the report).
P99_TARGET_MS = 150.0
RECALL_FLOOR = 0.9
MODEL_STALENESS_S = 6.0
INDEX_STALENESS_S = 30.0
MIN_HOT_SWAPS = 3


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f)


def _child_env(failpoints_spec: str = "") -> Dict[str, str]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("NPAIRLOSS_FAILPOINTS", None)
    if failpoints_spec:
        env["NPAIRLOSS_FAILPOINTS"] = failpoints_spec
    return env


def _jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail — the writer was SIGTERMed
    return out


def _count_fires(paths: Sequence[str]) -> Dict[str, int]:
    """``failpoint fired: <name>`` occurrences across the child logs —
    the injection evidence the verdict reconciles declarations
    against."""
    fires: Dict[str, int] = {}
    for path in paths:
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                marker = "failpoint fired: "
                idx = line.find(marker)
                if idx >= 0:
                    name = line[idx + len(marker):].strip()
                    fires[name] = fires.get(name, 0) + 1
    return fires


class GamedayError(RuntimeError):
    """The run itself broke (a child died wrong, setup failed) — as
    opposed to a clean run whose verdict failed."""


class _Supervisor:
    """The process group: launch, signal, drain, never leak."""

    def __init__(self):
        self.procs: Dict[str, subprocess.Popen] = {}
        self.files: List[Any] = []

    def open(self, path: str):
        f = open(path, "wb")
        self.files.append(f)
        return f

    def launch(self, name: str, cmd: List[str], *, env: Dict[str, str],
               stdout, stderr, stdin=None) -> subprocess.Popen:
        log.info("gameday: launching %s: %s", name, " ".join(cmd))
        proc = subprocess.Popen(cmd, env=env, stdin=stdin,
                                stdout=stdout, stderr=stderr,
                                cwd=_repo_root())
        self.procs[name] = proc
        return proc

    def cleanup(self):
        for name, proc in self.procs.items():
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        for f in self.files:
            try:
                f.close()
            except OSError:
                pass


def _python() -> List[str]:
    return [sys.executable, "-m", "npairloss_tpu"]


def _setup_workspace(out: str, cfg: tg.TrafficConfig):
    """Gallery, initial index commit, solver config, SLO/policy
    tables.  Returns (emb, labels, solver_path)."""
    for sub in ("idx", "snap", "serve_tel", "train_tel"):
        os.makedirs(os.path.join(out, sub), exist_ok=True)
    rng = np.random.default_rng(cfg.seed)
    emb = rng.standard_normal((cfg.catalog, 64)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    labels = (np.arange(cfg.catalog) % 16).astype(np.int32)

    from npairloss_tpu.serve.index import GalleryIndex

    index = GalleryIndex.build(emb, labels, normalize=False)
    index.save(os.path.join(out, "idx", "g_0000.gidx"))

    solver = os.path.join(out, "solver.prototxt")
    with open(solver, "w", encoding="utf-8") as f:
        f.write(
            'net: "examples/tiny_net.prototxt"\n'
            "base_lr: 0.05\n"
            'lr_policy: "fixed"\n'
            "momentum: 0.9\n"
            "max_iter: 100000\n"
            "display: 0\n"
            "test_interval: 0\n"
            "test_iter: 0\n"
            "snapshot: 40\n"
            f'snapshot_prefix: "{out}/snap/m_"\n'
        )

    _write_json(os.path.join(out, "slo.json"), {"slos": [
        {"name": "model_staleness", "metric": "serve_model_age_s",
         "op": "<=", "target": MODEL_STALENESS_S, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "warning"},
        {"name": "index_staleness", "metric": "serve_index_age_s",
         "op": "<=", "target": INDEX_STALENESS_S, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "warning"},
        {"name": "serve_p99", "metric": "serve_p99_ms", "op": "<=",
         "target": P99_TARGET_MS, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "critical"},
        {"name": "serve_recall_floor", "metric": "serve_recall_at_10",
         "op": ">=", "target": RECALL_FLOOR, "window_s": 2.0,
         "burn_threshold": 0.5, "min_samples": 1,
         "severity": "critical"},
    ]})
    # Generous budgets: early hot-swap attempts legitimately fail with
    # NothingNewer while the freshly-launched trainer is still
    # importing — the policy must retry past that window.
    _write_json(os.path.join(out, "rem.json"), {"policies": [
        {"name": "hotswap_model", "slo": "model_staleness",
         "action": "snapshot_hotswap", "cooldown_s": 3.0,
         "max_attempts": 10},
        {"name": "hotswap_index", "slo": "index_staleness",
         "action": "snapshot_hotswap", "cooldown_s": 3.0,
         "max_attempts": 10},
        {"name": "load_shed", "slo": "serve_p99", "action": "load_shed",
         "cooldown_s": 6.0, "max_attempts": 4},
    ]})
    _write_json(os.path.join(out, "train_slo.json"), {"slos": [
        {"name": "embedding_collapse",
         "metric": "train_an_threshold_mean", "op": "<=",
         "target": 0.98, "window_s": 2.0, "burn_threshold": 0.5,
         "min_samples": 3, "severity": "warning"},
    ]})
    _write_json(os.path.join(out, "train_rem.json"), {"policies": [
        {"name": "trainer_rollback", "slo": "embedding_collapse",
         "action": "trainer_rollback", "cooldown_s": 6.0,
         "max_attempts": 5},
    ]})
    return emb, labels, solver


def _train_cmd(solver: str, out: str) -> List[str]:
    return _python() + [
        "train", "--solver", solver, "--model", "mlp", "--synthetic",
        "--resume", "auto", "--health-metrics",
        # Retention GC is a CLI knob, not a Caffe solver field — the
        # prototxt parser would silently drop it, and a 75s compressed
        # day at CPU step rates commits hundreds of snapshots.
        "--snapshot-keep", "10",
        "--telemetry-dir", os.path.join(out, "train_tel"),
        "--live-obs", "--slo-config", os.path.join(out, "train_slo.json"),
        "--slo-tick", "0.2", "--remediate",
        "--remediation-config", os.path.join(out, "train_rem.json"),
    ]


def _serve_cmd(out: str, replicas: int) -> List[str]:
    return _python() + [
        "serve", "--index-prefix", os.path.join(out, "idx", "g_"),
        "--snapshot", os.path.join(out, "boot", "m_iter_40.ckpt"),
        "--model", "mlp", "--input-size", "8",
        "--watch-snapshots", os.path.join(out, "snap", "m_"),
        "--compile-cache", os.path.join(out, "xla_cache"),
        "--top-k", "10", "--buckets", "1", "--deadline-ms", "1",
        "--max-queue", "64", "--replicas", str(replicas),
        "--admission", "slo", "--admission-slos", "serve_p99",
        "--explicit-drops", "--metrics-window", "4",
        "--shadow-rate", "1", "--shadow-window", "4",
        "--telemetry-dir", os.path.join(out, "serve_tel"),
        "--live-obs", "--slo-config", os.path.join(out, "slo.json"),
        "--slo-tick", "0.2", "--remediate",
        "--remediation-config", os.path.join(out, "rem.json"),
        # Per-query tracing: the p99-attribution verdict check reads
        # the qtrace_dominant window rows and the qtrace.json reroute
        # counters this arms (docs/OBSERVABILITY.md §Query tracing).
        "--qtrace", "--qtrace-slo-ms", str(P99_TARGET_MS),
    ]


def _feed(plan: tg.TrafficPlan, emb: np.ndarray, stdin, t0: float,
          state: Dict[str, Any]) -> None:
    """Pace the plan's query events against the monotonic clock and
    write them to the tier's stdin.  Writes may block on pipe
    backpressure while the tier warms or degrades — that only delays
    later events, it never reorders or drops them."""
    n = emb.shape[0]
    for ev in plan.queries:
        wait = (t0 + ev.t) - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        line = json.dumps({"id": ev.qid,
                           "embedding": emb[ev.key % n].tolist()})
        try:
            stdin.write(line.encode("utf-8") + b"\n")
            stdin.flush()
        except (BrokenPipeError, OSError) as e:
            state["feed_error"] = f"serve stdin broke at qid {ev.qid}: {e}"
            return
        state["fed"] = state.get("fed", 0) + 1


def _ingest(plan: tg.TrafficPlan, emb: np.ndarray,
            labels: np.ndarray, out: str, t0: float,
            state: Dict[str, Any]) -> None:
    """The gallery-growth stream: at each scripted ingest event,
    ``add()`` a batch of new rows and commit the grown index under the
    watched prefix — the hot-swap remediation's food supply."""
    from npairloss_tpu.serve.index import GalleryIndex

    cfg = plan.cfg
    rng = np.random.default_rng(cfg.seed + 1)
    grown_emb, grown_labels = emb, labels
    for ev in plan.ingest:
        wait = (t0 + ev.t) - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        new = rng.standard_normal((ev.rows, emb.shape[1])
                                  ).astype(np.float32)
        new /= np.linalg.norm(new, axis=1, keepdims=True)
        new_labels = (np.arange(ev.rows) % 16).astype(np.int32)
        try:
            index = GalleryIndex.build(grown_emb, grown_labels,
                                       normalize=False)
            index.add(new, new_labels, normalize=False)
            index.save(os.path.join(
                out, "idx", f"g_{ev.commit_id + 1:04d}.gidx"))
        except Exception as e:  # noqa: BLE001 — a failed commit is a
            # run-level fact the verdict should see, not a crash
            state["ingest_error"] = f"commit {ev.commit_id}: {e}"
            return
        grown_emb = np.concatenate([grown_emb, new])
        grown_labels = np.concatenate([grown_labels, new_labels])
        state["ingest_commits"] = state.get("ingest_commits", 0) + 1


def run_gameday(out: str, *, seed: int = 0, duration_s: float = 75.0,
                schedule_path: Optional[str] = None,
                replicas: int = 2) -> Dict[str, Any]:
    """The whole gameday: setup, launch, drive, drain, verdict.
    Returns the ``npairloss-gameday-v1`` report (also written to
    ``<out>/gameday.json``)."""
    out = os.path.abspath(out)
    os.makedirs(out, exist_ok=True)
    entries = (chaos.load_schedule(schedule_path) if schedule_path
               else chaos.default_schedule(duration_s))
    cfg = tg.TrafficConfig(seed=seed, duration_s=duration_s,
                           base_qps=6.0, peak_qps=14.0, burst_qps=45.0,
                           bursts=2, burst_s=3.0, catalog=256,
                           zipf_s=1.1, ingest_every_s=10.0,
                           ingest_rows=16)
    plan = tg.generate(cfg)
    with open(os.path.join(out, "traffic.jsonl"), "w",
              encoding="utf-8") as f:
        f.write("\n".join(tg.plan_lines(plan)) + "\n")
    emb, labels, solver = _setup_workspace(out, cfg)

    sup = _Supervisor()
    state: Dict[str, Any] = {"fed": 0}
    trainer_exits: List[int] = []
    try:
        # Phase 0: one short run commits the INITIAL snapshot the
        # server restores (and the freshness clock starts from).
        seed_log = os.path.join(out, "seed.log")
        with open(seed_log, "wb") as f:
            rc = subprocess.call(
                _python() + ["train", "--solver", solver, "--model",
                             "mlp", "--synthetic", "--max_iter", "40"],
                env=_child_env(), stdout=f, stderr=subprocess.STDOUT,
                cwd=_repo_root())
        seed_snap = os.path.join(out, "snap", "m_iter_40.ckpt",
                                 "manifest.json")
        if rc != 0 or not os.path.exists(seed_snap):
            raise GamedayError(
                f"seed training failed (rc={rc}); see {seed_log}")
        # The chaos trainer's retention GC (--snapshot-keep) will delete
        # m_iter_40 within seconds at CPU step rates — copy it outside
        # the GC'd prefix so the server's initial --snapshot load can
        # never race the deletion.
        boot_snap = os.path.join(out, "boot", "m_iter_40.ckpt")
        shutil.copytree(os.path.dirname(seed_snap), boot_snap)

        # Launch the group: trainer (chaos-armed), serving tier
        # (chaos-armed), watch evaluator.
        trainer = sup.launch(
            "train", _train_cmd(solver, out),
            env=_child_env(chaos.env_spec(entries, "train")),
            stdout=sup.open(os.path.join(out, "train1.log")),
            stderr=subprocess.STDOUT)
        serve = sup.launch(
            "serve", _serve_cmd(out, replicas),
            env=_child_env(chaos.env_spec(entries, "serve")),
            stdin=subprocess.PIPE,
            stdout=sup.open(os.path.join(out, "answers.jsonl")),
            stderr=sup.open(os.path.join(out, "serve.log")))
        t0 = time.monotonic()

        feeder = threading.Thread(
            target=_feed, args=(plan, emb, serve.stdin, t0, state),
            name="gameday-feed", daemon=True)
        feeder.start()
        ingester = threading.Thread(
            target=_ingest, args=(plan, emb, labels, out, t0, state),
            name="gameday-ingest", daemon=True)
        ingester.start()

        # Watch follows the serve telemetry once it exists.
        serve_metrics = os.path.join(out, "serve_tel", "metrics.jsonl")
        watch = None
        observed_signals: Dict[str, int] = {}
        sigs = chaos.signals(entries, "train")
        while time.monotonic() - t0 < duration_s:
            now = time.monotonic() - t0
            if watch is None and os.path.exists(serve_metrics):
                watch = sup.launch(
                    "watch",
                    _python() + ["watch", os.path.join(out, "serve_tel"),
                                 "--slo-config",
                                 os.path.join(out, "slo.json"),
                                 "--follow", "--poll-s", "0.5",
                                 "--for", str(duration_s + 30.0)],
                    env=_child_env(),
                    stdout=sup.open(os.path.join(out, "watch.log")),
                    stderr=subprocess.STDOUT)
            if sigs and now >= sigs[0].at_s:
                entry = sigs.pop(0)
                signum = getattr(signal, entry.name, signal.SIGTERM)
                log.info("gameday: delivering %s to trainer at %.1fs",
                         entry.name, now)
                trainer.send_signal(signum)
                rc = trainer.wait(timeout=60)
                trainer_exits.append(rc)
                observed_signals[entry.name] = (
                    observed_signals.get(entry.name, 0) + 1)
                if rc != 75:
                    raise GamedayError(
                        f"trainer {entry.name} expected exit 75, "
                        f"got {rc}; see {out}/train1.log")
                # Relaunch the SAME command — the auto-resume
                # contract; the consumed chaos env is NOT re-armed.
                trainer = sup.launch(
                    "train", _train_cmd(solver, out),
                    env=_child_env(),
                    stdout=sup.open(os.path.join(out, "train2.log")),
                    stderr=subprocess.STDOUT)
            if serve.poll() is not None:
                raise GamedayError(
                    f"serve died mid-window (rc={serve.returncode}); "
                    f"see {out}/serve.log")
            if trainer.poll() is not None:
                raise GamedayError(
                    f"trainer died mid-window (rc={trainer.returncode})"
                    f"; see {out}/train1.log")
            time.sleep(0.25)

        feeder.join(timeout=30.0)
        time.sleep(3.0)  # let the last swap's resolution land

        # Drain: SIGTERM first (rc 75, the preemption contract), then
        # EOF on stdin so the reader unblocks.
        serve.send_signal(signal.SIGTERM)
        time.sleep(0.2)
        serve.stdin.close()
        serve_rc = serve.wait(timeout=120)
        if serve_rc != 75:
            raise GamedayError(
                f"serve drain expected exit 75, got {serve_rc}; "
                f"see {out}/serve.log")
        trainer.send_signal(signal.SIGTERM)
        rc = trainer.wait(timeout=60)
        trainer_exits.append(rc)
        if rc != 75:
            raise GamedayError(
                f"trainer drain expected exit 75, got {rc}; "
                f"see {out}/train2.log")
        if watch is not None:
            try:
                watch.wait(timeout=45)
            except subprocess.TimeoutExpired:
                watch.terminate()
                watch.wait(timeout=15)
        ingester.join(timeout=15.0)
    finally:
        sup.cleanup()

    if state.get("feed_error"):
        raise GamedayError(state["feed_error"])
    if state.get("ingest_error"):
        raise GamedayError(f"ingest failed: {state['ingest_error']}")

    return _reconcile(out, entries, plan, state, trainer_exits,
                      observed_signals, duration_s=duration_s,
                      seed=seed)


def _reconcile(out: str, entries, plan: tg.TrafficPlan,
               state: Dict[str, Any], trainer_exits: List[int],
               observed_signals: Dict[str, int], *,
               duration_s: float, seed: int) -> Dict[str, Any]:
    """Load every artifact and build the verdict."""
    answers = _jsonl(os.path.join(out, "answers.jsonl"))
    drains = [a for a in answers if a.get("event") == "serve_drain"]
    if not drains:
        raise GamedayError("no serve_drain summary in answers.jsonl")
    drain = drains[-1]

    serve_tel = os.path.join(out, "serve_tel")
    train_tel = os.path.join(out, "train_tel")
    serve_alerts = _jsonl(os.path.join(serve_tel, "alerts.jsonl"))
    train_alerts = _jsonl(os.path.join(train_tel, "alerts.jsonl"))
    serve_rem = _jsonl(os.path.join(serve_tel, "remediation.jsonl"))
    train_rem = _jsonl(os.path.join(train_tel, "remediation.jsonl"))
    serve_rows = [r for r in _jsonl(os.path.join(serve_tel,
                                                 "metrics.jsonl"))
                  if "p99_ms" in r and "wall_time" in r]
    quality = [r for r in _jsonl(os.path.join(serve_tel,
                                              "quality.jsonl"))
               if r.get("kind") == "window"]

    # Qtrace evidence for the p99-attribution check: totals (reroute /
    # hot-swap markers) + the rolling budget decomposition.  A missing
    # or torn artifact is a reportable fact — the stage-declaring
    # faults will fail their attribution gate, which is the point.
    qtrace_block: Dict[str, Any] = {"available": False}
    try:
        with open(os.path.join(serve_tel, "qtrace.json"), "r",
                  encoding="utf-8") as f:
            qt = json.load(f)
        if isinstance(qt, dict) and isinstance(qt.get("totals"), dict):
            qtrace_block = {"available": True,
                            "totals": qt["totals"],
                            "budget": qt.get("budget", {}),
                            "slo_ms": qt.get("slo_ms")}
    except (OSError, ValueError) as e:
        qtrace_block = {"available": False, "reason": str(e)}

    from npairloss_tpu.obs.fleet.aggregate import build_fleet_report

    try:
        fleet = build_fleet_report(train_tel)
        comms = fleet.get("comms", {"available": False})
    except Exception as e:  # noqa: BLE001 — a missing fleet report is
        # a reportable fact, not a crash
        comms = {"available": False, "reason": f"fleet report: {e}"}

    fires = _count_fires([os.path.join(out, name) for name in
                          ("serve.log", "train1.log", "train2.log")])
    for name, count in observed_signals.items():
        fires[name] = fires.get(name, 0) + count

    train2 = os.path.join(out, "train2.log")
    resumed = False
    if os.path.exists(train2):
        with open(train2, "r", encoding="utf-8",
                  errors="replace") as f:
            resumed = "resuming from iteration" in f.read()

    report = gv.build_gameday_report(
        chaos.entry_dicts(entries),
        traffic={
            "planned": len(plan.queries),
            "fed": state.get("fed", 0),
            "answered": drain.get("answered"),
            "errors": drain.get("errors"),
            "rejected": drain.get("rejected"),
            "sha256": tg.plan_digest(plan),
        },
        serve_alerts=serve_alerts, train_alerts=train_alerts,
        serve_remediation=serve_rem, train_remediation=train_rem,
        serve_rows=serve_rows, quality_windows=quality,
        drain=drain, comms=comms,
        trainer={"segments": len(trainer_exits),
                 "exit_codes": trainer_exits, "resumed": resumed},
        observed_fires=fires,
        client_errors=int(drain.get("errors", 0)),
        window_s=duration_s, seed=seed,
        p99_target_ms=P99_TARGET_MS, recall_floor=RECALL_FLOOR,
        min_hot_swaps=MIN_HOT_SWAPS, qtrace=qtrace_block,
    )
    _write_json(os.path.join(out, "gameday.json"), report)
    try:
        # One Perfetto file for the whole day: trainer rank lanes,
        # serve spans + exemplar query trees, chaos/alert/remediation
        # instants (obs/fleet/merge_traces.py).  Evidence, not a gate —
        # a failed merge is logged, never fatal.
        from npairloss_tpu.obs.fleet.merge_traces import merge_timeline

        tl_path, _ = merge_timeline(out)
        if tl_path:
            log.info("gameday: merged timeline at %s", tl_path)
    except Exception as e:  # noqa: BLE001 — the timeline is evidence
        log.error("gameday: timeline merge failed: %s", e)
    log.info("gameday: verdict=%s (%d fault(s), %d hot-swap(s), "
             "%d/%d answered)",
             report["verdict"], len(report["faults"]),
             report["zero_drop"]["hot_swaps"],
             drain.get("answered", 0), state.get("fed", 0))
    return report
