"""Production gameday (docs/RESILIENCE.md §8).

Deterministic traffic (:mod:`~npairloss_tpu.gameday.traffic`), a
declarative chaos schedule (:mod:`~npairloss_tpu.gameday.schedule`),
one supervised composed-system run
(:mod:`~npairloss_tpu.gameday.runner`), and the versioned
``npairloss-gameday-v1`` verdict whose validator IS the pass/fail
contract (:mod:`~npairloss_tpu.gameday.verdict` — stdlib-only, loaded
by file path from the jax-free ``bench_check --gameday`` gate).

The runner is deliberately NOT imported here: it pulls numpy and the
serving stack, while traffic/schedule/verdict stay stdlib-only.
"""

from npairloss_tpu.gameday.schedule import (  # noqa: F401
    ChaosEntry,
    default_schedule,
    env_spec,
    load_schedule,
)
from npairloss_tpu.gameday.traffic import (  # noqa: F401
    TrafficConfig,
    TrafficPlan,
    generate,
    plan_digest,
    plan_lines,
    plan_stats,
)
from npairloss_tpu.gameday.verdict import (  # noqa: F401
    GAMEDAY_SCHEMA,
    build_gameday_report,
    load_gameday_report,
    validate_gameday_report,
)
