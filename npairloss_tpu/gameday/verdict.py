"""The gameday verdict — schema ``npairloss-gameday-v1``.

:func:`build_gameday_report` cross-reconciles every artifact a gameday
run produced — the alert logs, the remediation audits, the serve
metric rows, the shadow-recall quality windows, the drain summary, the
fleet comms block, the trainer's exit codes — into ONE versioned
report, and :func:`validate_gameday_report` IS the pass/fail contract:

  * every injected fault fired, its declared alert fired AND resolved,
    and its declared remediation succeeded (signal faults: exit 75 +
    a resumed segment);
  * every fault that declares a ``stage`` shows it in the qtrace
    evidence (the ``serve.p99_attribution`` check): the dominant stage
    of the worst decomposed window row inside the fault's own incident
    windows must match the declaration — or, for ``reroute``, the
    qtrace artifact must have counted crash-reroute markers — so the
    per-stage attribution is proven against scripted faults, not
    decorative;
  * p99 and shadow recall held on every metric row OUTSIDE the
    declared incident windows (injected faults are supposed to breach
    — each fired alert opens a window ``[fired_at - pad_before,
    resolved_at + pad_after]``; a breach outside every window is a
    real regression);
  * zero dropped queries across every hot-swap: ``queries_dropped`` is
    PRESENT and 0 (the tier ran with explicit drops on — zero is
    evidence, not a default), the ``queries == answered + errors +
    rejected`` invariant holds, and ``hot_swaps`` meets the declared
    minimum;
  * zero unattributed comms bytes whenever the fleet comms block is
    available.

Like every ``npairloss-*-v1`` contract, this module is **stdlib-only
and self-contained**: jax-free gate processes (scripts/bench_check.py
``--gameday``) load it by file path without importing the package, so
it must not import jax, numpy, or any sibling module — pinned by the
staticcheck purity pass (npairloss_tpu/analysis/purity.py).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

GAMEDAY_SCHEMA = "npairloss-gameday-v1"

# Top-level keys every report carries, in order.  "tenants" is NOT in
# this tuple on purpose: reports written before multi-tenant serving
# existed must keep validating, so the tenant-isolation block is
# optional-but-judged (gated whenever present and available).
REPORT_KEYS = (
    "schema", "window_s", "seed", "traffic", "faults", "incidents",
    "slo", "drain", "zero_drop", "comms", "trainer", "qtrace",
    "host_crash", "verdict", "failures",
)
# The tenant-isolation evidence block (the tenant_skew scenario):
# per-tenant counters lifted from the drain summary, per-tenant worst
# recall outside incident windows, and whether a tenant-scoped alert
# (slo name ending "@<tenant_id>" — serve/tenants.py's tenant_of_slo
# naming contract, restated here because this module is loaded by file
# path without the package) ever fired for each tenant.
TENANT_GATE_KEYS = ("available", "hot", "p99_target_ms",
                    "recall_floor", "tenants")
TENANT_ROW_KEYS = ("queries", "answered", "errors", "rejected", "shed",
                   "p99_ms", "alerted", "recall_worst")
# Durable-ingest evidence the SIGKILL drill stores (host_crash block;
# ``{"available": false}`` on runs that scripted no serve kill).  The
# ingest_durable / ingest_no_duplicates fault checks are RECOMPUTED
# from these numbers by ``_gate_failures`` — a report whose fault rows
# claim the checks passed over evidence that says otherwise is refused.
HOST_CRASH_KEYS = ("available", "kills", "acked_batches",
                   "acked_vectors", "lost", "duplicates",
                   "torn_records", "self_recall")
TRAFFIC_KEYS = ("planned", "fed", "answered", "errors", "rejected",
                "sha256")
FAULT_KEYS = (
    "name", "target", "kind", "count", "delay", "at_s", "alert",
    "remediation", "expect", "stage", "observed_fires", "fired",
    "alert_fired", "alert_resolved", "remediation_state",
    "stage_observed", "checks", "ok",
)
P99_KEYS = ("target_ms", "rows", "in_incident", "breaches_outside",
            "worst_outside_ms")
RECALL_KEYS = ("floor", "rows", "in_incident", "breaches_outside",
               "worst_outside")
ZERO_DROP_KEYS = ("min_hot_swaps", "hot_swaps", "queries_dropped",
                  "invariant_holds")
TRAINER_KEYS = ("segments", "exit_codes", "resumed")
VERDICTS = ("pass", "fail")


# -- incident windows --------------------------------------------------------


def incident_windows(alerts: Sequence[Dict[str, Any]],
                     pad_before_s: float = 30.0,
                     pad_after_s: float = 10.0,
                     horizon: Optional[float] = None,
                     ) -> List[Dict[str, Any]]:
    """One window per fired alert: ``[fired_at - pad_before,
    resolved_at + pad_after]``.  The pads cover window-quantized metric
    rows: the breach that FED the alert landed in rows stamped before
    the alert's tick, and recovery is visible one window late.  An
    alert never resolved stays open to ``horizon`` (the run's last
    wall time) — the unresolved alert itself fails a different gate."""
    open_at: Dict[str, Dict[str, Any]] = {}
    out: List[Dict[str, Any]] = []
    for rec in alerts:
        if not isinstance(rec, dict) or "_bad_line" in rec:
            continue
        state, aid = rec.get("state"), rec.get("alert_id")
        if state == "firing" and aid not in open_at:
            open_at[aid] = {
                "slo": rec.get("slo"), "alert_id": aid,
                "start": float(rec["fired_at"]) - pad_before_s,
            }
        elif state == "resolved" and aid in open_at:
            win = open_at.pop(aid)
            win["end"] = float(rec["ts"]) + pad_after_s
            out.append(win)
    for win in open_at.values():  # never resolved: open to the horizon
        win["end"] = (float(horizon) + pad_after_s
                      if horizon is not None else win["start"])
        out.append(win)
    out.sort(key=lambda w: w["start"])
    return out


def _in_windows(t: float, windows: Sequence[Dict[str, Any]]) -> bool:
    return any(w["start"] <= t <= w["end"] for w in windows)


def _slo_gate(rows: Sequence[Dict[str, Any]], metric: str, bad,
              windows: Sequence[Dict[str, Any]]
              ) -> Tuple[int, int, int, float]:
    """(rows, in_incident, breaches_outside, worst_outside) for one
    metric over the run's window rows; ``bad(value)`` is the breach
    predicate."""
    n = inside = breaches = 0
    worst = 0.0
    for row in rows:
        if metric not in row or "wall_time" not in row:
            continue
        n += 1
        value = float(row[metric])
        if _in_windows(float(row["wall_time"]), windows):
            inside += 1
            continue
        if bad(value):
            breaches += 1
        worst = max(worst, value) if metric.endswith("_ms") else worst
    return n, inside, breaches, worst


# -- fault evaluation --------------------------------------------------------


def _host_crash_checks(block: Any) -> Dict[str, bool]:
    """The durable-ingest judgements, derived ONLY from the host_crash
    evidence block (docs/RESILIENCE.md §Durability): ``ingest_durable``
    needs at least one kill actually delivered, zero acknowledged
    vectors lost, and the replayed gallery still answering each acked
    vector with itself (recall parity); ``ingest_no_duplicates`` is the
    exactly-once half — a replay that applied a record twice shows up
    as duplicate ids in the final index."""
    ok = isinstance(block, dict) and block.get("available") is True
    if not ok:
        return {"ingest_durable": False, "ingest_no_duplicates": False}
    try:
        durable = (int(block.get("kills", 0)) >= 1
                   and int(block.get("acked_vectors", -1)) > 0
                   and int(block.get("lost", -1)) == 0
                   and float(block.get("self_recall", 0.0)) >= 0.99)
        nodup = int(block.get("duplicates", -1)) == 0
    except (TypeError, ValueError):
        return {"ingest_durable": False, "ingest_no_duplicates": False}
    return {"ingest_durable": durable, "ingest_no_duplicates": nodup}


def _alert_events(alerts: Sequence[Dict[str, Any]], slo: str
                  ) -> Tuple[bool, bool]:
    fired = resolved = False
    for rec in alerts:
        if not isinstance(rec, dict) or rec.get("slo") != slo:
            continue
        if rec.get("state") == "firing":
            fired = True
        elif rec.get("state") == "resolved":
            resolved = True
    return fired, resolved


def _remediation_state(records: Sequence[Dict[str, Any]], policy: str
                       ) -> str:
    """Best outcome the audit shows for ``policy``: succeeded beats
    failed beats attempted beats missing (a retried action that
    eventually lands is a success story, not a failure)."""
    states = {rec.get("state") for rec in records
              if isinstance(rec, dict) and rec.get("policy") == policy}
    for best in ("succeeded", "failed", "attempted"):
        if best in states:
            return best
    return "missing"


def _observed_stage(entry: Dict[str, Any], *, windows, serve_rows,
                    qtrace: Optional[Dict[str, Any]]) -> str:
    """The ``serve.p99_attribution`` evidence for one fault: the
    qtrace dominant stage of the WORST decomposed row inside the
    fault's own alert windows (the row where the fault bit hardest) —
    or ``"reroute"`` when the artifact counted crash-reroute markers
    (a reroute is a marker, not a stage, so it has no window).  ""
    means no evidence: qtrace off, no decomposed rows, no markers."""
    if entry.get("stage") == "reroute":
        reroutes = int(((qtrace or {}).get("totals") or {})
                       .get("reroutes", 0))
        return "reroute" if reroutes > 0 else ""
    mine = [w for w in windows if w.get("slo") == entry.get("alert")]
    best, best_ms = "", -1.0
    for row in serve_rows:
        if not isinstance(row, dict) or "wall_time" not in row:
            continue
        stage = row.get("qtrace_dominant")
        ms = row.get("qtrace_dominant_ms")
        if not stage or not isinstance(ms, (int, float)):
            continue
        if not _in_windows(float(row["wall_time"]), mine):
            continue
        if ms > best_ms:
            best, best_ms = str(stage), float(ms)
    return best


def _eval_fault(entry: Dict[str, Any], *, alerts, remediation,
                observed_fires: Dict[str, int], client_errors: int,
                trainer: Dict[str, Any], windows=(), serve_rows=(),
                qtrace: Optional[Dict[str, Any]] = None,
                host_crash: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    name = entry["name"]
    kind = entry.get("kind", "failpoint")
    observed = int(observed_fires.get(name, 0))
    fired = observed > 0
    alert = entry.get("alert")
    remedy = entry.get("remediation")
    stage = entry.get("stage")
    alert_fired = alert_resolved = False
    if alert:
        alert_fired, alert_resolved = _alert_events(alerts, alert)
    state = _remediation_state(remediation, remedy) if remedy else None
    stage_observed = (_observed_stage(entry, windows=windows,
                                      serve_rows=serve_rows,
                                      qtrace=qtrace)
                      if stage else "")
    checks: Dict[str, bool] = {}
    for check in entry.get("expect") or ():
        if check == "zero_client_errors":
            checks[check] = client_errors == 0
        elif check == "preempt_exit":
            checks[check] = 75 in (trainer.get("exit_codes") or [])
        elif check == "resume":
            checks[check] = bool(trainer.get("resumed"))
        elif check in ("ingest_durable", "ingest_no_duplicates"):
            checks[check] = _host_crash_checks(host_crash)[check]
        else:
            checks[check] = False  # unknown check never passes
    ok = all(checks.values())
    if kind == "failpoint":
        ok = ok and fired
    if kind in ("failpoint", "traffic"):
        # A "traffic" entry scripts no fault site (the chaos is the
        # traffic plan's own shape, e.g. a hot-tenant burst), so its
        # whole evidence is the declared alert pair + remediation.
        if alert:
            ok = ok and alert_fired and alert_resolved
        if remedy:
            ok = ok and state == "succeeded"
    if stage:
        ok = ok and stage_observed == stage
    return {
        "name": name, "target": entry.get("target", "serve"),
        "kind": kind, "count": int(entry.get("count", 1)),
        "delay": int(entry.get("delay", 0)),
        "at_s": float(entry.get("at_s", 0.0)),
        "alert": alert, "remediation": remedy,
        "expect": list(entry.get("expect") or ()),
        "stage": stage,
        "observed_fires": observed, "fired": fired,
        "alert_fired": alert_fired, "alert_resolved": alert_resolved,
        "remediation_state": state, "stage_observed": stage_observed,
        "checks": checks, "ok": ok,
    }


# -- report assembly ---------------------------------------------------------


def build_gameday_report(
    entries: Sequence[Dict[str, Any]],
    *,
    traffic: Dict[str, Any],
    serve_alerts: Sequence[Dict[str, Any]],
    train_alerts: Sequence[Dict[str, Any]],
    serve_remediation: Sequence[Dict[str, Any]],
    train_remediation: Sequence[Dict[str, Any]],
    serve_rows: Sequence[Dict[str, Any]],
    quality_windows: Sequence[Dict[str, Any]],
    drain: Dict[str, Any],
    comms: Dict[str, Any],
    trainer: Dict[str, Any],
    observed_fires: Dict[str, int],
    client_errors: int,
    window_s: float,
    seed: int,
    p99_target_ms: float = 250.0,
    recall_floor: float = 0.95,
    pad_before_s: float = 30.0,
    pad_after_s: float = 10.0,
    min_hot_swaps: int = 3,
    qtrace: Optional[Dict[str, Any]] = None,
    host_crash: Optional[Dict[str, Any]] = None,
    tenant_hot: Optional[str] = None,
    tenant_quality: Optional[Dict[str, Sequence[Dict[str, Any]]]] = None,
) -> Dict[str, Any]:
    """Assemble (and self-judge) the report.  Inputs are plain dicts/
    lists — the runner loads the artifacts; this function only
    reconciles, so it stays importable without the package."""
    wall_times = [float(r["wall_time"])
                  for r in list(serve_rows) + list(quality_windows)
                  if isinstance(r, dict) and "wall_time" in r]
    horizon = max(wall_times) if wall_times else None
    windows = incident_windows(
        list(serve_alerts) + list(train_alerts),
        pad_before_s=pad_before_s, pad_after_s=pad_after_s,
        horizon=horizon)

    faults = [_eval_fault(
        e, alerts=(serve_alerts if e.get("target", "serve") == "serve"
                   else train_alerts),
        remediation=(serve_remediation
                     if e.get("target", "serve") == "serve"
                     else train_remediation),
        observed_fires=observed_fires, client_errors=client_errors,
        trainer=trainer, windows=windows, serve_rows=serve_rows,
        qtrace=qtrace, host_crash=host_crash) for e in entries]

    n, inside, breaches, worst = _slo_gate(
        serve_rows, "p99_ms", lambda v: v > p99_target_ms, windows)
    p99 = {"target_ms": p99_target_ms, "rows": n, "in_incident": inside,
           "breaches_outside": breaches, "worst_outside_ms": worst}
    n, inside, breaches, _ = _slo_gate(
        quality_windows, "recall_at_10", lambda v: v < recall_floor,
        windows)
    outside = [float(r["recall_at_10"]) for r in quality_windows
               if isinstance(r, dict) and "recall_at_10" in r
               and "wall_time" in r
               and not _in_windows(float(r["wall_time"]), windows)]
    recall = {"floor": recall_floor, "rows": n, "in_incident": inside,
              "breaches_outside": breaches,
              "worst_outside": min(outside) if outside else 1.0}

    dropped = drain.get("queries_dropped")
    invariant = (drain.get("queries", -1)
                 == (drain.get("answered", 0) + drain.get("errors", 0)
                     + drain.get("rejected", 0)))
    zero_drop = {
        "min_hot_swaps": min_hot_swaps,
        "hot_swaps": int(drain.get("hot_swaps", 0)),
        "queries_dropped": dropped,
        "invariant_holds": bool(invariant),
    }

    # Tenant-isolation evidence (the tenant_skew scenario): every
    # number RE-derived from the drain's per-tenant blocks, the alert
    # log and the per-tenant quality windows — never trusted from a
    # caller's claim.
    tenants_block: Dict[str, Any] = {"available": False}
    tdrain = drain.get("tenants")
    if tenant_hot is not None and isinstance(tdrain, dict):
        per: Dict[str, Any] = {}
        for tid in sorted(tdrain):
            row = tdrain[tid] if isinstance(tdrain[tid], dict) else {}
            quota_sheds = 0
            quota = row.get("quota")
            if isinstance(quota, dict):
                quota_sheds = int(quota.get("sheds", 0))
            alerted = any(
                isinstance(rec, dict) and rec.get("state") == "firing"
                and isinstance(rec.get("slo"), str)
                and rec["slo"].endswith(f"@{tid}")
                for rec in serve_alerts)
            qrows = list((tenant_quality or {}).get(tid) or ())
            outside = [
                float(r["recall_at_10"]) for r in qrows
                if isinstance(r, dict) and "recall_at_10" in r
                and "wall_time" in r
                and not _in_windows(float(r["wall_time"]), windows)]
            per[tid] = {
                "queries": int(row.get("queries", 0)),
                "answered": int(row.get("answered", 0)),
                "errors": int(row.get("errors", 0)),
                "rejected": int(row.get("rejected", 0)),
                "shed": quota_sheds + int(row.get("shed", 0)),
                "p99_ms": float(row.get("p99_ms", 0.0)),
                "alerted": alerted,
                "recall_worst": (min(outside) if outside else None),
            }
        tenants_block = {
            "available": True,
            "hot": tenant_hot,
            "p99_target_ms": float(p99_target_ms),
            "recall_floor": float(recall_floor),
            "tenants": per,
        }

    report = {
        "schema": GAMEDAY_SCHEMA,
        "window_s": float(window_s),
        "seed": int(seed),
        "traffic": {key: traffic.get(key) for key in TRAFFIC_KEYS},
        "faults": faults,
        "incidents": windows,
        "slo": {"p99": p99, "recall": recall},
        "drain": dict(drain),
        "zero_drop": zero_drop,
        "comms": dict(comms),
        "trainer": {key: trainer.get(key) for key in TRAINER_KEYS},
        "qtrace": (dict(qtrace) if isinstance(qtrace, dict)
                   else {"available": False}),
        "host_crash": (dict(host_crash) if isinstance(host_crash, dict)
                       else {"available": False}),
        "tenants": tenants_block,
        "verdict": "fail",
        "failures": [],
    }
    report["failures"] = _gate_failures(report)
    report["verdict"] = "pass" if not report["failures"] else "fail"
    return report


def _gate_failures(report: Dict[str, Any]) -> List[str]:
    """Every violated gate, by name — the verdict and the validator
    both derive from this one judgement, so they can never disagree."""
    failures: List[str] = []
    for fault in report["faults"]:
        if fault.get("ok"):
            continue
        name = fault.get("name", "?")
        if fault.get("kind") == "failpoint" and not fault.get("fired"):
            failures.append(f"fault never fired: {name}")
        elif fault.get("alert") and not (fault.get("alert_fired")
                                         and fault.get("alert_resolved")):
            failures.append(
                f"unremediated injected fault: {name} (alert "
                f"{fault.get('alert')} fired={fault.get('alert_fired')} "
                f"resolved={fault.get('alert_resolved')})")
        elif (fault.get("remediation")
              and fault.get("remediation_state") != "succeeded"):
            failures.append(
                f"unremediated injected fault: {name} (remediation "
                f"{fault.get('remediation')} state="
                f"{fault.get('remediation_state')})")
        elif (fault.get("stage")
              and fault.get("stage_observed") != fault.get("stage")):
            failures.append(
                f"p99 attribution mismatch: {name} declared stage "
                f"{fault.get('stage')!r}, evidence showed "
                f"{fault.get('stage_observed') or 'nothing'!r}")
        else:
            bad = [c for c, ok in (fault.get("checks") or {}).items()
                   if not ok]
            failures.append(f"fault check failed: {name} ({bad})")
    # Durable-ingest checks are RECOMPUTED from the host_crash evidence
    # block, never trusted from the stored fault row — a report whose
    # SIGKILL fault claims ingest_durable over evidence showing acked
    # loss (or no evidence at all) is refused here, which is the same
    # gate validate_gameday_report re-derives.
    hc: Optional[Dict[str, bool]] = None
    for fault in report["faults"]:
        ingest_checks = [c for c in (fault.get("expect") or ())
                         if c in ("ingest_durable", "ingest_no_duplicates")]
        if not ingest_checks:
            continue
        if hc is None:
            hc = _host_crash_checks(report.get("host_crash"))
        for check in ingest_checks:
            if not hc[check]:
                failures.append(
                    f"host-crash evidence refutes {fault.get('name', '?')}"
                    f": {check} recomputed false from the host_crash "
                    f"block")
    p99 = report["slo"]["p99"]
    if p99["breaches_outside"]:
        failures.append(
            f"p99 breached outside incident windows: "
            f"{p99['breaches_outside']} row(s), worst "
            f"{p99['worst_outside_ms']:.1f}ms > {p99['target_ms']}ms")
    recall = report["slo"]["recall"]
    if recall["breaches_outside"]:
        failures.append(
            f"recall breached outside incident windows: "
            f"{recall['breaches_outside']} row(s), worst "
            f"{recall['worst_outside']:.3f} < {recall['floor']}")
    zero = report["zero_drop"]
    if zero["queries_dropped"] is None:
        failures.append(
            "queries_dropped missing from the drain summary (the tier "
            "must run with explicit drops on — zero is evidence)")
    elif zero["queries_dropped"] != 0:
        failures.append(
            f"dropped queries: {zero['queries_dropped']}")
    if not zero["invariant_holds"]:
        failures.append("drain invariant violated "
                        "(queries != answered + errors + rejected)")
    if zero["hot_swaps"] < zero["min_hot_swaps"]:
        failures.append(
            f"too few hot-swaps: {zero['hot_swaps']} < "
            f"{zero['min_hot_swaps']}")
    comms = report["comms"]
    if comms.get("available") and comms.get("unattributed_bytes", 0) != 0:
        failures.append(
            f"unattributed comms bytes: {comms.get('unattributed_bytes')}")
    # Tenant isolation (the tenant_skew scenario): the NOISY tenant
    # must have been shed AND paged with a tenant-scoped alert, while
    # every OTHER tenant kept zero errors, zero rejects, its p99 under
    # the target, and (when shadow-scored) its recall over the floor —
    # a hot neighbor that degrades the quiet tenants fails the gameday
    # even if every tier-wide gate above held.
    tb = report.get("tenants") or {}
    if isinstance(tb, dict) and tb.get("available"):
        hot = tb.get("hot")
        target = float(tb.get("p99_target_ms", 0.0) or 0.0)
        floor = tb.get("recall_floor")
        per = tb.get("tenants") if isinstance(tb.get("tenants"), dict) \
            else {}
        hot_row = per.get(hot)
        if not isinstance(hot_row, dict):
            failures.append(
                f"tenant skew: hot tenant {hot!r} missing from the "
                "drain's per-tenant evidence")
        else:
            if (int(hot_row.get("rejected", 0)) <= 0
                    and int(hot_row.get("shed", 0)) <= 0):
                failures.append(
                    f"tenant skew: noisy tenant {hot!r} was never "
                    "shed — isolation unproven")
            if not hot_row.get("alerted"):
                failures.append(
                    f"tenant skew: no tenant-scoped alert "
                    f"(...@{hot}) ever fired for the noisy tenant")
        for tid in sorted(per):
            row = per[tid]
            if tid == hot or not isinstance(row, dict):
                continue
            if int(row.get("errors", 0)) != 0:
                failures.append(
                    f"tenant isolation: {tid!r} saw "
                    f"{row.get('errors')} error(s) during the "
                    "hot-tenant burst")
            if int(row.get("rejected", 0)) != 0:
                failures.append(
                    f"tenant isolation: {tid!r} had "
                    f"{row.get('rejected')} rejected quer(ies) — the "
                    "noisy neighbor's shed leaked")
            if target and float(row.get("p99_ms", 0.0)) > target:
                failures.append(
                    f"tenant isolation: {tid!r} p99 "
                    f"{row.get('p99_ms')}ms > {target}ms")
            worst = row.get("recall_worst")
            if (floor is not None and worst is not None
                    and float(worst) < float(floor)):
                failures.append(
                    f"tenant isolation: {tid!r} recall {worst} < "
                    f"floor {floor}")
    return failures


# -- the contract ------------------------------------------------------------


def validate_gameday_report(obj: Any) -> Optional[str]:
    """None when ``obj`` is a passing ``npairloss-gameday-v1`` report;
    else the first violation.  The gate recomputes every judgement from
    the report's own evidence — a tampered ``verdict: "pass"`` over
    failing blocks is refused, and so is a failing verdict."""
    if not isinstance(obj, dict):
        return f"report must be an object, got {type(obj).__name__}"
    if obj.get("schema") != GAMEDAY_SCHEMA:
        return (f"schema must be {GAMEDAY_SCHEMA!r}, "
                f"got {obj.get('schema')!r}")
    for key in REPORT_KEYS:
        if key not in obj:
            return f"missing key: {key}"
    if obj.get("verdict") not in VERDICTS:
        return f"verdict must be one of {VERDICTS}, got {obj.get('verdict')!r}"
    for block, keys in (("traffic", TRAFFIC_KEYS),
                        ("zero_drop", ZERO_DROP_KEYS),
                        ("trainer", TRAINER_KEYS)):
        if not isinstance(obj[block], dict):
            return f"{block} must be an object"
        for key in keys:
            if key not in obj[block]:
                return f"{block} missing key: {key}"
    slo = obj["slo"]
    if not isinstance(slo, dict) or "p99" not in slo or "recall" not in slo:
        return "slo must carry p99 and recall blocks"
    for block, keys in (("p99", P99_KEYS), ("recall", RECALL_KEYS)):
        for key in keys:
            if key not in slo[block]:
                return f"slo.{block} missing key: {key}"
    if not isinstance(obj["faults"], list) or not obj["faults"]:
        return "faults must be a non-empty list (a gameday with no "\
               "injected faults proved nothing)"
    for i, fault in enumerate(obj["faults"]):
        if not isinstance(fault, dict):
            return f"faults[{i}] must be an object"
        for key in FAULT_KEYS:
            if key not in fault:
                return f"faults[{i}] missing key: {key}"
    if not isinstance(obj["incidents"], list):
        return "incidents must be a list"
    if not isinstance(obj["failures"], list):
        return "failures must be a list"
    if not isinstance(obj["qtrace"], dict):
        return "qtrace must be an object (the summarized qtrace "\
               "evidence, or {\"available\": false})"
    hc = obj["host_crash"]
    if not isinstance(hc, dict):
        return "host_crash must be an object (the durable-ingest "\
               "evidence, or {\"available\": false})"
    if hc.get("available"):
        for key in HOST_CRASH_KEYS:
            if key not in hc:
                return f"host_crash missing key: {key}"
    # "tenants" is optional (pre-multi-tenant reports lack it) but when
    # present and available its shape must be complete — the per-tenant
    # isolation gates below read it blind.
    tb = obj.get("tenants")
    if tb is not None:
        if not isinstance(tb, dict):
            return "tenants must be an object (the per-tenant "\
                   "isolation evidence, or {\"available\": false})"
        if tb.get("available"):
            for key in TENANT_GATE_KEYS:
                if key not in tb:
                    return f"tenants missing key: {key}"
            per = tb["tenants"]
            if not isinstance(per, dict) or not per:
                return "tenants.tenants must be a non-empty object "\
                       "keyed by tenant id"
            if tb["hot"] not in per:
                return (f"tenants.hot {tb['hot']!r} is not one of the "
                        "evidenced tenants")
            for tid, row in per.items():
                if not isinstance(row, dict):
                    return f"tenants.tenants[{tid!r}] must be an object"
                for key in TENANT_ROW_KEYS:
                    if key not in row:
                        return (f"tenants.tenants[{tid!r}] missing "
                                f"key: {key}")

    # Recompute the gates from the evidence; the stored verdict and
    # failures must agree with them.
    failures = _gate_failures(obj)
    if failures:
        return f"gameday gate failed: {failures[0]}" \
            + (f" (+{len(failures) - 1} more)" if len(failures) > 1
               else "")
    if obj["verdict"] != "pass":
        return ("every gate holds but verdict says "
                f"{obj['verdict']!r} — inconsistent report")
    if obj["failures"]:
        return ("verdict is pass but failures is non-empty: "
                f"{obj['failures'][0]}")
    return None


def load_gameday_report(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
