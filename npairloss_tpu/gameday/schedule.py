"""Declarative chaos schedule — scripted faults with declared evidence.

Each :class:`ChaosEntry` arms ONE existing failpoint
(resilience/failpoints.py; the gameday invents no new fault sites) or
schedules ONE signal, in the same ``name:count@delay`` grammar the
``NPAIRLOSS_FAILPOINTS`` env var speaks — and declares, up front, the
evidence the run must produce: the alert that must fire, the
remediation that must resolve it, and any extra checks
(``zero_client_errors``, ``preempt_exit``, ``resume``,
``ingest_durable``, ``ingest_no_duplicates``).  The verdict
(gameday/verdict.py) holds the run to exactly these declarations: an
injected fault with no paging/actuation evidence fails the gameday.

Stdlib-only: schedules load in the jax-free gate path too.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

TARGETS = ("serve", "train")
# "traffic" entries script no fault site at all: the chaos IS the
# traffic plan's shape (e.g. the tenant-skew hot-tenant burst), and the
# declared alert pair is the only evidence they leave.
KINDS = ("failpoint", "signal", "traffic")
# Extra per-entry checks the verdict knows how to verify.
EXPECT_CHECKS = ("zero_client_errors", "preempt_exit", "resume",
                 "ingest_durable", "ingest_no_duplicates")
# Declarable p99-attribution evidence: the qtrace stage the fault's
# incident window must show as dominant (the obs.qtrace stage
# vocabulary — restated here because the gate path loads this module
# without the package), plus "reroute" for faults whose signature is
# the crash-reroute marker rather than a stage.
STAGE_CHECKS = ("admit_wait", "queue_wait", "batch_assemble",
                "dispatch", "score", "topk_merge", "reroute")


@dataclasses.dataclass(frozen=True)
class ChaosEntry:
    """One scripted fault and the evidence it must leave behind.

    ``failpoint`` entries arm ``name:count@delay`` in the target
    process's environment; ``delay`` counts CHECKS at the site (the
    grammar's contract), ``at_s`` is advisory wall-clock documentation
    of roughly when that lands in the window.  ``signal`` entries are
    delivered by the runner at ``at_s`` (name is the signal, e.g.
    ``SIGTERM``)."""

    name: str
    target: str = "serve"
    kind: str = "failpoint"
    count: int = 1
    delay: int = 0
    at_s: float = 0.0
    alert: Optional[str] = None        # SLO id that must fire+resolve
    remediation: Optional[str] = None  # policy that must succeed
    expect: Tuple[str, ...] = ()
    stage: Optional[str] = None        # dominant qtrace stage expected

    def __post_init__(self):
        if not self.name:
            raise ValueError("ChaosEntry needs a name")
        if self.target not in TARGETS:
            raise ValueError(
                f"target must be one of {TARGETS}, got {self.target!r}")
        if self.kind not in KINDS:
            raise ValueError(
                f"kind must be one of {KINDS}, got {self.kind!r}")
        if self.kind == "failpoint" and self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.delay < 0 or self.at_s < 0:
            raise ValueError(
                f"delay/at_s must be >= 0, got {self.delay}/{self.at_s}")
        if self.remediation and not self.alert:
            raise ValueError(
                f"{self.name}: a remediation declaration needs the "
                "alert that triggers it")
        bad = [e for e in self.expect if e not in EXPECT_CHECKS]
        if bad:
            raise ValueError(
                f"{self.name}: unknown expect check(s) {bad}; "
                f"known: {EXPECT_CHECKS}")
        if self.kind == "signal" and (self.alert or self.remediation):
            raise ValueError(
                f"{self.name}: signal entries declare evidence via "
                "expect checks (preempt_exit/resume), not alerts")
        if self.kind == "traffic" and not self.alert:
            raise ValueError(
                f"{self.name}: a traffic entry's only evidence is its "
                "alert pair — declare the alert it must fire+resolve")
        if self.stage is not None:
            if self.stage not in STAGE_CHECKS:
                raise ValueError(
                    f"{self.name}: unknown stage {self.stage!r}; "
                    f"known: {STAGE_CHECKS}")
            if self.stage != "reroute" and not self.alert:
                raise ValueError(
                    f"{self.name}: a stage declaration needs the alert "
                    "whose incident window anchors the attribution "
                    "check (reroute is marker-counted, not windowed)")

    def spec(self) -> str:
        """This entry in the env grammar: ``name``, ``name:count`` or
        ``name:count@delay`` — canonical (no redundant suffixes)."""
        if self.kind != "failpoint":
            raise ValueError(f"{self.name} is a {self.kind}, not a "
                             "failpoint")
        if self.delay:
            return f"{self.name}:{self.count}@{self.delay}"
        if self.count != 1:
            return f"{self.name}:{self.count}"
        return self.name


def env_spec(entries: Sequence[ChaosEntry], target: str) -> str:
    """The comma-separated ``NPAIRLOSS_FAILPOINTS`` value arming every
    failpoint entry aimed at ``target`` ("" = nothing to arm)."""
    return ",".join(e.spec() for e in entries
                    if e.kind == "failpoint" and e.target == target)


def signals(entries: Sequence[ChaosEntry],
            target: str) -> List[ChaosEntry]:
    """Signal entries aimed at ``target``, soonest first."""
    out = [e for e in entries
           if e.kind == "signal" and e.target == target]
    return sorted(out, key=lambda e: e.at_s)


def default_schedule(duration_s: float = 75.0) -> List[ChaosEntry]:
    """The compressed-day schedule (docs/RESILIENCE.md §8): every
    serving/training fault family, timed so pre-fault health exists
    (snapshots committed, warmup done, traffic flowing)."""
    return [
        # Staleness poisoning: a handful of poisoned freshness probes
        # after the tier has warmed — drives model_staleness and the
        # snapshot hot-swap remediation.
        ChaosEntry(name="serve.stale_model", target="serve",
                   count=6, delay=10, at_s=0.15 * duration_s,
                   alert="model_staleness",
                   remediation="hotswap_model"),
        # A p99 burst well into the window (delay counts dispatches,
        # so it lands once real traffic has flowed) — drives serve_p99
        # and load shedding.  The declared stage is queue_wait, not
        # dispatch: on a saturated single-slot tier only the first
        # stalled query pays the stall as dispatch time — everyone
        # behind it pays it as queue wait (the ci.sh qtrace smoke
        # covers the throttled-traffic case where dispatch dominates).
        ChaosEntry(name="serve.latency", target="serve",
                   count=40, delay=200, at_s=0.5 * duration_s,
                   alert="serve_p99", remediation="load_shed",
                   stage="queue_wait"),
        # One replica dies mid-burst; the reroute contract says no
        # client ever notices — checked, not alerted.
        ChaosEntry(name="serve.replica_crash", target="serve",
                   count=1, delay=120, at_s=0.35 * duration_s,
                   expect=("zero_client_errors",), stage="reroute"),
        # Embedding collapse after snapshots exist — drives the
        # embedding-collapse watchdog and the trainer rollback.
        ChaosEntry(name="train.collapse", target="train",
                   count=160, delay=60, at_s=0.3 * duration_s,
                   alert="embedding_collapse",
                   remediation="trainer_rollback"),
        # Mid-stream preemption: the trainer must exit 75 with an
        # emergency snapshot and resume on relaunch.
        ChaosEntry(name="SIGTERM", target="train", kind="signal",
                   at_s=0.4 * duration_s,
                   expect=("preempt_exit", "resume")),
        # Host crash mid-ingest: SIGKILL the serving tier (no handler
        # runs, no drain, no final checkpoint), cold-restart it from
        # the published artifacts + WAL alone, and prove from the
        # host_crash evidence block that every ACKED ingest batch
        # survived exactly once (docs/RESILIENCE.md §Durability).
        ChaosEntry(name="SIGKILL", target="serve", kind="signal",
                   at_s=0.55 * duration_s,
                   expect=("ingest_durable", "ingest_no_duplicates")),
    ]


def tenant_skew_schedule(hot_tenant: str,
                         duration_s: float = 45.0) -> List[ChaosEntry]:
    """The multi-tenant noisy-neighbor scenario (docs/SERVING.md
    §Multi-tenant): the scripted chaos is the traffic plan itself — the
    hot tenant's arrival weight is multiplied inside the burst windows
    (traffic.TrafficConfig hot_burst_factor) until its quota sheds —
    and the declared evidence is the tenant-scoped quota alert pair.
    The ``tenant_quota@<id>`` spelling is serve/tenants.py's
    ``tenant_of_slo`` naming contract, restated here because schedules
    load on the jax-free gate path without the package."""
    if not hot_tenant:
        raise ValueError("tenant_skew_schedule needs the hot tenant id")
    return [ChaosEntry(
        name="hot_tenant_burst", target="serve", kind="traffic",
        at_s=0.4 * duration_s,
        alert=f"tenant_quota@{hot_tenant}")]


def entry_dicts(entries: Sequence[ChaosEntry]) -> List[dict]:
    return [dataclasses.asdict(e) for e in entries]


def load_schedule(path: str) -> List[ChaosEntry]:
    """Load ``{"entries": [...]}`` — validation is ChaosEntry's
    (loud), so a typo'd target or an impossible declaration fails at
    load, not at verdict time."""
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "entries" not in obj:
        raise ValueError(f"{path}: expected an object with 'entries'")
    entries = []
    for i, raw in enumerate(obj["entries"]):
        if not isinstance(raw, dict):
            raise ValueError(f"{path}: entry {i} is not an object")
        kwargs = dict(raw)
        if "expect" in kwargs:
            kwargs["expect"] = tuple(kwargs["expect"])
        try:
            entries.append(ChaosEntry(**kwargs))
        except TypeError as e:
            raise ValueError(f"{path}: entry {i}: {e}") from None
    return entries
