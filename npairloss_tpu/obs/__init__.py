"""Run-telemetry subsystem (docs/OBSERVABILITY.md).

Three coordinated parts:

  * ``obs.sinks`` — structured metric sinks (JSONL / CSV / ring buffer /
    multiplex) behind the ``MetricLogger`` protocol;
  * ``obs.tracing`` — host-side hierarchical span tracing to
    Chrome-trace JSON (Perfetto), complementing device-side
    ``jax.named_scope`` / ``utils.profiling.trace``;
  * ``obs.health`` — optional jit-compatible training-health signals
    (grad/param/update norms, embedding magnitude, mined-pair hardness)
    gated by ``HealthConfig``;
  * ``obs.fleet`` — the multi-rank layer: rank-stamped telemetry with
    per-rank file streams, collective/comms attribution, offline
    straggler/skew aggregation (``prof --fleet``), and merged
    cross-rank Perfetto timelines;
  * ``obs.live`` — the ONLINE layer (§Live observatory): in-process
    metric registry fed by the telemetry streams, declarative SLOs
    with burn-rate alerting, Prometheus ``/metrics``, and per-answer
    freshness — imported explicitly (``npairloss_tpu.obs.live``), not
    re-exported here, so the no-live-obs path pays nothing;

tied together per run by ``obs.run.RunTelemetry`` (run dir with
``manifest.json`` + ``metrics.jsonl`` + ``trace.json``).

``obs.sinks`` and ``obs.tracing`` are stdlib-only modules; jax-free
processes (bench.py's parent) load them by file path to avoid this
package's jax-importing ``__init__``.
"""

from npairloss_tpu.obs.fleet.stamp import FleetStamp, fleet_stamp
from npairloss_tpu.obs.health import HealthConfig
from npairloss_tpu.obs.manifest import RunManifest
from npairloss_tpu.obs.run import RunTelemetry
from npairloss_tpu.obs.sinks import (
    FLEET_KEYS,
    REQUIRED_KEYS,
    CsvSink,
    JsonlSink,
    MetricLogger,
    MultiSink,
    RingBufferSink,
)
from npairloss_tpu.obs.tracing import SpanTracer, validate_chrome_trace

__all__ = [
    "HealthConfig",
    "RunManifest",
    "RunTelemetry",
    "FleetStamp",
    "fleet_stamp",
    "MetricLogger",
    "JsonlSink",
    "CsvSink",
    "RingBufferSink",
    "MultiSink",
    "SpanTracer",
    "validate_chrome_trace",
    "REQUIRED_KEYS",
    "FLEET_KEYS",
]
