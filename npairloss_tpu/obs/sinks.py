"""Structured metric sinks — the one emission pipeline for run telemetry.

The reference layer's only observability was commented-out ``LOG(INFO)``
wall-clock probes (reference: npair_multi_class_loss.cu:423, cu:464-468);
this framework's early telemetry scattered across a ``log_fn`` string
callback, hand-rolled JSON writers in ``bench.py``, and ``StepTimer``.
This module is the structured replacement: a ``MetricLogger`` protocol
with file (JSONL/CSV), in-memory (ring buffer), and fan-out (multiplex)
implementations.  Every record is a flat dict; the stamping of the
required ``{run_id, step, wall_time, phase}`` envelope is
``obs.run.RunTelemetry``'s job, so sinks stay dumb and composable.

IMPORTANT: this module must stay importable WITHOUT jax (stdlib only).
``bench.py``'s parent process loads it by file path to append bench
records — that process is jax-free by design (a hung backend import
must never kill the bench orchestration).
"""

from __future__ import annotations

import collections
import csv
import json
import os
import threading
from typing import Any, Dict, List, Optional, Protocol, Sequence, runtime_checkable

# The envelope every emitted record carries (stamped by RunTelemetry;
# validated by tests and by downstream consumers of metrics.jsonl).
REQUIRED_KEYS = ("run_id", "step", "wall_time", "phase")

# The ADDITIONAL envelope of fleet-stamped records (docs/OBSERVABILITY.md
# §Fleet observatory): rank identity on every row of a multi-process
# run.  Spelled out here (not imported from obs.fleet.stamp, which pins
# the same tuple by test) because THIS module is the one jax-free
# processes load by file path — it must not drag the package in.
FLEET_KEYS = ("process_index", "process_count", "local_device_ids")


@runtime_checkable
class MetricLogger(Protocol):
    """Anything that accepts structured metric records.

    ``log`` takes one flat dict per event; values should be JSON-able
    scalars (floats/ints/strings).  ``flush``/``close`` are lifecycle
    hooks — file sinks flush buffers, in-memory sinks no-op.
    """

    def log(self, record: Dict[str, Any]) -> None: ...

    def flush(self) -> None: ...

    def close(self) -> None: ...


class JsonlSink:
    """Append-only JSON-lines file sink — one record per line.

    Line-buffered so a killed process loses at most the current line
    (the bench spill lesson: partial telemetry beats no telemetry).
    Parent directories are created on demand.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(self.path, "a", buffering=1)
        self._lock = threading.Lock()

    def log(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            self._f.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class CsvSink:
    """CSV file sink for spreadsheet-shaped consumers.

    Columns are fixed by the FIRST record (plus any ``fieldnames`` given
    up front); later records with extra keys have them dropped and
    missing keys filled with "" — CSV cannot grow columns after the
    header, so put the stable keys first or pass ``fieldnames``.
    """

    def __init__(self, path: str, fieldnames: Optional[Sequence[str]] = None):
        self.path = os.path.abspath(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # Appending to an existing file must reuse ITS header, not the
        # first record's key order — otherwise a second process/instance
        # silently writes values under the wrong columns.
        if fieldnames is None and os.path.exists(self.path) \
                and os.path.getsize(self.path) > 0:
            with open(self.path, newline="") as f:
                header = next(csv.reader(f), None)
            if header:
                fieldnames = header
        self._f = open(self.path, "a", buffering=1, newline="")
        self._fieldnames = list(fieldnames) if fieldnames else None
        self._writer: Optional[csv.DictWriter] = None
        self._lock = threading.Lock()

    def log(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._writer is None:
                if self._fieldnames is None:
                    self._fieldnames = list(record.keys())
                self._writer = csv.DictWriter(
                    self._f, self._fieldnames, restval="",
                    extrasaction="ignore",
                )
                if self._f.tell() == 0:
                    self._writer.writeheader()
            self._writer.writerow(record)

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class RingBufferSink:
    """Bounded in-memory sink: keeps the most recent ``capacity`` records.

    The live-introspection sink — a training loop (or an embedding
    process) can read the recent trajectory without touching disk; old
    records evict FIFO so memory stays bounded over million-step runs.
    """

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"ring buffer needs capacity >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0

    def log(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(dict(record))
            self._total += 1

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._buf[-1]) if self._buf else None

    @property
    def total_logged(self) -> int:
        return self._total

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class MultiSink:
    """Fan one record out to several sinks (file + ring buffer is the
    RunTelemetry default).  A child failing must not starve its
    siblings — on log, flush, AND close: every child sees the call, then
    the first child error is re-raised."""

    def __init__(self, children: Sequence[MetricLogger]):
        self.children = list(children)

    def _fan(self, method: str, *args) -> None:
        first_err = None
        for c in self.children:
            try:
                getattr(c, method)(*args)
            except Exception as e:  # noqa: BLE001 — fan-out isolation
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def log(self, record: Dict[str, Any]) -> None:
        self._fan("log", record)

    def flush(self) -> None:
        self._fan("flush")

    def close(self) -> None:
        self._fan("close")
