"""Host-side span tracing — Chrome-trace-event JSON, viewable in Perfetto.

``utils.profiling.trace`` captures DEVICE profiles (XProf) and
``jax.named_scope`` names ops inside the compiled graph; neither shows
the HOST timeline — where did the wall clock go between dispatches?
(data loading, eval, snapshot writes, and above all COMPILES: the
dynamic-batch path in ``train/solver.py`` recompiles on every new batch
shape, and without host spans a recompile is a mystery stall.)

``SpanTracer`` records hierarchical host spans as Chrome trace events
("X" complete events keyed by pid/tid; nesting is derived from
timestamp containment, the Chrome/Perfetto convention), plus "i"
instant events for point-in-time markers.  ``write()`` emits the
``{"traceEvents": [...]}`` JSON Perfetto accepts.

Stdlib only — no jax import (the tracer must work in jax-free
processes like bench.py's parent).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple


class SpanTracer:
    """Collects host spans; thread-safe; bounded by ``max_events``.

    Timestamps are microseconds since the tracer's creation (Chrome
    trace ``ts`` is relative anyway); absolute wall time at creation is
    stamped in the trace metadata so events can be correlated with
    metric records' ``wall_time``.
    """

    def __init__(self, max_events: int = 200_000):
        self._t0 = time.perf_counter()
        self.wall_time_origin = time.time()
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._max_events = max_events
        self._dropped = 0
        self._pid = os.getpid()
        # Fleet identity (obs.fleet.stamp): stamped into the trace
        # metadata so every span in a per-rank trace file is
        # attributable to its rank; None = no fleet block in the
        # output (byte-identical to the pre-fleet trace).
        self.stamp: Optional[Dict[str, Any]] = None

    @property
    def dropped(self) -> int:
        """Events the ``max_events`` cap has eaten so far — consumers
        (solver window rows, serve window rows, the fleet aggregator)
        surface this instead of silently averaging a truncated
        stream."""
        with self._lock:
            return self._dropped

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def now_us(self) -> float:
        """Current tracer-relative timestamp — a cursor consumers can
        compare span timestamps against (e.g. the ``prof`` CLI keeps
        only the spans of its measured loop)."""
        return self._now_us()

    def events_since(self, index: int) -> "Tuple[List[Dict[str, Any]], int, int]":
        """``(events[index:], next_index, dropped)`` — the incremental
        read for windowed consumers (the serve window rows).  Spans are
        appended at span END, so the tail slice is exactly the spans
        that *finished* since the last read: a span in flight across
        the boundary lands in the next window instead of vanishing
        (filtering a full snapshot by start-``ts`` drops every
        boundary-straddling span — the longest ones).  O(new events)
        per read, not O(whole buffer); ``dropped`` > 0 means the
        ``max_events`` cap is eating spans and the split is partial."""
        with self._lock:
            tail = self._events[index:]
            return tail, index + len(tail), self._dropped

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                # No silent caps: the drop count is published in the
                # trace metadata (and a truncated trace stays valid).
                self._dropped += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        """``with tracer.span("data/next_batch"): ...`` — one complete
        ("X") event covering the block.  Nest freely; Perfetto stacks
        spans on the same thread by timestamp containment."""
        t0 = self._now_us()
        try:
            yield
        finally:
            t1 = self._now_us()
            ev: Dict[str, Any] = {
                "name": name,
                "ph": "X",
                "ts": t0,
                "dur": max(t1 - t0, 0.0),
                "pid": self._pid,
                "tid": threading.get_ident() & 0xFFFFFFFF,
            }
            if args:
                ev["args"] = args
            self._append(ev)

    def instant(self, name: str, **args: Any) -> None:
        """Point-in-time marker ("i" event) — e.g. "recompile"."""
        ev: Dict[str, Any] = {
            "name": name,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    @property
    def num_events(self) -> int:
        with self._lock:
            return len(self._events)

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event JSON object (Perfetto's legacy-JSON
        loader accepts exactly this shape)."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        meta: Dict[str, Any] = {
            "wall_time_origin": self.wall_time_origin,
        }
        if dropped:
            meta["dropped_events"] = dropped
        if self.stamp:
            # Rank identity for every span in this stream: the trace
            # file is per-rank under the fleet path scheme, so a
            # file-level stamp makes each event unambiguous without
            # paying ~30 bytes of args on all 200k of them.
            meta["fleet"] = dict(self.stamp)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": meta,
        }

    def write(self, path: str) -> str:
        """Serialize to ``path`` (atomic: tmp + rename); returns path."""
        path = os.path.abspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


def validate_chrome_trace(obj: Any) -> Optional[str]:
    """Schema check for the trace JSON this module writes — returns an
    error string or None.  The contract Perfetto's JSON importer needs:
    a ``traceEvents`` list whose entries carry ``name``/``ph``/``ts``
    (+ ``dur`` for "X" events), with numeric timestamps."""
    if not isinstance(obj, dict):
        return "trace must be a JSON object"
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return "missing traceEvents list"
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return f"event {i} is not an object"
        for key in ("name", "ph", "ts"):
            if key not in ev:
                return f"event {i} missing {key!r}"
        if not isinstance(ev["ts"], (int, float)):
            return f"event {i} ts is not numeric"
        if ev["ph"] == "X" and not isinstance(ev.get("dur"), (int, float)):
            return f"event {i} is 'X' but has no numeric dur"
    return None
