"""In-graph training-health signals — optional, jit-compatible diagnostics.

The reference monitored training health with an in-training
Recall@{1,5,10} metric and a feature-magnitude probe
(GetRetrivePerformance + asum, reference:
npair_multi_class_loss.cu:173-206, cu:400-401).  This module generalizes
that idea to the signals large-scale training actually triages with:

  * global gradient norm (exploding/vanishing gradients),
  * parameter norm and update/param ratio (the "is the lr sane" signal
    — healthy runs sit around 1e-3),
  * embedding-magnitude mean/max (the reference's feature monitor: after
    L2 normalize these pin to 1.0; drift means the normalize layer or
    its gradient broke),
  * mined-pair hardness summaries (selected pair counts and the mining
    thresholds from ``ops.rank_select``-backed RELATIVE mining — a
    collapsing embedding shows up here before it shows up in loss).

Everything is a fixed-shape fp32 reduction folded into the jitted step's
metric dict, gated by ``HealthConfig``: with ``health=None`` (the
Solver default) no op is added and the hot path compiles identical HLO
to a build without this module.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Which health signals to fold into the step's metric dict.

    Each enabled signal costs a few whole-tree or whole-batch fp32
    reductions inside the jitted step — negligible next to the trunk
    gemms, but not free; the Solver's default (no HealthConfig) adds
    nothing.
    """

    grad_norm: bool = True
    param_norm: bool = True
    update_ratio: bool = True
    embedding_magnitude: bool = True
    pair_hardness: bool = True
    # Mining-health telemetry (docs/OBSERVABILITY.md §Quality
    # observatory): AP/AN margin-distribution + hard-negative-
    # saturation stats derived from the SAME loss aux pair_hardness
    # already reads — collapse as a quality trend.  Default OFF: the
    # row-key set with the flag off is byte-identical to a pre-quality
    # build (pinned by tests/test_quality.py).
    mining_health: bool = False
    eps: float = 1e-12


def tree_l2_norm(tree: Any) -> jax.Array:
    """Global L2 norm over every leaf of a pytree, accumulated in fp32
    (bf16 params/grads would overflow a squared sum in their own dtype)."""
    sq = jax.tree_util.tree_reduce(
        lambda acc, x: acc + jnp.sum(jnp.square(x.astype(jnp.float32))),
        tree,
        jnp.float32(0.0),
    )
    return jnp.sqrt(sq)


def update_health(
    grads: Any, params: Any, updates: Any, cfg: HealthConfig
) -> Dict[str, jax.Array]:
    """Optimizer-side signals from one step's (grads, params, updates).

    ``update_ratio`` is ||update|| / ||param|| — the per-step relative
    parameter motion; lr schedules are sane when this sits near 1e-3
    and broken when it hits 1e-1 (divergence) or 1e-7 (frozen run).
    """
    out: Dict[str, jax.Array] = {}
    if cfg.grad_norm:
        out["grad_norm"] = tree_l2_norm(grads)
    param_norm = None
    if cfg.param_norm or cfg.update_ratio:
        param_norm = tree_l2_norm(params)
    if cfg.param_norm:
        out["param_norm"] = param_norm
    if cfg.update_ratio:
        out["update_norm"] = tree_l2_norm(updates)
        out["update_ratio"] = out["update_norm"] / (
            param_norm + jnp.float32(cfg.eps)
        )
    return out


def embedding_health(features: jax.Array) -> Dict[str, jax.Array]:
    """Embedding-magnitude mean/max — the reference's feature monitor
    generalized from asum to row L2 norms (one home:
    ``ops.metrics.embedding_magnitude``)."""
    from npairloss_tpu.ops.metrics import embedding_magnitude

    return embedding_magnitude(features)


# Mining thresholds use ±inf/±FLT_MAX sentinels for "no candidates /
# select everything" queries; any |threshold| past this cutoff is a
# sentinel, not a similarity (post-L2Normalize sims live in [-1, 1]).
_THRESHOLD_SENTINEL = 1e30


def _finite_mean(x: jax.Array) -> jax.Array:
    """Mean over non-sentinel entries; 0 when every entry is a sentinel
    (an all-sentinel batch must report a FINITE health row — the health
    metrics feed assert_all_finite under --debug-checks)."""
    x = x.astype(jnp.float32)
    ok = jnp.isfinite(x) & (jnp.abs(x) < _THRESHOLD_SENTINEL)
    cnt = ok.sum()
    total = jnp.where(ok, x, 0.0).sum()
    return jnp.where(cnt > 0, total / jnp.maximum(cnt, 1), 0.0)


# The AN-frontier cosine past which a query's mined negatives count as
# SATURATED: the threshold no longer discriminates — everything looks
# like a hard negative (post-L2Normalize sims live in [-1, 1], so 0.9
# is deep in collapse territory for random-ish classes).
SATURATION_COSINE = 0.9


def pair_hardness_health(
    aux: Dict[str, jax.Array], mining: bool = False
) -> Dict[str, jax.Array]:
    """Mined-pair hardness summary from the dense engine's loss aux.

    ``mined_pos/neg_per_query`` are the reference's identNum/diffNum
    (cu:357/360) averaged over queries; ``ap/an_threshold_mean`` are the
    mining thresholds (exact rank statistics via ``ops.rank_select`` for
    RELATIVE_* methods), averaged over the queries that actually had
    candidates.  Thresholds drifting toward +1 while counts collapse is
    the classic embedding-collapse signature.

    ``mining=True`` (HealthConfig.mining_health) adds the quality-trend
    stats — derived from the SAME per-query thresholds, so they exist
    across the whole GLOBAL/LOCAL × HARD/RELATIVE mining grid:

      * ``ap_an_margin_mean``: mean AP−AN threshold margin over queries
        with both frontiers defined — the distance between "what counts
        as a positive" and "what counts as a hard negative";
      * ``ap_an_margin_p10``: the 10th-percentile margin — the weakest
        queries collapse FIRST, so the low tail leads the mean;
      * ``an_saturation``: fraction of defined AN frontiers past
        :data:`SATURATION_COSINE` — how much of the batch mines
        negatives that are indistinguishable from positives.

    With ``mining=False`` the returned key set is byte-identical to the
    pre-quality build (the row-parity pin).  Every stat is finite by
    construction (sentinel-masked, zero-filled when undefined) — the
    health metrics feed assert_all_finite under --debug-checks.
    """
    stop = jax.lax.stop_gradient
    out = {
        "mined_pos_per_query": stop(aux["ident_num"]).mean(),
        "mined_neg_per_query": stop(aux["diff_num"]).mean(),
        "ap_threshold_mean": _finite_mean(stop(aux["pos_threshold"])),
        "an_threshold_mean": _finite_mean(stop(aux["neg_threshold"])),
    }
    if not mining:
        return out
    pos = stop(aux["pos_threshold"]).astype(jnp.float32)
    neg = stop(aux["neg_threshold"]).astype(jnp.float32)
    ok_p = jnp.isfinite(pos) & (jnp.abs(pos) < _THRESHOLD_SENTINEL)
    ok_n = jnp.isfinite(neg) & (jnp.abs(neg) < _THRESHOLD_SENTINEL)
    ok = ok_p & ok_n
    cnt = ok.sum()
    margin = jnp.where(ok, pos - neg, 0.0)
    out["ap_an_margin_mean"] = jnp.where(
        cnt > 0, margin.sum() / jnp.maximum(cnt, 1), 0.0)
    # p10 without a masked-quantile primitive: sort with undefined
    # queries pushed to +inf, index the 10th percentile of the DEFINED
    # count (a traced index — jnp.take handles it).
    filled = jnp.where(ok, pos - neg, jnp.float32(jnp.inf))
    ranked = jnp.sort(filled)
    i10 = jnp.clip((cnt - 1) // 10, 0, ranked.shape[0] - 1)
    p10 = jnp.take(ranked, i10)
    out["ap_an_margin_p10"] = jnp.where(
        (cnt > 0) & jnp.isfinite(p10), p10, 0.0)
    cnt_n = ok_n.sum()
    saturated = (ok_n & (neg > jnp.float32(SATURATION_COSINE))).sum()
    out["an_saturation"] = jnp.where(
        cnt_n > 0, saturated / jnp.maximum(cnt_n, 1), 0.0)
    return out
