"""In-graph training-health signals — optional, jit-compatible diagnostics.

The reference monitored training health with an in-training
Recall@{1,5,10} metric and a feature-magnitude probe
(GetRetrivePerformance + asum, reference:
npair_multi_class_loss.cu:173-206, cu:400-401).  This module generalizes
that idea to the signals large-scale training actually triages with:

  * global gradient norm (exploding/vanishing gradients),
  * parameter norm and update/param ratio (the "is the lr sane" signal
    — healthy runs sit around 1e-3),
  * embedding-magnitude mean/max (the reference's feature monitor: after
    L2 normalize these pin to 1.0; drift means the normalize layer or
    its gradient broke),
  * mined-pair hardness summaries (selected pair counts and the mining
    thresholds from ``ops.rank_select``-backed RELATIVE mining — a
    collapsing embedding shows up here before it shows up in loss).

Everything is a fixed-shape fp32 reduction folded into the jitted step's
metric dict, gated by ``HealthConfig``: with ``health=None`` (the
Solver default) no op is added and the hot path compiles identical HLO
to a build without this module.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Which health signals to fold into the step's metric dict.

    Each enabled signal costs a few whole-tree or whole-batch fp32
    reductions inside the jitted step — negligible next to the trunk
    gemms, but not free; the Solver's default (no HealthConfig) adds
    nothing.
    """

    grad_norm: bool = True
    param_norm: bool = True
    update_ratio: bool = True
    embedding_magnitude: bool = True
    pair_hardness: bool = True
    eps: float = 1e-12


def tree_l2_norm(tree: Any) -> jax.Array:
    """Global L2 norm over every leaf of a pytree, accumulated in fp32
    (bf16 params/grads would overflow a squared sum in their own dtype)."""
    sq = jax.tree_util.tree_reduce(
        lambda acc, x: acc + jnp.sum(jnp.square(x.astype(jnp.float32))),
        tree,
        jnp.float32(0.0),
    )
    return jnp.sqrt(sq)


def update_health(
    grads: Any, params: Any, updates: Any, cfg: HealthConfig
) -> Dict[str, jax.Array]:
    """Optimizer-side signals from one step's (grads, params, updates).

    ``update_ratio`` is ||update|| / ||param|| — the per-step relative
    parameter motion; lr schedules are sane when this sits near 1e-3
    and broken when it hits 1e-1 (divergence) or 1e-7 (frozen run).
    """
    out: Dict[str, jax.Array] = {}
    if cfg.grad_norm:
        out["grad_norm"] = tree_l2_norm(grads)
    param_norm = None
    if cfg.param_norm or cfg.update_ratio:
        param_norm = tree_l2_norm(params)
    if cfg.param_norm:
        out["param_norm"] = param_norm
    if cfg.update_ratio:
        out["update_norm"] = tree_l2_norm(updates)
        out["update_ratio"] = out["update_norm"] / (
            param_norm + jnp.float32(cfg.eps)
        )
    return out


def embedding_health(features: jax.Array) -> Dict[str, jax.Array]:
    """Embedding-magnitude mean/max — the reference's feature monitor
    generalized from asum to row L2 norms (one home:
    ``ops.metrics.embedding_magnitude``)."""
    from npairloss_tpu.ops.metrics import embedding_magnitude

    return embedding_magnitude(features)


# Mining thresholds use ±inf/±FLT_MAX sentinels for "no candidates /
# select everything" queries; any |threshold| past this cutoff is a
# sentinel, not a similarity (post-L2Normalize sims live in [-1, 1]).
_THRESHOLD_SENTINEL = 1e30


def _finite_mean(x: jax.Array) -> jax.Array:
    """Mean over non-sentinel entries; 0 when every entry is a sentinel
    (an all-sentinel batch must report a FINITE health row — the health
    metrics feed assert_all_finite under --debug-checks)."""
    x = x.astype(jnp.float32)
    ok = jnp.isfinite(x) & (jnp.abs(x) < _THRESHOLD_SENTINEL)
    cnt = ok.sum()
    total = jnp.where(ok, x, 0.0).sum()
    return jnp.where(cnt > 0, total / jnp.maximum(cnt, 1), 0.0)


def pair_hardness_health(aux: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Mined-pair hardness summary from the dense engine's loss aux.

    ``mined_pos/neg_per_query`` are the reference's identNum/diffNum
    (cu:357/360) averaged over queries; ``ap/an_threshold_mean`` are the
    mining thresholds (exact rank statistics via ``ops.rank_select`` for
    RELATIVE_* methods), averaged over the queries that actually had
    candidates.  Thresholds drifting toward +1 while counts collapse is
    the classic embedding-collapse signature.
    """
    stop = jax.lax.stop_gradient
    return {
        "mined_pos_per_query": stop(aux["ident_num"]).mean(),
        "mined_neg_per_query": stop(aux["diff_num"]).mean(),
        "ap_threshold_mean": _finite_mean(stop(aux["pos_threshold"])),
        "an_threshold_mean": _finite_mean(stop(aux["neg_threshold"])),
    }
