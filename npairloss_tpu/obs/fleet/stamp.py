"""Rank identity for fleet telemetry — who wrote this row/span/file?

The source paper's training step is pod-global (an MPI_Allgather of
embeddings plus an MPI_Allreduce of gradients every step — PAPER.md §0),
yet until this module every observability artifact assumed exactly one
process: no rank on any row, no way to tell which stream came from the
straggling host.  ``FleetStamp`` is the identity every fleet-aware
artifact carries: ``{process_index, process_count, local_device_ids}``
stamped on metric rows, into trace metadata, and into the manifest —
and the rank-aware path scheme (``telemetry.r<k>.jsonl``) that keeps
concurrent ranks from ever interleaving one stream.

Resolution order for the ambient stamp:

  1. ``NPAIRLOSS_FLEET_PROCESS="<rank>/<count>"`` — the explicit
     override for harnesses that run N cooperating OS processes without
     a jax.distributed cluster (boxes whose CPU backend cannot execute
     multiprocess computations still exercise the whole fleet
     observability path this way; the stamp records what the harness
     declares).
  2. jax's own ``process_index()``/``process_count()`` — but only when
     jax is ALREADY imported (the obs rule: telemetry must never force
     a backend init; see ``obs.manifest.device_topology``).
  3. None — no fleet identity; telemetry behaves exactly as before.

Stdlib-only at import time (file-path-loadable from jax-free
processes, same contract as ``obs.sinks``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional


def load_json(path: str) -> Optional[Dict[str, Any]]:
    """Tolerant JSON-object load for fleet artifacts: unreadable,
    unparseable, or non-object content is None, never fatal — the
    aggregation/merge readers report what is missing instead of dying
    on one rank's torn file."""
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (OSError, ValueError):
        return None

# Env override: "<rank>/<count>", e.g. "1/2".
FLEET_PROCESS_ENV = "NPAIRLOSS_FLEET_PROCESS"

# The keys a fleet stamp contributes to every metric row (consumers —
# the aggregator, tests — key on exactly these; see obs.sinks.FLEET_KEYS
# for the jax-free re-export).
STAMP_KEYS = ("process_index", "process_count", "local_device_ids")


@dataclasses.dataclass(frozen=True)
class FleetStamp:
    """One process's identity in the fleet."""

    process_index: int
    process_count: int
    local_device_ids: tuple = ()

    def __post_init__(self):
        if not (0 <= self.process_index < self.process_count):
            raise ValueError(
                f"process_index {self.process_index} outside "
                f"[0, {self.process_count})"
            )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "process_index": self.process_index,
            "process_count": self.process_count,
            "local_device_ids": list(self.local_device_ids),
        }


def fleet_stamp() -> Optional[FleetStamp]:
    """The ambient stamp per the resolution order above (None when no
    fleet identity is declared or derivable)."""
    override = os.environ.get(FLEET_PROCESS_ENV, "").strip()
    if override:
        m = re.fullmatch(r"(\d+)/(\d+)", override)
        if not m:
            raise ValueError(
                f"{FLEET_PROCESS_ENV}={override!r} is not '<rank>/<count>'"
            )
        return FleetStamp(int(m.group(1)), int(m.group(2)))
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return FleetStamp(
            jax.process_index(),
            jax.process_count(),
            tuple(d.id for d in jax.local_devices()),
        )
    except Exception:
        return None


def resolved_process() -> tuple:
    """``(process_index, process_count)`` for COORDINATION (resume
    waits, topology records, engine planning): a live multi-controller
    runtime outranks the declared harness stamp, which outranks the
    single-process default.  Never raises — a malformed override
    degrades to ``(0, 1)``, because a coordination probe must not kill
    the run it coordinates (the stamping path, ``fleet_stamp``, stays
    loud about malformed overrides)."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            if jax.process_count() > 1:
                return jax.process_index(), jax.process_count()
        except Exception:
            pass
    try:
        stamp = fleet_stamp()
    except Exception:
        return 0, 1
    if stamp is not None:
        return stamp.process_index, stamp.process_count
    return 0, 1


def resolve_fleet(fleet) -> Optional[FleetStamp]:
    """Normalize a ``fleet=`` argument: None/False = off, True = the
    ambient stamp (rank 0 of 1 when nothing else is declared — an
    explicitly-requested single-process fleet still stamps), a
    FleetStamp passes through."""
    if fleet is None or fleet is False:
        return None
    if fleet is True:
        return fleet_stamp() or FleetStamp(0, 1)
    if isinstance(fleet, FleetStamp):
        return fleet
    raise TypeError(f"fleet must be None/bool/FleetStamp, got {fleet!r}")


# -- the rank-aware path scheme ----------------------------------------------

# Per-rank file names inside a fleet run directory.  The METRICS stream
# deliberately changes base name (metrics.jsonl -> telemetry.r<k>.jsonl)
# so a single-process consumer reading ``metrics.jsonl`` can never
# half-read one rank of a fleet run and mistake it for the whole run.
TELEMETRY_PATTERN = "telemetry.r{rank}.jsonl"
TRACE_PATTERN = "trace.r{rank}.json"
MANIFEST_PATTERN = "manifest.r{rank}.json"

_RANK_FILE_RE = re.compile(
    r"^(?:telemetry|trace|manifest)\.r(\d+)\.(?:jsonl|json)$")


def rank_metrics_name(rank: int) -> str:
    return TELEMETRY_PATTERN.format(rank=int(rank))


def rank_trace_name(rank: int) -> str:
    return TRACE_PATTERN.format(rank=int(rank))


def rank_manifest_name(rank: int) -> str:
    return MANIFEST_PATTERN.format(rank=int(rank))


def rank_of_file(name: str) -> Optional[int]:
    """The rank a fleet file name belongs to, or None for non-fleet
    names (``metrics.jsonl``, ``trace.json``, ...)."""
    m = _RANK_FILE_RE.match(os.path.basename(name))
    return int(m.group(1)) if m else None


def discover_ranks(run_dir: str) -> List[int]:
    """Sorted ranks that left ANY per-rank file in ``run_dir`` (a rank
    that wrote a trace but lost its metrics stream still counts as
    present — the aggregator reports what is missing, it does not
    silently shrink the fleet)."""
    ranks = set()
    try:
        names = os.listdir(run_dir)
    except OSError:
        return []
    for name in names:
        r = rank_of_file(name)
        if r is not None:
            ranks.add(r)
    return sorted(ranks)
