"""Merge per-rank Chrome traces into one fleet timeline.

Each rank's ``trace.r<k>.json`` is a self-consistent host timeline with
timestamps relative to ITS tracer's creation.  Loaded separately in
Perfetto they answer nothing about the fleet — the question is always
cross-rank ("rank 3's dispatch starts 40 ms after everyone else's").
This module folds them into ONE Perfetto-loadable file:

  * every rank becomes its own numbered process lane (``pid = rank``,
    with ``process_name``/``process_sort_index`` metadata events, so
    the UI shows ``rank 0`` .. ``rank G-1`` top-to-bottom);
  * timestamps are re-based onto a common origin using the
    **clock-offset estimate** from each trace's absolute
    ``wall_time_origin`` (falling back to the rank manifest's
    ``created`` stamp): ``offset_k = origin_k - min(origins)``.  On one
    host this is exact (one clock); across hosts it is as good as the
    hosts' wall-clock sync — the per-rank offsets are recorded in the
    merged trace's metadata so a reader can judge.

Torn/unreadable per-rank traces are skipped with a note in the
metadata, never fatal.  Stdlib-only.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from npairloss_tpu.obs.fleet.stamp import (
    discover_ranks,
    load_json as _load_json,
    rank_manifest_name,
    rank_trace_name,
)

MERGED_TRACE_FILENAME = "fleet_trace.json"


def collect_rank_traces(
    run_dir: str,
) -> Tuple[Dict[int, Dict[str, Any]], Dict[int, Optional[float]], List[str]]:
    """(traces by rank, wall-time origin by rank, notes).  The origin
    prefers the trace's own ``wall_time_origin`` (stamped at tracer
    creation) and falls back to the rank manifest's ``created``."""
    run_dir = os.path.abspath(run_dir)
    traces: Dict[int, Dict[str, Any]] = {}
    origins: Dict[int, Optional[float]] = {}
    notes: List[str] = []
    ranks = discover_ranks(run_dir)
    layouts = (
        [(r, rank_trace_name(r), rank_manifest_name(r)) for r in ranks]
        if ranks else [(0, "trace.json", "manifest.json")]
    )
    for rank, trace_name, manifest_name in layouts:
        path = os.path.join(run_dir, trace_name)
        trace = _load_json(path)
        if trace is None or not isinstance(trace.get("traceEvents"), list):
            if os.path.exists(path):
                notes.append(f"rank {rank}: unreadable trace {trace_name}")
            else:
                notes.append(f"rank {rank}: no trace file")
            continue
        traces[rank] = trace
        origin = (trace.get("otherData", {}) or {}).get("wall_time_origin")
        if not isinstance(origin, (int, float)):
            man = _load_json(os.path.join(run_dir, manifest_name)) or {}
            origin = man.get("created")
            if isinstance(origin, (int, float)):
                notes.append(
                    f"rank {rank}: clock offset estimated from manifest "
                    "created time (trace carried no wall_time_origin)")
            else:
                origin = None
                notes.append(
                    f"rank {rank}: no clock reference — events kept on "
                    "the rank's own relative timeline")
        origins[rank] = origin
    return traces, origins, notes


def merge_chrome_traces(
    traces: Dict[int, Dict[str, Any]],
    origins: Optional[Dict[int, Optional[float]]] = None,
    notes: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Per-rank trace objects -> one merged Chrome-trace object with
    rank-numbered process lanes and clock-offset-aligned timestamps."""
    origins = origins or {}
    known = [o for o in origins.values() if isinstance(o, (int, float))]
    base = min(known) if known else None
    events: List[Dict[str, Any]] = []
    offsets_us: Dict[str, float] = {}
    dropped: Dict[str, int] = {}
    for rank in sorted(traces):
        trace = traces[rank]
        origin = origins.get(rank)
        offset_us = ((origin - base) * 1e6
                     if base is not None
                     and isinstance(origin, (int, float)) else 0.0)
        offsets_us[str(rank)] = round(offset_us, 1)
        # Perfetto lane naming: ts=0 keeps the metadata events valid
        # under validate_chrome_trace (which requires numeric ts).
        events.append({"name": "process_name", "ph": "M", "ts": 0,
                       "pid": rank, "tid": 0,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M", "ts": 0,
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        meta = trace.get("otherData", {}) or {}
        if meta.get("dropped_events"):
            dropped[str(rank)] = int(meta["dropped_events"])
        for ev in trace.get("traceEvents", []):
            # Malformed events (no name/ph, non-numeric ts, an "X"
            # without a numeric dur) are dropped here, per the
            # never-fatal contract: one rank's damaged trace must not
            # invalidate the merged timeline of the whole fleet.
            if not isinstance(ev, dict) \
                    or not isinstance(ev.get("ts"), (int, float)) \
                    or "name" not in ev or "ph" not in ev:
                continue
            if ev["ph"] == "X" and not isinstance(
                    ev.get("dur"), (int, float)):
                continue
            out = dict(ev)
            out["ts"] = ev["ts"] + offset_us
            out["pid"] = rank
            events.append(out)
    merged_meta: Dict[str, Any] = {
        "merged_ranks": sorted(traces),
        "clock_offsets_us": offsets_us,
        "clock_note": (
            "offsets estimated from per-rank wall-clock origins; exact "
            "on one host, host-clock-sync-accurate across hosts"),
    }
    if base is not None:
        merged_meta["wall_time_origin"] = base
    if dropped:
        merged_meta["dropped_events_by_rank"] = dropped
    if notes:
        merged_meta["notes"] = list(notes)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": merged_meta,
    }


def merge_run_traces(
    run_dir: str, out_path: Optional[str] = None
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Merge every per-rank trace under ``run_dir`` and write the
    result (atomic tmp+rename); returns ``(path, merged_trace)`` —
    path None when no rank left a readable trace."""
    traces, origins, notes = collect_rank_traces(run_dir)
    merged = merge_chrome_traces(traces, origins, notes)
    if not traces:
        return None, merged
    if out_path is None:
        out_path = os.path.join(os.path.abspath(run_dir),
                                MERGED_TRACE_FILENAME)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return out_path, merged
