"""Merge per-rank Chrome traces into one fleet timeline.

Each rank's ``trace.r<k>.json`` is a self-consistent host timeline with
timestamps relative to ITS tracer's creation.  Loaded separately in
Perfetto they answer nothing about the fleet — the question is always
cross-rank ("rank 3's dispatch starts 40 ms after everyone else's").
This module folds them into ONE Perfetto-loadable file:

  * every rank becomes its own numbered process lane (``pid = rank``,
    with ``process_name``/``process_sort_index`` metadata events, so
    the UI shows ``rank 0`` .. ``rank G-1`` top-to-bottom);
  * timestamps are re-based onto a common origin using the
    **clock-offset estimate** from each trace's absolute
    ``wall_time_origin`` (falling back to the rank manifest's
    ``created`` stamp): ``offset_k = origin_k - min(origins)``.  On one
    host this is exact (one clock); across hosts it is as good as the
    hosts' wall-clock sync — the per-rank offsets are recorded in the
    merged trace's metadata so a reader can judge.

Torn/unreadable per-rank traces are skipped with a note in the
metadata, never fatal.  Stdlib-only.

:func:`merge_timeline` widens the merge from the trainer fleet to the
WHOLE system (docs/OBSERVABILITY.md §Query tracing): trainer rank
lanes, the serve tier's host spans, per-replica lanes carrying the
qtrace exemplar span trees (one row per retained worst query), and the
run's operational instants — chaos injections from the gameday report,
alert fire/resolve transitions, remediation attempts/outcomes — all on
one wall-clock-aligned Perfetto timeline.  Every source is optional;
whatever exists merges, whatever is missing or torn leaves a note.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from npairloss_tpu.obs.fleet.stamp import (
    discover_ranks,
    load_json as _load_json,
    rank_manifest_name,
    rank_trace_name,
)

MERGED_TRACE_FILENAME = "fleet_trace.json"


def collect_rank_traces(
    run_dir: str,
) -> Tuple[Dict[int, Dict[str, Any]], Dict[int, Optional[float]], List[str]]:
    """(traces by rank, wall-time origin by rank, notes).  The origin
    prefers the trace's own ``wall_time_origin`` (stamped at tracer
    creation) and falls back to the rank manifest's ``created``."""
    run_dir = os.path.abspath(run_dir)
    traces: Dict[int, Dict[str, Any]] = {}
    origins: Dict[int, Optional[float]] = {}
    notes: List[str] = []
    ranks = discover_ranks(run_dir)
    layouts = (
        [(r, rank_trace_name(r), rank_manifest_name(r)) for r in ranks]
        if ranks else [(0, "trace.json", "manifest.json")]
    )
    for rank, trace_name, manifest_name in layouts:
        path = os.path.join(run_dir, trace_name)
        trace = _load_json(path)
        if trace is None or not isinstance(trace.get("traceEvents"), list):
            if os.path.exists(path):
                notes.append(f"rank {rank}: unreadable trace {trace_name}")
            else:
                notes.append(f"rank {rank}: no trace file")
            continue
        traces[rank] = trace
        origin = (trace.get("otherData", {}) or {}).get("wall_time_origin")
        if not isinstance(origin, (int, float)):
            man = _load_json(os.path.join(run_dir, manifest_name)) or {}
            origin = man.get("created")
            if isinstance(origin, (int, float)):
                notes.append(
                    f"rank {rank}: clock offset estimated from manifest "
                    "created time (trace carried no wall_time_origin)")
            else:
                origin = None
                notes.append(
                    f"rank {rank}: no clock reference — events kept on "
                    "the rank's own relative timeline")
        origins[rank] = origin
    return traces, origins, notes


def merge_chrome_traces(
    traces: Dict[int, Dict[str, Any]],
    origins: Optional[Dict[int, Optional[float]]] = None,
    notes: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Per-rank trace objects -> one merged Chrome-trace object with
    rank-numbered process lanes and clock-offset-aligned timestamps."""
    origins = origins or {}
    known = [o for o in origins.values() if isinstance(o, (int, float))]
    base = min(known) if known else None
    events: List[Dict[str, Any]] = []
    offsets_us: Dict[str, float] = {}
    dropped: Dict[str, int] = {}
    for rank in sorted(traces):
        trace = traces[rank]
        origin = origins.get(rank)
        offset_us = ((origin - base) * 1e6
                     if base is not None
                     and isinstance(origin, (int, float)) else 0.0)
        offsets_us[str(rank)] = round(offset_us, 1)
        # Perfetto lane naming: ts=0 keeps the metadata events valid
        # under validate_chrome_trace (which requires numeric ts).
        events.append({"name": "process_name", "ph": "M", "ts": 0,
                       "pid": rank, "tid": 0,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M", "ts": 0,
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        meta = trace.get("otherData", {}) or {}
        if meta.get("dropped_events"):
            dropped[str(rank)] = int(meta["dropped_events"])
        for ev in trace.get("traceEvents", []):
            # Malformed events (no name/ph, non-numeric ts, an "X"
            # without a numeric dur) are dropped here, per the
            # never-fatal contract: one rank's damaged trace must not
            # invalidate the merged timeline of the whole fleet.
            if not isinstance(ev, dict) \
                    or not isinstance(ev.get("ts"), (int, float)) \
                    or "name" not in ev or "ph" not in ev:
                continue
            if ev["ph"] == "X" and not isinstance(
                    ev.get("dur"), (int, float)):
                continue
            out = dict(ev)
            out["ts"] = ev["ts"] + offset_us
            out["pid"] = rank
            events.append(out)
    merged_meta: Dict[str, Any] = {
        "merged_ranks": sorted(traces),
        "clock_offsets_us": offsets_us,
        "clock_note": (
            "offsets estimated from per-rank wall-clock origins; exact "
            "on one host, host-clock-sync-accurate across hosts"),
    }
    if base is not None:
        merged_meta["wall_time_origin"] = base
    if dropped:
        merged_meta["dropped_events_by_rank"] = dropped
    if notes:
        merged_meta["notes"] = list(notes)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": merged_meta,
    }


def merge_run_traces(
    run_dir: str, out_path: Optional[str] = None
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Merge every per-rank trace under ``run_dir`` and write the
    result (atomic tmp+rename); returns ``(path, merged_trace)`` —
    path None when no rank left a readable trace."""
    traces, origins, notes = collect_rank_traces(run_dir)
    merged = merge_chrome_traces(traces, origins, notes)
    if not traces:
        return None, merged
    if out_path is None:
        out_path = os.path.join(os.path.abspath(run_dir),
                                MERGED_TRACE_FILENAME)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return out_path, merged


# -- the composed-system timeline --------------------------------------------

TIMELINE_FILENAME = "timeline.json"

# Lane (pid) allocation for the non-trainer sources.  Trainer ranks
# keep pid = rank (0..G-1, matching fleet_trace.json); everything else
# sits far above any plausible rank count so the groups never collide.
SERVE_HOST_PID = 900
QTRACE_PID_BASE = 1000
SERVE_EVENTS_PID = 1998
OPS_PID = 1999


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Records from a JSONL file; torn lines skipped (never fatal)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _lane_meta(events: List[Dict[str, Any]], pid: int, name: str,
               sort_index: int) -> None:
    events.append({"name": "process_name", "ph": "M", "ts": 0,
                   "pid": pid, "tid": 0, "args": {"name": name}})
    events.append({"name": "process_sort_index", "ph": "M", "ts": 0,
                   "pid": pid, "tid": 0,
                   "args": {"sort_index": sort_index}})


def _first_existing(run_dir: str, names: Tuple[str, ...]
                    ) -> Optional[str]:
    for name in names:
        path = os.path.join(run_dir, name)
        if os.path.exists(path):
            return path
    return None


def merge_timeline(
    run_dir: str, out_path: Optional[str] = None
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Merge every timeline source under ``run_dir`` into one
    Perfetto-loadable ``timeline.json``.

    Sources (each optional, searched in the gameday layout's subdirs
    too): trainer rank traces (``trace.r<k>.json`` in ``run_dir`` or
    ``train_tel/``), the serve host trace (``serve_tel/trace.json``),
    the qtrace exemplar artifact (``qtrace.json`` in ``run_dir`` or
    ``serve_tel/``), alert + remediation logs (``alerts.jsonl`` /
    ``remediation.jsonl`` anywhere in those dirs), and the gameday
    report's chaos schedule (``gameday.json``).  Alignment uses each
    source's absolute wall clock (trace ``wall_time_origin``, alert /
    remediation ``ts``); gameday chaos offsets are anchored at the
    merged base origin — a run-start estimate, noted in the metadata.
    Returns ``(path, merged)``; path is None when NO source produced
    events (nothing worth writing)."""
    run_dir = os.path.abspath(run_dir)
    serve_tel = os.path.join(run_dir, "serve_tel")
    train_tel = os.path.join(run_dir, "train_tel")
    notes: List[str] = []
    events: List[Dict[str, Any]] = []

    # Trainer rank lanes: first layout that yields traces wins (a rank
    # set split across both dirs would double-allocate pids).
    traces: Dict[int, Dict[str, Any]] = {}
    origins: Dict[int, Optional[float]] = {}
    for cand in (run_dir, train_tel):
        if not os.path.isdir(cand):
            continue
        traces, origins, rank_notes = collect_rank_traces(cand)
        if traces:
            notes.extend(rank_notes)
            break

    # Serve host trace (span stream from the serving process).
    serve_origin: Optional[float] = None
    path = os.path.join(serve_tel, "trace.json")
    serve_trace = _load_json(path) if os.path.exists(path) else None
    if serve_trace is not None:
        if not isinstance(serve_trace.get("traceEvents"), list):
            notes.append("serve host trace unreadable")
            serve_trace = None
        else:
            origin = (serve_trace.get("otherData", {}) or {}).get(
                "wall_time_origin")
            serve_origin = (origin
                            if isinstance(origin, (int, float))
                            else None)

    # Qtrace exemplar artifact.
    qtrace_path = _first_existing(
        run_dir, ("qtrace.json", os.path.join("serve_tel",
                                              "qtrace.json")))
    qtrace = _load_json(qtrace_path) if qtrace_path else None
    qtrace_origin: Optional[float] = None
    if qtrace is not None:
        origin = qtrace.get("wall_time_origin")
        if isinstance(origin, (int, float)) and \
                isinstance(qtrace.get("exemplars"), list):
            qtrace_origin = float(origin)
        else:
            notes.append("qtrace artifact unreadable — exemplar lanes "
                         "skipped")
            qtrace = None

    # Operational instants: alert + remediation logs, wall-clock ``ts``.
    alert_recs: List[Dict[str, Any]] = []
    rem_recs: List[Dict[str, Any]] = []
    for cand in (run_dir, serve_tel, train_tel):
        if not os.path.isdir(cand):
            continue
        alert_recs.extend(_read_jsonl(os.path.join(cand,
                                                   "alerts.jsonl")))
        rem_recs.extend(_read_jsonl(os.path.join(cand,
                                                 "remediation.jsonl")))
    op_times = [float(r["ts"]) for r in alert_recs + rem_recs
                if isinstance(r.get("ts"), (int, float))]

    # One common origin: the earliest absolute wall clock any source
    # carries (exact on one host — the fleet-merge contract).
    known = [o for o in origins.values()
             if isinstance(o, (int, float))]
    if serve_origin is not None:
        known.append(serve_origin)
    if qtrace_origin is not None:
        known.append(qtrace_origin)
    known.extend(op_times)
    base = min(known) if known else None

    def _us(wall: float) -> float:
        return (wall - base) * 1e6 if base is not None else 0.0

    # Trainer lanes re-use the fleet merge (pid = rank), re-based onto
    # the composed-system origin via each rank's own offset.
    if traces:
        fleet = merge_chrome_traces(traces, origins)
        fleet_origin = fleet["otherData"].get("wall_time_origin")
        shift = (_us(fleet_origin)
                 if isinstance(fleet_origin, (int, float)) else 0.0)
        for ev in fleet["traceEvents"]:
            if ev.get("ph") != "M":
                ev = dict(ev)
                ev["ts"] = ev["ts"] + shift
            events.append(ev)

    if serve_trace is not None:
        _lane_meta(events, SERVE_HOST_PID, "serve host", SERVE_HOST_PID)
        shift = _us(serve_origin) if serve_origin is not None else 0.0
        if serve_origin is None:
            notes.append("serve host trace has no wall_time_origin — "
                         "kept on its own relative timeline")
        for ev in serve_trace["traceEvents"]:
            if not isinstance(ev, dict) \
                    or not isinstance(ev.get("ts"), (int, float)):
                continue
            out = dict(ev)
            out["ts"] = ev["ts"] + shift
            out["pid"] = SERVE_HOST_PID
            events.append(out)

    # Per-replica exemplar lanes: one pid per replica, one tid (row)
    # per exemplar, so each worst-query span tree reads as its own
    # nested track next to the host spans.
    if qtrace is not None:
        shift = _us(qtrace_origin)
        replicas = sorted({str(ex.get("replica") or "?")
                           for ex in qtrace["exemplars"]})
        rep_pid = {rep: QTRACE_PID_BASE + i
                   for i, rep in enumerate(replicas)}
        for rep in replicas:
            _lane_meta(events, rep_pid[rep],
                       f"serve queries {rep}", rep_pid[rep])
        for i, ex in enumerate(qtrace["exemplars"]):
            if not isinstance(ex.get("events"), list):
                continue
            pid = rep_pid[str(ex.get("replica") or "?")]
            events.append({
                "name": "thread_name", "ph": "M", "ts": 0,
                "pid": pid, "tid": i,
                "args": {"name": f"{ex.get('trace_id', f'ex{i}')} "
                                 f"({ex.get('reason', '?')})"}})
            for ev in ex["events"]:
                if not isinstance(ev, dict) \
                        or not isinstance(ev.get("ts"), (int, float)):
                    continue
                out = dict(ev)
                out["ts"] = ev["ts"] + shift
                out["pid"] = pid
                out["tid"] = i
                events.append(out)
        markers = qtrace.get("markers")
        if isinstance(markers, list) and markers:
            _lane_meta(events, SERVE_EVENTS_PID, "serve events",
                       SERVE_EVENTS_PID)
            for ev in markers:
                if not isinstance(ev, dict) \
                        or not isinstance(ev.get("ts"), (int, float)):
                    continue
                out = dict(ev)
                out["ts"] = ev["ts"] + shift
                out["pid"] = SERVE_EVENTS_PID
                out["tid"] = 0
                events.append(out)

    if op_times:
        _lane_meta(events, OPS_PID, "alerts & remediation", OPS_PID)
        for rec in alert_recs:
            if not isinstance(rec.get("ts"), (int, float)):
                continue
            events.append({
                "name": f"alert:{rec.get('slo', '?')} "
                        f"{rec.get('state', '?')}",
                "ph": "i", "s": "t", "ts": _us(float(rec["ts"])),
                "pid": OPS_PID, "tid": 0,
                "args": {key: rec.get(key) for key in
                         ("slo", "state", "severity", "alert_id")},
            })
        for rec in rem_recs:
            if not isinstance(rec.get("ts"), (int, float)):
                continue
            events.append({
                "name": f"remediation:{rec.get('policy', '?')} "
                        f"{rec.get('state', '?')}",
                "ph": "i", "s": "t", "ts": _us(float(rec["ts"])),
                "pid": OPS_PID, "tid": 1,
                "args": {key: rec.get(key) for key in
                         ("policy", "action", "state", "attempt")},
            })

    # Gameday chaos schedule: at_s offsets anchored at the merged base
    # (the run-start estimate — documented, not asserted).
    gameday = _load_json(os.path.join(run_dir, "gameday.json"))
    if isinstance(gameday, dict) and \
            isinstance(gameday.get("faults"), list):
        if not op_times:
            _lane_meta(events, OPS_PID, "alerts & remediation",
                       OPS_PID)
        notes.append("chaos instants anchored at the merged base "
                     "origin (run-start estimate)")
        for fault in gameday["faults"]:
            if not isinstance(fault, dict) \
                    or not isinstance(fault.get("at_s"),
                                      (int, float)):
                continue
            events.append({
                "name": f"chaos:{fault.get('name', '?')}",
                "ph": "i", "s": "t",
                "ts": float(fault["at_s"]) * 1e6,
                "pid": OPS_PID, "tid": 2,
                "args": {key: fault.get(key) for key in
                         ("name", "target", "kind", "at_s")},
            })

    merged: Dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "timeline": True,
            "sources": {
                "trainer_ranks": sorted(traces),
                "serve_host": serve_trace is not None,
                "qtrace": qtrace is not None,
                "alerts": len(alert_recs),
                "remediation": len(rem_recs),
                "gameday": isinstance(gameday, dict),
            },
            **({"wall_time_origin": base} if base is not None else {}),
            **({"notes": notes} if notes else {}),
        },
    }
    if not any(ev.get("ph") != "M" for ev in events):
        return None, merged
    if out_path is None:
        out_path = os.path.join(run_dir, TIMELINE_FILENAME)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out_path)
    return out_path, merged
