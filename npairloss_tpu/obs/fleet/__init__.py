"""Fleet observatory (docs/OBSERVABILITY.md §Fleet observatory).

The multi-rank observability layer — the instrumentation side of the
pod-scale roadmap item, landed ahead of the mesh refactor it will
debug.  Four coordinated parts:

  * ``fleet.stamp`` — rank identity (``FleetStamp``) stamped on every
    metric row / trace / manifest, plus the rank-aware path scheme
    (``telemetry.r<k>.jsonl``) that keeps concurrent ranks from
    interleaving a stream;
  * ``fleet.comms`` — collective attribution: the ``comm/<kind>``
    scope claims joined with the HLO-priced collective bytes
    (``obs.perf.hlo``) into per-kind effective-bandwidth rows checked
    against the roofline interconnect specs (ICI vs DCN);
  * ``fleet.aggregate`` — offline straggler/skew analysis over all
    ranks' streams, emitting the versioned
    ``npairloss-fleet-report-v1`` artifact
    (``validate_fleet_report`` IS the contract);
  * ``fleet.merge_traces`` — per-rank Chrome traces folded into one
    Perfetto file with rank-numbered process lanes and a clock-offset
    estimate.

All modules are stdlib-only at import time (the obs rule): ``prof
--fleet`` and jax-free harness processes use them without touching a
backend.  Entry point: ``python -m npairloss_tpu prof --fleet RUNDIR``.
"""

from npairloss_tpu.obs.fleet.aggregate import (
    FLEET_REPORT_SCHEMA,
    build_fleet_report,
    load_rank_streams,
    render_fleet_table,
    validate_fleet_report,
    write_fleet_report,
)
from npairloss_tpu.obs.fleet.comms import (
    KIND_OF_OPCODE,
    comm_rows_from_hlo,
    effective_bandwidth,
    grad_sync_claim_bytes,
)
from npairloss_tpu.obs.fleet.merge_traces import (
    MERGED_TRACE_FILENAME,
    merge_chrome_traces,
    merge_run_traces,
)
from npairloss_tpu.obs.fleet.stamp import (
    FLEET_PROCESS_ENV,
    STAMP_KEYS,
    FleetStamp,
    discover_ranks,
    fleet_stamp,
    rank_metrics_name,
    rank_trace_name,
    resolve_fleet,
)

__all__ = [
    "FLEET_REPORT_SCHEMA",
    "build_fleet_report",
    "load_rank_streams",
    "render_fleet_table",
    "validate_fleet_report",
    "write_fleet_report",
    "KIND_OF_OPCODE",
    "comm_rows_from_hlo",
    "effective_bandwidth",
    "grad_sync_claim_bytes",
    "MERGED_TRACE_FILENAME",
    "merge_chrome_traces",
    "merge_run_traces",
    "FLEET_PROCESS_ENV",
    "STAMP_KEYS",
    "FleetStamp",
    "discover_ranks",
    "fleet_stamp",
    "rank_metrics_name",
    "rank_trace_name",
    "resolve_fleet",
]
