"""Offline fleet aggregation: per-rank streams -> one straggler/skew report.

Reads every rank's telemetry out of one run directory (the rank-aware
path scheme of ``obs.fleet.stamp``; a plain single-process layout loads
as rank 0 of 1) and computes what no single stream can show:

  * **per-step rank skew** — the spread of dispatch-start times across
    ranks for the same step (a straggler dispatches late; in a
    collective-coupled step everyone else then waits for it at the
    gather), plus the end-time spread from the metric rows;
  * **slowest-rank identity with persistence** — the same rank arriving
    last step after step is a sick host, not noise; the report names it
    and counts the longest consecutive run;
  * **barrier-wait share** — per rank, the mean fraction of a step it
    spends ahead of the straggler (= waiting at the collective);
  * **dropped-span flagging** — a rank whose tracer hit its event cap
    has a PARTIAL timeline; it is flagged (and its span-derived numbers
    marked) instead of being silently averaged into the fleet;
  * **comms join** — when the training run left its HLO collective
    pricing (``fleet_comms.json``, written by the Solver under fleet
    telemetry), the per-kind bytes are joined with the measured step
    cadence into effective-bandwidth rows checked against the roofline
    interconnect spec (``obs.fleet.comms``).

The output is the versioned ``npairloss-fleet-report-v1`` artifact;
:func:`validate_fleet_report` IS the contract (the ``obs.perf.report``
pattern) — tests, the ci.sh fleet smoke, and ``scripts/bench_check.py
--fleet-report`` call exactly it.

Torn tail lines (a rank killed mid-write) are counted per rank, never
fatal: partial telemetry beats no telemetry, but the count is in the
report so a truncated stream is visible evidence.

Stdlib-only — ``prof --fleet`` must run without touching a backend.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from npairloss_tpu.obs.fleet.stamp import (
    discover_ranks,
    load_json as _load_json,
    rank_manifest_name,
    rank_metrics_name,
    rank_trace_name,
)

FLEET_REPORT_SCHEMA = "npairloss-fleet-report-v1"

# The file the Solver leaves behind (rank 0, fleet telemetry on) with
# the compiled step's HLO-priced collectives + its analytic claims.
COMMS_FILENAME = "fleet_comms.json"

# Keys every per-rank row of the report carries (pinned by tests; the
# validator enforces them).
RANK_KEYS = (
    "rank", "rows", "torn_lines", "steps", "first_step", "last_step",
    "spans_dropped", "flagged", "ms_per_step_p50", "barrier_wait_share",
)

SKEW_KEYS = (
    "steps_analyzed", "dispatch_spread_ms_p50", "dispatch_spread_ms_p99",
    "end_spread_ms_p50", "end_spread_ms_p99", "slowest",
)

_STEP_SPAN_NAMES = ("step/dispatch", "step/compile")


def read_jsonl(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """(rows, torn_lines): every parseable JSON object line; lines that
    fail to parse (the torn tail of a killed writer) are counted, not
    fatal."""
    rows: List[Dict[str, Any]] = []
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if isinstance(obj, dict):
                rows.append(obj)
            else:
                torn += 1
    return rows, torn


def load_rank_streams(run_dir: str) -> Dict[int, Dict[str, Any]]:
    """rank -> {"rows", "torn_lines", "trace", "manifest"} for every
    rank that left any per-rank file; a plain single-process layout
    (``metrics.jsonl``/``trace.json``/``manifest.json``) loads as rank
    0 when no rank files exist."""
    run_dir = os.path.abspath(run_dir)
    out: Dict[int, Dict[str, Any]] = {}
    ranks = discover_ranks(run_dir)
    if ranks:
        layouts = [
            (r, rank_metrics_name(r), rank_trace_name(r),
             rank_manifest_name(r))
            for r in ranks
        ]
    else:
        layouts = [(0, "metrics.jsonl", "trace.json", "manifest.json")]
    for rank, metrics_name, trace_name, manifest_name in layouts:
        entry: Dict[str, Any] = {
            "rows": [], "torn_lines": 0, "trace": None, "manifest": None,
        }
        mpath = os.path.join(run_dir, metrics_name)
        if os.path.exists(mpath):
            entry["rows"], entry["torn_lines"] = read_jsonl(mpath)
        entry["trace"] = _load_json(os.path.join(run_dir, trace_name))
        entry["manifest"] = _load_json(os.path.join(run_dir, manifest_name))
        if entry["rows"] or entry["trace"] is not None \
                or entry["manifest"] is not None:
            out[rank] = entry
    return out


def expected_process_count(streams: Dict[int, Dict[str, Any]]) -> int:
    """The fleet size the streams themselves declare: the max
    process_count any manifest or row carries, floored by the ranks
    actually present (a stream claiming rank 5 proves count >= 6)."""
    count = 0
    for rank, entry in streams.items():
        man = entry.get("manifest") or {}
        fleet = man.get("fleet") or {}
        if isinstance(fleet.get("process_count"), int):
            count = max(count, fleet["process_count"])
        for row in entry.get("rows", [])[:1]:
            if isinstance(row.get("process_count"), int):
                count = max(count, row["process_count"])
        count = max(count, rank + 1)
    return max(count, len(streams))


# -- per-rank timelines -------------------------------------------------------


def _percentile(vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile, None on empty — wraps the ONE
    implementation (obs.perf.decompose; lazy import so bench_check's
    jax-free file-path loader never touches the package)."""
    if not vals:
        return None
    from npairloss_tpu.obs.perf.decompose import _percentile as nearest

    return nearest(sorted(vals), q)


def _rank_timeline(entry: Dict[str, Any]) -> Dict[str, Any]:
    """One rank's per-step event times, in ABSOLUTE wall seconds.

    ``end_wall[step]`` comes from the train metric rows' ``wall_time``
    (the sync loop stamps it at step materialization; the pipelined
    loop at window emission — which is why dispatch spans are the
    primary skew source).  ``dispatch_wall[step]`` comes from the
    ``step/dispatch``/``step/compile`` spans: ``wall_time_origin +
    ts/1e6``, with the span's own ``step`` arg when present (fleet runs
    stamp it) and row-order assignment as the fallback."""
    rows = entry.get("rows", [])
    train_rows = [r for r in rows if r.get("phase") == "train"
                  and isinstance(r.get("step"), int)]
    end_wall = {r["step"]: float(r["wall_time"]) for r in train_rows
                if isinstance(r.get("wall_time"), (int, float))}
    steps_in_order = [r["step"] for r in train_rows]

    dispatch_wall: Dict[int, float] = {}
    spans_dropped = 0
    trace = entry.get("trace")
    if trace is not None:
        meta = trace.get("otherData", {}) or {}
        origin = meta.get("wall_time_origin")
        spans_dropped = int(meta.get("dropped_events", 0) or 0)
        if isinstance(origin, (int, float)):
            spans = sorted(
                (e for e in trace.get("traceEvents", [])
                 if e.get("ph") == "X"
                 and str(e.get("name", "")) in _STEP_SPAN_NAMES
                 and isinstance(e.get("ts"), (int, float))),
                key=lambda e: e["ts"],
            )
            unnumbered = []
            for ev in spans:
                args = ev.get("args") or {}
                if isinstance(args.get("step"), int):
                    dispatch_wall[args["step"]] = origin + ev["ts"] / 1e6
                else:
                    unnumbered.append(ev)
            if unnumbered and not dispatch_wall:
                # Ordinal fallback: the i-th step span belongs to the
                # i-th train row's step.
                for ev, step in zip(unnumbered, steps_in_order):
                    dispatch_wall[step] = origin + ev["ts"] / 1e6
    for r in rows:
        if isinstance(r.get("spans_dropped"), (int, float)):
            spans_dropped = max(spans_dropped, int(r["spans_dropped"]))
    return {
        "steps": sorted(end_wall),
        "end_wall": end_wall,
        "dispatch_wall": dispatch_wall,
        "spans_dropped": spans_dropped,
        "rows": len(rows),
    }


def _spread_series(
    timelines: Dict[int, Dict[str, Any]], key: str
) -> Tuple[List[int], Dict[int, float], Dict[int, int]]:
    """Steps every rank has a ``key`` time for -> (steps, spread_ms per
    step, slowest-rank per step)."""
    per_rank = {r: t[key] for r, t in timelines.items()}
    if not per_rank:
        return [], {}, {}
    common = set.intersection(*(set(m) for m in per_rank.values())) \
        if per_rank else set()
    steps = sorted(common)
    spread: Dict[int, float] = {}
    slowest: Dict[int, int] = {}
    for s in steps:
        times = {r: per_rank[r][s] for r in per_rank}
        lo, hi = min(times.values()), max(times.values())
        spread[s] = (hi - lo) * 1e3
        slowest[s] = max(times, key=times.get)
    return steps, spread, slowest


def _persistence(slowest: Dict[int, int]) -> Dict[str, Any]:
    """Who is slowest, how often, and for how long in a row."""
    if not slowest:
        return {"rank": None, "share": None, "persistence": 0}
    order = [slowest[s] for s in sorted(slowest)]
    counts: Dict[int, int] = {}
    for r in order:
        counts[r] = counts.get(r, 0) + 1
    top = max(counts, key=counts.get)
    best_run = run = 0
    run_rank = None
    for r in order:
        run = run + 1 if r == run_rank else 1
        run_rank = r
        if r == top:
            best_run = max(best_run, run)
    return {
        "rank": top,
        "share": round(counts[top] / len(order), 4),
        "persistence": best_run,
    }


# -- the report ---------------------------------------------------------------


def build_fleet_report(run_dir: str) -> Dict[str, Any]:
    """Aggregate one run directory into the versioned fleet report."""
    run_dir = os.path.abspath(run_dir)
    streams = load_rank_streams(run_dir)
    report: Dict[str, Any] = {
        "schema": FLEET_REPORT_SCHEMA,
        "run_dir": run_dir,
        "process_count": expected_process_count(streams),
        "ranks_present": sorted(streams),
        "ranks": [],
        "skew": {},
        "comms": {"available": False},
        "notes": [],
    }
    if not streams:
        report["notes"].append("no telemetry streams found")
        return report

    timelines = {r: _rank_timeline(e) for r, e in streams.items()}
    d_steps, d_spread, d_slowest = _spread_series(timelines,
                                                 "dispatch_wall")
    e_steps, e_spread, e_slowest = _spread_series(timelines, "end_wall")
    # Dispatch spans are the primary straggler evidence (the pipelined
    # loop's row wall_times stamp window emission, not the step); fall
    # back to row end times when no rank left numbered spans.
    steps, slowest = (d_steps, d_slowest) if d_steps else (e_steps,
                                                           e_slowest)
    spread_src = d_spread if d_steps else e_spread

    # Per-rank step cadence + barrier-wait share.
    wall_key = "dispatch_wall" if d_steps else "end_wall"
    step_ms: Dict[int, List[float]] = {r: [] for r in streams}
    wait_share: Dict[int, List[float]] = {r: [] for r in streams}
    for i in range(1, len(steps)):
        s0, s1 = steps[i - 1], steps[i]
        durs = {r: (timelines[r][wall_key][s1]
                    - timelines[r][wall_key][s0]) * 1e3
                for r in streams}
        slow_t = max(timelines[r][wall_key][s1] for r in streams)
        for r in streams:
            step_ms[r].append(durs[r])
            if durs[r] > 0:
                wait = (slow_t - timelines[r][wall_key][s1]) * 1e3
                # A SHARE of the step by definition: uncoupled streams
                # (no collectives actually linking the ranks) can show
                # a boundary gap larger than one step; clamp so the
                # column stays readable as "fraction of the step spent
                # waiting".
                wait_share[r].append(min(max(wait, 0.0) / durs[r], 1.0))

    for rank in sorted(streams):
        t = timelines[rank]
        dropped = t["spans_dropped"]
        flags: List[str] = []
        if dropped:
            flags.append(
                f"{dropped} spans dropped at the tracer cap — span-"
                "derived numbers for this rank are partial")
        if streams[rank]["torn_lines"]:
            flags.append(
                f"{streams[rank]['torn_lines']} torn metric line(s)")
        p50 = _percentile(step_ms[rank], 50)
        report["ranks"].append({
            "rank": rank,
            "rows": t["rows"],
            "torn_lines": streams[rank]["torn_lines"],
            "steps": len(t["steps"]),
            "first_step": t["steps"][0] if t["steps"] else None,
            "last_step": t["steps"][-1] if t["steps"] else None,
            "spans_dropped": dropped,
            "flagged": bool(flags),
            "flags": flags,
            "ms_per_step_p50": round(p50, 3) if p50 is not None else None,
            "barrier_wait_share": (
                round(sum(wait_share[rank]) / len(wait_share[rank]), 4)
                if wait_share[rank] else None
            ),
        })

    spread_vals = [spread_src[s] for s in steps]
    d_vals = [d_spread[s] for s in d_steps]
    e_vals = [e_spread[s] for s in e_steps]
    report["skew"] = {
        "steps_analyzed": len(steps),
        "source": "dispatch_spans" if d_steps else "row_wall_times",
        "dispatch_spread_ms_p50": _round(_percentile(d_vals, 50)),
        "dispatch_spread_ms_p99": _round(_percentile(d_vals, 99)),
        "end_spread_ms_p50": _round(_percentile(e_vals, 50)),
        "end_spread_ms_p99": _round(_percentile(e_vals, 99)),
        "slowest": _persistence(slowest),
    }

    # Missing ranks / step-count disagreement are REPORTED here and
    # ENFORCED by the validator / bench_check respectively.
    missing = [r for r in range(report["process_count"])
               if r not in streams]
    if missing:
        report["notes"].append(f"missing rank(s): {missing}")
    counts = {r["rank"]: r["steps"] for r in report["ranks"]}
    if len(set(counts.values())) > 1:
        report["notes"].append(
            f"per-rank step counts disagree: {counts} — ranks did not "
            "train in lockstep (or a stream was truncated)")
    dropped_ranks = [r["rank"] for r in report["ranks"]
                     if r["spans_dropped"]]
    if dropped_ranks:
        report["notes"].append(
            f"rank(s) {dropped_ranks} dropped spans at the tracer cap; "
            "their skew contribution is partial")

    report["comms"] = _comms_block(run_dir, streams, step_ms)
    return report


def _round(v: Optional[float], nd: int = 3) -> Optional[float]:
    return round(v, nd) if isinstance(v, (int, float)) else None


def _comms_block(
    run_dir: str,
    streams: Dict[int, Dict[str, Any]],
    step_ms: Dict[int, List[float]],
) -> Dict[str, Any]:
    """Join the Solver's compile-time HLO collective pricing with the
    measured step cadence (obs.fleet.comms)."""
    from npairloss_tpu.obs.fleet import comms as comms_mod

    payload = _load_json(os.path.join(run_dir, COMMS_FILENAME))
    if payload is None:
        return {
            "available": False,
            "reason": f"{COMMS_FILENAME} not found (training ran "
            "without fleet telemetry, or on a meshless solver)",
        }
    rows = comms_mod.comm_rows_from_hlo(
        payload.get("per_opcode", {}),
        extra_claims=payload.get("extra_claims", {}),
    )
    all_ms = [m for r in step_ms.values() for m in r]
    joined = comms_mod.effective_bandwidth(
        rows,
        _percentile(all_ms, 50),
        payload.get("device_kind", ""),
        payload.get("link", "ici"),
    )
    joined["available"] = True
    joined["unattributed_bytes"] = rows["unattributed_bytes"]
    return joined


# -- contract -----------------------------------------------------------------


def validate_fleet_report(obj: Any) -> Optional[str]:
    """Schema check; returns an error string or None.  This IS the
    fleet-report contract (the ``obs.perf.validate_report`` pattern):
    tests, the ci.sh fleet smoke, and ``bench_check.py --fleet-report``
    call exactly this."""
    if not isinstance(obj, dict):
        return "report must be a JSON object"
    if obj.get("schema") != FLEET_REPORT_SCHEMA:
        return (f"schema must be {FLEET_REPORT_SCHEMA!r}, "
                f"got {obj.get('schema')!r}")
    pc = obj.get("process_count")
    if not isinstance(pc, int) or pc < 1:
        return f"process_count must be a positive int, got {pc!r}"
    ranks = obj.get("ranks")
    if not isinstance(ranks, list) or not ranks:
        return "missing ranks list"
    seen = []
    for i, row in enumerate(ranks):
        if not isinstance(row, dict):
            return f"rank row {i} is not an object"
        for key in RANK_KEYS:
            if key not in row:
                return f"rank row {i} missing {key!r}"
        if row["spans_dropped"] and not row["flagged"]:
            return (f"rank {row['rank']} dropped {row['spans_dropped']} "
                    "spans but is not flagged — a capped rank must be "
                    "flagged, not averaged")
        seen.append(row["rank"])
    missing = [r for r in range(pc) if r not in seen]
    if missing:
        return (f"rank(s) {missing} missing: report covers {sorted(seen)} "
                f"of process_count {pc}")
    skew = obj.get("skew")
    if not isinstance(skew, dict):
        return "missing skew block"
    for key in SKEW_KEYS:
        if key not in skew:
            return f"skew block missing {key!r}"
    slowest = skew["slowest"]
    if not isinstance(slowest, dict) or "rank" not in slowest \
            or "persistence" not in slowest:
        return "skew.slowest must carry rank + persistence"
    comms = obj.get("comms")
    if not isinstance(comms, dict) or "available" not in comms:
        return "missing comms block"
    if comms.get("available"):
        if not isinstance(comms.get("kinds"), list):
            return "comms block missing kinds list"
        for i, k in enumerate(comms["kinds"]):
            for key in ("kind", "bytes_per_step", "claimed",
                        "effective_bytes_per_s", "link_utilization"):
                if key not in k:
                    return f"comms kind {i} missing {key!r}"
        ub = comms.get("unattributed_bytes")
        if not isinstance(ub, (int, float)) or ub < 0:
            return f"comms.unattributed_bytes invalid: {ub!r}"
    return None


# -- renderer -----------------------------------------------------------------


def render_fleet_table(report: Dict[str, Any]) -> str:
    """Human-readable counterpart of the JSON."""
    lines = [
        f"fleet report: {report.get('process_count')} process(es), "
        f"ranks present {report.get('ranks_present')}",
        "",
        f"{'rank':>4s} {'steps':>6s} {'ms/step':>9s} {'wait%':>7s} "
        f"{'dropped':>8s} {'torn':>5s}  flags",
    ]
    for r in report.get("ranks", []):
        ms = (f"{r['ms_per_step_p50']:.2f}"
              if r["ms_per_step_p50"] is not None else "-")
        ws = (f"{100 * r['barrier_wait_share']:.1f}"
              if r["barrier_wait_share"] is not None else "-")
        lines.append(
            f"{r['rank']:4d} {r['steps']:6d} {ms:>9s} {ws:>7s} "
            f"{r['spans_dropped']:8d} {r['torn_lines']:5d}  "
            + ("; ".join(r.get("flags", [])) or "-"))
    skew = report.get("skew", {})
    if skew:
        sl = skew.get("slowest", {})
        lines += [
            "",
            f"skew over {skew.get('steps_analyzed')} step(s) "
            f"[{skew.get('source')}]: dispatch spread p50 "
            f"{skew.get('dispatch_spread_ms_p50')} ms / p99 "
            f"{skew.get('dispatch_spread_ms_p99')} ms; end spread p50 "
            f"{skew.get('end_spread_ms_p50')} ms",
            f"slowest rank: {sl.get('rank')} "
            f"(share {sl.get('share')}, persistence "
            f"{sl.get('persistence')} consecutive step(s))",
        ]
    comms = report.get("comms", {})
    if comms.get("available"):
        lines += ["", f"comms (link {comms.get('link')}, peak "
                  f"{(comms.get('peak_bytes_per_s') or 0) / 1e9:.0f} GB/s"
                  + ("" if comms.get("peak_known") else ", fallback spec")
                  + "):"]
        for k in comms.get("kinds", []):
            eff = k.get("effective_bytes_per_s")
            eff_s = f"{eff / 1e9:.3f} GB/s" if eff else "-"
            util = k.get("link_utilization")
            util_s = f"{100 * util:.2f}%" if util is not None else "-"
            cov = k.get("scope_coverage")
            cov_s = f"{100 * cov:.0f}%" if cov is not None else "-"
            lines.append(
                f"  {k['kind']:14s} {k['bytes_per_step']:12.3e} B/step  "
                f"eff {eff_s:>12s}  util {util_s:>8s}  "
                f"scope {cov_s:>5s}  "
                + ("claimed" if k.get("claimed") else "UNCLAIMED"))
        lines.append(
            f"  unattributed collective bytes: "
            f"{comms.get('unattributed_bytes', 0):.0f}")
    elif comms:
        lines += ["", f"comms: unavailable ({comms.get('reason')})"]
    for note in report.get("notes", []):
        lines.append(f"note: {note}")
    return "\n".join(lines) + "\n"


def write_fleet_report(report: Dict[str, Any], out_dir: str,
                       name: str = "fleet_report") -> Dict[str, str]:
    """Write ``<out_dir>/<name>.json`` + ``.txt`` (atomic tmp+rename);
    returns the paths — the obs.perf.report writer pattern."""
    from npairloss_tpu.obs.perf.report import write_json_txt

    return write_json_txt(report, out_dir, name, render_fleet_table)
