"""Collective/comms attribution — what does the interconnect carry per step?

The source paper's step is two collectives (MPI_Allgather of embeddings,
MPI_Allreduce of gradients — PAPER.md §0), and at pod scale the TPU-v4
paper's lesson is that those wires, not per-chip FLOPs, set throughput.
This module joins three honest sources into per-step comms rows:

  * **scope claims** — the loss-engine exchange paths are wrapped in
    ``jax.named_scope("comm/<kind>")`` (dense all_gather and the grad
    allreduce in ``ops/npair_loss.py``, the ring's ppermute hops in
    ``parallel/ring.py``), so the compiled HLO's collective
    instructions carry the marker in their ``op_name`` metadata;
  * **HLO pricing** — ``obs.perf.hlo.collective_bytes_by_opcode``
    prices EVERY collective in the compiled step (output-shape bytes ×
    trip count), including the implicit all-reduces XLA's SPMD
    partitioner inserts for replicated-parameter gradients, which no
    source-level scope can mark;
  * **measured step time** — the per-rank step cadence from the fleet
    telemetry streams, giving each kind an *effective bandwidth
    demand* ``bytes_per_step / step_time``: the rate the link must
    sustain if the collective were perfectly overlapped.  The host
    cannot time an in-graph collective (that would require the device
    trace this observatory exists to avoid), so no per-collective
    latency is fabricated — the demand figure is checked against the
    roofline interconnect peak (ICI within a host, DCN across hosts)
    and a demand above peak means the step is interconnect-bound.

Reconciliation contract: every HLO-priced collective byte must belong
to a *claimed kind* — a kind some ``comm/<kind>`` scope (or the
solver's grad-sync claim for SPMD-inserted all-reduces) vouches for.
``unattributed_bytes`` sums the kinds nobody claims; the ci gate holds
it at zero, so adding a new exchange path without instrumenting it
fails CI instead of silently vanishing from the fleet report.  Within
a claimed kind, ``scope_coverage`` reports the fraction of its bytes
that sit inside an explicit ``comm/`` scope — honesty about how much
is marker-attributed vs. merely claimed.

Stdlib-only (dicts in, dicts out) — loadable from jax-free processes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# HLO collective opcode -> the comm kind the fleet report speaks in.
KIND_OF_OPCODE = {
    "all-gather": "all_gather",
    "all-gather-start": "all_gather",
    "collective-permute": "ppermute",
    "collective-permute-start": "ppermute",
    "all-reduce": "allreduce",
    "all-reduce-start": "allreduce",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-broadcast": "broadcast",
}

# The scope marker the exchange paths carry (``jax.named_scope``).
COMM_SCOPE_MARKER = "comm/"


def _scoped_bytes(regions: Dict[str, float]) -> float:
    """Bytes of one opcode's instructions whose full scope path carries
    the ``comm/`` marker."""
    return float(sum(
        b for region, b in regions.items() if COMM_SCOPE_MARKER in region
    ))


def comm_rows_from_hlo(
    per_opcode: Dict[str, Dict[str, Any]],
    extra_claims: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Fold ``collective_bytes_by_opcode`` output into per-KIND rows
    plus the reconciliation verdict.

    ``extra_claims``: kind -> analytic bytes claimed by instrumentation
    that cannot mark scopes (the solver's grad-sync claim: XLA inserts
    the replicated-parameter all-reduce itself, so the claim is the
    param-tree byte size, priced host-side).  A kind counts as claimed
    when it has scope-marked bytes OR an extra claim.
    """
    extra_claims = dict(extra_claims or {})
    kinds: Dict[str, Dict[str, Any]] = {}
    for opcode, row in per_opcode.items():
        kind = KIND_OF_OPCODE.get(opcode, opcode)
        k = kinds.setdefault(kind, {
            "kind": kind, "bytes_per_step": 0.0, "count_per_step": 0.0,
            "scope_bytes": 0.0, "opcodes": [],
        })
        k["bytes_per_step"] += float(row.get("bytes", 0.0))
        k["count_per_step"] += float(row.get("count", 0.0))
        k["scope_bytes"] += _scoped_bytes(row.get("regions", {}))
        k["opcodes"].append(opcode)
    unattributed = 0.0
    for kind, k in sorted(kinds.items()):
        claimed_extra = float(extra_claims.get(kind, 0.0))
        k["claimed"] = bool(k["scope_bytes"] > 0.0 or claimed_extra > 0.0)
        k["claim_bytes"] = k["scope_bytes"] + claimed_extra
        k["scope_coverage"] = (
            round(k["scope_bytes"] / k["bytes_per_step"], 4)
            if k["bytes_per_step"] > 0 else None
        )
        k["opcodes"] = sorted(set(k["opcodes"]))
        if not k["claimed"]:
            unattributed += k["bytes_per_step"]
    return {
        "kinds": [kinds[k] for k in sorted(kinds)],
        "unattributed_bytes": unattributed,
        "total_bytes_per_step": float(
            sum(k["bytes_per_step"] for k in kinds.values())),
    }


def grad_sync_claim_bytes(param_bytes: float,
                          process_count: int) -> Dict[str, float]:
    """The solver's analytic claim for the SPMD-inserted gradient
    all-reduce: with replicated parameters, XLA all-reduces one
    gradient tree per step — output bytes = the param tree's own size
    (the output-shape convention the HLO pricing uses).  Claimed only
    when there is more than one shard to reduce over."""
    if process_count <= 0:
        raise ValueError(f"process_count must be positive: {process_count}")
    return {"allreduce": float(param_bytes)} if param_bytes > 0 else {}


def effective_bandwidth(
    comm: Dict[str, Any],
    ms_per_step: Optional[float],
    device_kind: str,
    link: str,
) -> Dict[str, Any]:
    """Attach the per-kind effective-bandwidth-demand columns and the
    roofline interconnect check to a ``comm_rows_from_hlo`` result
    (mutates a copy; the input is not changed).

    ``link``: ``"ici"`` (single-host mesh) or ``"dcn"`` (collectives
    crossing host processes) — resolved against
    ``obs.perf.roofline.interconnect_peak``.
    """
    from npairloss_tpu.obs.perf.roofline import chip_peaks, interconnect_peak

    spec = chip_peaks(device_kind)
    peak = interconnect_peak(spec, link)
    out = {
        **{k: v for k, v in comm.items() if k != "kinds"},
        "link": link,
        "peak_bytes_per_s": peak,
        "peak_known": spec.known,
        "ms_per_step": ms_per_step,
        "kinds": [],
    }
    for k in comm["kinds"]:
        row = dict(k)
        if ms_per_step and ms_per_step > 0:
            bps = row["bytes_per_step"] / (ms_per_step * 1e-3)
            row["effective_bytes_per_s"] = bps
            row["link_utilization"] = round(bps / peak, 4) if peak else None
        else:
            row["effective_bytes_per_s"] = None
            row["link_utilization"] = None
        out["kinds"].append(row)
    return out
