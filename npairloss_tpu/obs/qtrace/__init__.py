"""Query-level tracing: per-stage tail-latency attribution from socket
to answer (docs/OBSERVABILITY.md §Query tracing).

``QueryTracer`` assigns a trace id at ingestion and records one span
per serving-tier stage; exemplar span trees for SLO-violating and
slowest-tail queries land in the versioned ``npairloss-qtrace-v1``
artifact (contract: :mod:`npairloss_tpu.obs.qtrace.report`, jax-free,
gated by ``bench_check --qtrace``), and the rolling p99 budget
decomposition surfaces in ``/healthz``, window rows, and the drain
summary.  The fleet merger folds the exemplars and markers into one
Perfetto timeline next to trainer rank lanes and gameday instants.
"""

from npairloss_tpu.obs.qtrace.core import (
    QTraceConfig,
    QueryTrace,
    QueryTracer,
)
from npairloss_tpu.obs.qtrace.report import (
    MARKER_NAMES,
    QTRACE_SCHEMA,
    STAGES,
    load_qtrace_report,
    qtrace_p99_consistency,
    validate_qtrace_report,
)

__all__ = [
    "MARKER_NAMES",
    "QTRACE_SCHEMA",
    "QTraceConfig",
    "QueryTrace",
    "QueryTracer",
    "STAGES",
    "load_qtrace_report",
    "qtrace_p99_consistency",
    "validate_qtrace_report",
]
