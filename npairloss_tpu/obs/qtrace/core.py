"""QueryTracer — per-query, per-stage tail-latency attribution.

One trace id is assigned per query at INGESTION (the stdin FIFO loop
and the HTTP front end alike) and rides the record through admission,
the replica router, the micro-batcher, and the engine; each pipeline
stage records a span (``admit_wait``, ``queue_wait``,
``batch_assemble``, ``dispatch``, ``score``, ``topk_merge``) in the
stdlib ``SpanTracer`` event shape, so exemplar trees drop straight
into Perfetto next to the host spans and fleet lanes
(docs/OBSERVABILITY.md §Query tracing).

Two consumers sit on top of the raw spans:

* **always-on aggregation** — every answered query lands its stage
  durations in a rolling ring (and, when a live registry is attached,
  in per-stage ``qtrace_<stage>_ms`` histograms on ``/metrics``); the
  ring yields the p99 budget decomposition (which stage dominates the
  worst-window queries) for ``/healthz``, window rows, and the drain
  summary;
* **exemplar sampling** — the FULL span tree is retained only for
  SLO-violating queries and the slowest tail (rolling
  ``tail_quantile``), in a bounded store that evicts the fastest
  exemplar first — never a per-query flight recorder at full qps.

The drain writes the ``npairloss-qtrace-v1`` artifact; its contract
lives in :mod:`npairloss_tpu.obs.qtrace.report` (jax-free, gated by
``bench_check --qtrace``).

Population contract (shared with the server's latency rings,
tests/test_qtrace.py): only ANSWERED queries aggregate — rejected,
shed, and errored queries are counted (``totals.dropped`` /
``totals.errors``) but contribute to neither the budget decomposition
nor the exemplar ring, exactly as they contribute to neither of the
server's p99 populations.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from npairloss_tpu.obs.qtrace.report import (
    MARKER_NAMES,
    PROBE_FUSED_SPAN,
    QTRACE_SCHEMA,
    ROOT_SPAN,
    STAGES,
)

_MAX_MARKERS = 4096


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (the repo-standard
    stdlib convention, obs/perf/decompose.py)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q / 100.0 * (len(sorted_vals) - 1))),
              len(sorted_vals) - 1)
    return sorted_vals[idx]


@dataclasses.dataclass(frozen=True)
class QTraceConfig:
    """``exemplars``: bound on retained span trees (fastest evicted
    first); ``slo_ms``: retain any query slower than this (<=0 disables
    the SLO rule); ``window``: rolling aggregation ring length — the
    budget decomposition's population; ``tail_quantile``: retain
    queries at or above this rolling percentile (the slowest-tail
    rule); ``ring_tolerance``: slack the artifact grants consumers
    cross-checking its p99 against the worst exemplar."""

    exemplars: int = 64
    slo_ms: float = 250.0
    window: int = 1024
    tail_quantile: float = 99.9
    ring_tolerance: float = 0.25

    def __post_init__(self):
        if self.exemplars < 1:
            raise ValueError(
                f"exemplars must be >= 1, got {self.exemplars}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if not (0.0 < self.tail_quantile <= 100.0):
            raise ValueError(
                f"tail_quantile must be in (0, 100], got "
                f"{self.tail_quantile}")
        if self.ring_tolerance < 0:
            raise ValueError("ring_tolerance must be >= 0")


class QueryTrace:
    """One query's trace context — created at ingestion, carried with
    the record across the admission/batcher/replica threads.  Each
    field is written by exactly one stage and the handoffs happen
    through the admission queue and the result future, so no lock is
    needed on the context itself."""

    __slots__ = ("trace_id", "qid", "wall_time", "t_ingest",
                 "t_admitted", "t_picked", "t_dispatch", "stage_us",
                 "events", "replica", "probe", "tenant", "done")

    def __init__(self, trace_id: str, qid: Any, wall_time: float,
                 t_ingest: float):
        self.trace_id = trace_id
        self.qid = qid
        self.wall_time = wall_time
        self.t_ingest = t_ingest
        self.t_admitted = t_ingest
        self.t_picked = t_ingest
        self.t_dispatch = t_ingest
        self.stage_us: Dict[str, float] = {}
        self.events: List[Dict[str, Any]] = []
        self.replica: Optional[str] = None
        self.probe = False
        # Multi-tenant serving stamps the owning tenant id at ingestion;
        # it rides into the root span's args so an exemplar tree is
        # attributable to the tenant whose traffic produced it.
        self.tenant: Optional[str] = None
        self.done = False


class QueryTracer:
    """Assigns trace ids, records stage spans, aggregates, samples.

    ``clock``/``wall`` are injectable for deterministic tests (seeded
    monotonic time); defaults are the real clocks.  All shared state is
    mutated under ``_lock`` — per-stage record calls arrive from the
    front-end, batcher, and replica dispatcher threads concurrently.
    """

    def __init__(self, cfg: QTraceConfig = QTraceConfig(),
                 registry=None, out_path: Optional[str] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        self.cfg = cfg
        self.registry = registry
        self.out_path = out_path
        self._clock = clock
        self._wall = wall
        self._t0 = clock()
        self.wall_time_origin = wall()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._seq = 0            # guarded-by: _lock
        self._queries = 0        # guarded-by: _lock
        self._errors = 0         # guarded-by: _lock
        self._dropped = 0        # guarded-by: _lock
        self._violations = 0     # guarded-by: _lock
        self._evicted = 0        # guarded-by: _lock
        self._reroutes = 0       # guarded-by: _lock
        self._flips = 0          # guarded-by: _lock
        # (total_ms, stage_ms) per answered query, newest last — the
        # budget decomposition's rolling population.
        self._recent: Deque[Tuple[float, Dict[str, float]]] = \
            collections.deque(maxlen=cfg.window)  # guarded-by: _lock
        # Same tuples, cleared on every window_row() read — mirrors the
        # server's per-window latency population.
        self._window_acc: List[Tuple[float, Dict[str, float]]] = \
            []                   # guarded-by: _lock
        self._exemplars: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._markers: List[Dict[str, Any]] = []    # guarded-by: _lock

    # -- clock -------------------------------------------------------------

    def _now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _tid(self) -> int:
        return threading.get_ident() & 0xFFFFFFFF

    def _span_event(self, qt: QueryTrace, name: str, t0_us: float,
                    t1_us: float, **args) -> None:
        qt.events.append({
            "name": name,
            "ph": "X",
            "ts": t0_us,
            "dur": max(t1_us - t0_us, 0.0),
            "pid": self._pid,
            "tid": self._tid(),
            "args": {"trace_id": qt.trace_id, **args},
        })

    # -- per-stage recording ----------------------------------------------

    def begin(self, qid: Any) -> QueryTrace:
        """Assign a trace id at ingestion and start the clock."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        return QueryTrace(f"q-{seq:08d}", qid, self._wall(),
                          self._now_us())

    def admitted(self, qt: QueryTrace, probe: bool = False) -> None:
        """The admission gate let the query through; ``admit_wait`` is
        the shed-check plus router time up to the replica queue."""
        now = self._now_us()
        qt.probe = qt.probe or probe
        qt.t_admitted = now
        self._span_event(qt, f"qtrace/{STAGES[0]}", qt.t_ingest, now)

    def picked(self, qt: QueryTrace) -> None:
        """The replica's dispatcher pulled the query off its admission
        queue; ``queue_wait`` ends here."""
        now = self._now_us()
        qt.t_picked = now
        self._span_event(qt, f"qtrace/{STAGES[1]}", qt.t_admitted, now)

    def dispatch_begin(self, qts: List[QueryTrace],
                       replica: Optional[str] = None) -> None:
        """The coalesced batch entered the dispatch path;
        ``batch_assemble`` is the co-rider wait since pick."""
        now = self._now_us()
        for qt in qts:
            qt.replica = replica
            qt.t_dispatch = now
            self._span_event(qt, f"qtrace/{STAGES[2]}", qt.t_picked,
                             now, **({"replica": replica} if replica
                                     else {}))

    def dispatch_end(self, qts: List[QueryTrace], score_us: float = 0.0,
                     merge_us: float = 0.0,
                     fused: bool = False) -> None:
        """The batch's answers exist.  ``score``/``topk_merge`` spans
        are placed back-to-back at the tail of the dispatch span from
        the engine's measured durations; ``dispatch`` keeps the
        remainder (parse, encode, failpoint stalls) as self time.

        ``fused`` marks a fused-Pallas IVF probe dispatch: the
        score/merge clocks then came out of ONE kernel, so a wrapping
        ``probe_fused`` span is emitted around them — the stage
        VOCABULARY (and every per-query ``stage_us`` row) is unchanged,
        so ``npairloss-qtrace-v1`` artifacts stay valid either way."""
        now = self._now_us()
        score_us = max(float(score_us), 0.0)
        merge_us = max(float(merge_us), 0.0)
        for qt in qts:
            total = max(now - qt.t_dispatch, 0.0)
            inner = min(score_us + merge_us, total)
            scale = inner / (score_us + merge_us) \
                if score_us + merge_us > 0 else 0.0
            s_us, m_us = score_us * scale, merge_us * scale
            self._span_event(qt, f"qtrace/{STAGES[3]}", qt.t_dispatch,
                             now)
            if fused and s_us + m_us > 0:
                self._span_event(qt, PROBE_FUSED_SPAN,
                                 now - m_us - s_us, now)
            if s_us > 0:
                self._span_event(qt, f"qtrace/{STAGES[4]}",
                                 now - m_us - s_us, now - m_us)
            if m_us > 0:
                self._span_event(qt, f"qtrace/{STAGES[5]}", now - m_us,
                                 now)
            qt.stage_us[STAGES[3]] = total - s_us - m_us
            qt.stage_us[STAGES[4]] = s_us
            qt.stage_us[STAGES[5]] = m_us

    # -- markers -----------------------------------------------------------

    def marker(self, name: str, **args) -> None:
        """Tier-level instant (hot-swap flip, crash reroute) — lands in
        the artifact and on the merged timeline's serve lane."""
        if name not in MARKER_NAMES:
            raise ValueError(f"unknown qtrace marker {name!r}")
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": self._tid(),
            "args": dict(args),
        }
        with self._lock:
            if name == "crash_reroute":
                self._reroutes += 1
            elif name == "hotswap_flip":
                self._flips += 1
            if len(self._markers) < _MAX_MARKERS:
                self._markers.append(ev)

    # -- completion --------------------------------------------------------

    def drop(self, qt: Optional[QueryTrace], error: bool = False) -> None:
        """A query that will never be answered (shed, rejected, or
        errored): counted, excluded from every aggregation population
        (the shared population contract above)."""
        if qt is None or qt.done:
            return
        qt.done = True
        with self._lock:
            self._queries += 1
            if error:
                self._errors += 1
            else:
                self._dropped += 1

    def finish(self, qt: Optional[QueryTrace]) -> None:
        """An answered query: close the root span, aggregate its stage
        durations, and decide exemplar retention."""
        if qt is None or qt.done:
            return
        qt.done = True
        now = self._now_us()
        # Waits derived from the stage handoff timestamps; the engine
        # stages were filled by dispatch_end (zero when the query
        # errored before dispatch).
        stage_ms = {
            STAGES[0]: max(qt.t_admitted - qt.t_ingest, 0.0) / 1e3,
            STAGES[1]: max(qt.t_picked - qt.t_admitted, 0.0) / 1e3,
            STAGES[2]: max(qt.t_dispatch - qt.t_picked, 0.0) / 1e3,
            STAGES[3]: qt.stage_us.get(STAGES[3], 0.0) / 1e3,
            STAGES[4]: qt.stage_us.get(STAGES[4], 0.0) / 1e3,
            STAGES[5]: qt.stage_us.get(STAGES[5], 0.0) / 1e3,
        }
        total_ms = max(now - qt.t_ingest, 0.0) / 1e3
        self._span_event(qt, ROOT_SPAN, qt.t_ingest, now,
                         **({"qid": qt.qid} if qt.qid is not None
                            else {}),
                         **({"probe": True} if qt.probe else {}),
                         **({"tenant": qt.tenant} if qt.tenant
                            else {}))
        if self.registry is not None:
            for stage, ms in stage_ms.items():
                self.registry.observe(f"qtrace_{stage}_ms", ms)
            self.registry.observe("qtrace_total_ms", total_ms)
        with self._lock:
            self._queries += 1
            violating = self.cfg.slo_ms > 0 and total_ms > self.cfg.slo_ms
            if violating:
                self._violations += 1
            # Tail rule against the ring BEFORE this sample joins it:
            # any new ring maximum clears the threshold, so the worst
            # query is always retained (the consistency invariant
            # bench_check --qtrace cross-checks).
            totals = sorted(t for t, _ in self._recent)
            tail = (not totals
                    or total_ms >= _percentile(totals,
                                               self.cfg.tail_quantile))
            self._recent.append((total_ms, stage_ms))
            self._window_acc.append((total_ms, stage_ms))
            if violating or tail:
                self._retain_locked(qt, total_ms,
                                    "slo" if violating else "tail")

    def _retain_locked(self, qt, total_ms, reason):  # holds-lock: _lock
        ex = {
            "trace_id": qt.trace_id,
            "qid": qt.qid,
            "reason": reason,
            "total_ms": total_ms,
            "wall_time": qt.wall_time,
            "replica": qt.replica,
            "events": sorted(qt.events, key=lambda e: e["ts"]),
        }
        if len(self._exemplars) >= self.cfg.exemplars:
            # Bounded store: the FASTEST exemplar goes first, so the
            # retained set stays the tail-heavy one and the worst span
            # tree is never evicted.
            fastest = min(range(len(self._exemplars)),
                          key=lambda i: self._exemplars[i]["total_ms"])
            if self._exemplars[fastest]["total_ms"] >= total_ms:
                self._evicted += 1
                return
            del self._exemplars[fastest]
            self._evicted += 1
        self._exemplars.append(ex)

    # -- aggregation views -------------------------------------------------

    def _budget_locked(self) -> Dict[str, Any]:  # holds-lock: _lock
        totals = sorted(t for t, _ in self._recent)
        stage_p99 = {}
        for stage in STAGES:
            vals = sorted(s[stage] for _, s in self._recent)
            stage_p99[stage] = round(_percentile(vals, 99.0), 3)
        worst_mean, dominant, dominant_ms = {}, "", 0.0
        if self._recent:
            k = max(1, len(self._recent) // 100)
            worst = sorted(self._recent, key=lambda r: r[0],
                           reverse=True)[:k]
            for stage in STAGES:
                worst_mean[stage] = round(
                    sum(s[stage] for _, s in worst) / len(worst), 3)
            dominant = max(STAGES, key=lambda s: worst_mean[s])
            dominant_ms = worst_mean[dominant]
        return {
            "p99_ms": round(_percentile(totals, 99.0), 3),
            "dominant": dominant,
            "dominant_ms": dominant_ms,
            "stage_p99_ms": stage_p99,
            "worst_mean_ms": worst_mean,
        }

    def budget(self) -> Dict[str, Any]:
        """Rolling p99 budget decomposition: which stage dominates the
        worst-window queries (``/healthz`` and the drain summary)."""
        with self._lock:
            return self._budget_locked()

    def window_row(self) -> Dict[str, Any]:
        """Drain the per-window accumulator into the window-row keys:
        the dominant stage among that window's worst queries."""
        with self._lock:
            acc = self._window_acc
            self._window_acc = []
        if not acc:
            return {"qtrace_dominant": "", "qtrace_dominant_ms": 0.0}
        k = max(1, len(acc) // 100)
        worst = sorted(acc, key=lambda r: r[0], reverse=True)[:k]
        means = {stage: sum(s[stage] for _, s in worst) / len(worst)
                 for stage in STAGES}
        dominant = max(STAGES, key=lambda s: means[s])
        return {"qtrace_dominant": dominant,
                "qtrace_dominant_ms": round(means[dominant], 3)}

    def summary_block(self) -> Dict[str, Any]:
        """The drain summary's ``qtrace`` block."""
        with self._lock:
            return {**self._totals_locked(),
                    "budget": self._budget_locked()}

    def _totals_locked(self) -> Dict[str, int]:  # holds-lock: _lock
        return {
            "queries": self._queries,
            "errors": self._errors,
            "dropped": self._dropped,
            "violations": self._violations,
            "exemplars": len(self._exemplars),
            "evicted": self._evicted,
            "reroutes": self._reroutes,
            "hotswap_flips": self._flips,
        }

    # -- the artifact ------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "schema": QTRACE_SCHEMA,
                "wall_time_origin": self.wall_time_origin,
                "slo_ms": self.cfg.slo_ms,
                "ring_tolerance": self.cfg.ring_tolerance,
                "stages": list(STAGES),
                "totals": self._totals_locked(),
                "budget": self._budget_locked(),
                "markers": list(self._markers),
                "exemplars": [dict(ex) for ex in self._exemplars],
            }

    def write(self, path: Optional[str] = None) -> str:
        """Atomic write (tmp + rename), the snapshot-commit idiom."""
        path = path or self.out_path
        if not path:
            raise ValueError("QueryTracer.write needs a path")
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return path
