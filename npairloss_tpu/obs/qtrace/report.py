"""The versioned ``npairloss-qtrace-v1`` contract: exemplar query traces.

One JSON object per serve run (written at drain by
:class:`npairloss_tpu.obs.qtrace.core.QueryTracer`): the per-stage p99
budget decomposition plus the retained exemplar span trees — full
per-query traces kept ONLY for SLO-violating and slowest-tail queries,
never a full-qps flight recorder (docs/OBSERVABILITY.md §Query
tracing).  ``validate_qtrace_report`` IS the contract; consumers
(``scripts/bench_check.py --qtrace``, the timeline merger, the gameday
verdict's attribution check) rely on exactly the keys it checks.

Stdlib-only and self-contained: ``bench_check --qtrace`` file-path-loads
this module from a jax-free process, the same contract as
``obs.live.alerts`` (declared in ``analysis/purity.py``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

QTRACE_SCHEMA = "npairloss-qtrace-v1"

# The serving-tier stage vocabulary, in pipeline order (docs/SERVING.md:
# socket -> admission gate -> replica queue -> co-rider coalescing ->
# dispatcher -> device top-K -> host merge/answer assembly).
STAGES: Tuple[str, ...] = (
    "admit_wait",
    "queue_wait",
    "batch_assemble",
    "dispatch",
    "score",
    "topk_merge",
)

# Point markers (Chrome "i" instants) the serve tier may record outside
# any single query's tree: a hot-swap generation flip and a crash
# reroute are tier-level events that explain tail spikes.
MARKER_NAMES: Tuple[str, ...] = ("hotswap_flip", "crash_reroute")

# Span-name vocabulary inside an exemplar tree: one root covering
# ingest -> answer plus one span per stage.
ROOT_SPAN = "qtrace/query"
STAGE_SPANS: Tuple[str, ...] = tuple(f"qtrace/{s}" for s in STAGES)

# The fused IVF probe kernel collapses score + topk_merge into ONE
# device dispatch; its trace wraps those two stage spans in this extra
# (non-stage) span.  It is allowed vocabulary inside an exemplar tree
# but NOT a stage: ``stages``/``stage_us`` keep the v1 six-stage
# contract, so fused and scan artifacts validate identically.
PROBE_FUSED_SPAN = "qtrace/probe_fused"

REPORT_KEYS: Tuple[str, ...] = (
    "schema", "wall_time_origin", "slo_ms", "ring_tolerance", "stages",
    "totals", "budget", "markers", "exemplars",
)
TOTAL_KEYS: Tuple[str, ...] = (
    "queries", "errors", "dropped", "violations", "exemplars",
    "evicted", "reroutes", "hotswap_flips",
)
BUDGET_KEYS: Tuple[str, ...] = (
    "p99_ms", "dominant", "dominant_ms", "stage_p99_ms", "worst_mean_ms",
)
EXEMPLAR_KEYS: Tuple[str, ...] = (
    "trace_id", "qid", "reason", "total_ms", "wall_time", "replica",
    "events",
)
EXEMPLAR_REASONS: Tuple[str, ...] = ("slo", "tail")

# Span-containment slack in microseconds: stage timestamps are stamped
# by different threads off one monotonic clock, so exact float equality
# at span edges is not guaranteed.
NEST_SLACK_US = 2.0


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_event(ev: Any, where: str) -> Optional[str]:
    """Chrome-trace shape for one qtrace event; error string or None."""
    if not isinstance(ev, dict):
        return f"{where}: event is not an object"
    name = ev.get("name")
    if not isinstance(name, str) or not name:
        return f"{where}: event missing name"
    ph = ev.get("ph")
    if ph not in ("X", "i"):
        return f"{where}: event {name!r} has ph {ph!r} (want X or i)"
    if not _num(ev.get("ts")):
        return f"{where}: event {name!r} has non-numeric ts"
    if ph == "X" and not (_num(ev.get("dur")) and ev["dur"] >= 0):
        return f"{where}: X event {name!r} needs a non-negative dur"
    return None


def _check_exemplar(ex: Any, i: int) -> Optional[str]:
    where = f"exemplars[{i}]"
    if not isinstance(ex, dict):
        return f"{where}: not an object"
    for key in EXEMPLAR_KEYS:
        if key not in ex:
            return f"{where}: missing key {key!r}"
    tid = ex.get("trace_id")
    if not isinstance(tid, str) or not tid:
        return f"{where}: trace_id must be a non-empty string"
    if ex.get("reason") not in EXEMPLAR_REASONS:
        return (f"{where}: reason {ex.get('reason')!r} not in "
                f"{EXEMPLAR_REASONS}")
    if not (_num(ex.get("total_ms")) and ex["total_ms"] > 0):
        return f"{where}: total_ms must be a positive number"
    events = ex.get("events")
    if not isinstance(events, list) or not events:
        return f"{where}: events must be a non-empty list"
    roots: List[Dict[str, Any]] = []
    last_ts = None
    for j, ev in enumerate(events):
        err = _check_event(ev, f"{where}.events[{j}]")
        if err:
            return err
        name = ev["name"]
        if name == ROOT_SPAN:
            roots.append(ev)
        elif name not in STAGE_SPANS and name != PROBE_FUSED_SPAN:
            return (f"{where}.events[{j}]: span name {name!r} outside "
                    f"the qtrace vocabulary")
        args = ev.get("args")
        if not (isinstance(args, dict) and args.get("trace_id") == tid):
            return (f"{where}.events[{j}]: args.trace_id must equal the "
                    f"exemplar's trace_id {tid!r}")
        # Ordering: the tree is emitted sorted by start timestamp.
        if last_ts is not None and ev["ts"] < last_ts:
            return (f"{where}.events[{j}]: events out of ts order "
                    f"({ev['ts']} after {last_ts})")
        last_ts = ev["ts"]
    if len(roots) != 1:
        return (f"{where}: expected exactly one {ROOT_SPAN!r} root span, "
                f"got {len(roots)}")
    root = roots[0]
    r0, r1 = root["ts"], root["ts"] + root["dur"]
    dispatch = None
    for ev in events:
        if ev.get("ph") != "X" or ev is root:
            continue
        e0, e1 = ev["ts"], ev["ts"] + ev["dur"]
        if e0 < r0 - NEST_SLACK_US or e1 > r1 + NEST_SLACK_US:
            return (f"{where}: span {ev['name']!r} [{e0}, {e1}] escapes "
                    f"the root span [{r0}, {r1}] — broken nesting")
        if ev["name"] == f"qtrace/{STAGES[3]}":
            dispatch = ev
    if dispatch is not None:
        d0 = dispatch["ts"] - NEST_SLACK_US
        d1 = dispatch["ts"] + dispatch["dur"] + NEST_SLACK_US
        for ev in events:
            if ev.get("name") in ("qtrace/score", "qtrace/topk_merge",
                                  PROBE_FUSED_SPAN):
                if ev["ts"] < d0 or ev["ts"] + ev["dur"] > d1:
                    return (f"{where}: {ev['name']!r} escapes its parent "
                            "dispatch span — broken nesting")
    return None


def validate_qtrace_report(obj: Any) -> Optional[str]:
    """Error string when ``obj`` violates the qtrace-v1 contract, else
    None.  Schema tag, key presence, stage vocabulary, per-exemplar
    span shape/ordering/nesting, and trace-id uniqueness."""
    if not isinstance(obj, dict):
        return "qtrace report is not a JSON object"
    for key in REPORT_KEYS:
        if key not in obj:
            return f"missing key {key!r}"
    if obj["schema"] != QTRACE_SCHEMA:
        return (f"schema {obj['schema']!r} != {QTRACE_SCHEMA!r} — "
                "refusing to interpret a foreign artifact")
    if tuple(obj["stages"]) != STAGES:
        return (f"stages {obj['stages']!r} do not match the contract "
                f"vocabulary {STAGES}")
    if not (_num(obj["ring_tolerance"]) and obj["ring_tolerance"] >= 0):
        return "ring_tolerance must be a non-negative number"
    if not _num(obj["slo_ms"]):
        return "slo_ms must be numeric"
    totals = obj["totals"]
    if not isinstance(totals, dict):
        return "totals must be an object"
    for key in TOTAL_KEYS:
        v = totals.get(key)
        if not (isinstance(v, int) and not isinstance(v, bool)
                and v >= 0):
            return f"totals[{key!r}] must be a non-negative integer"
    budget = obj["budget"]
    if not isinstance(budget, dict):
        return "budget must be an object"
    for key in BUDGET_KEYS:
        if key not in budget:
            return f"budget missing key {key!r}"
    if not (_num(budget["p99_ms"]) and budget["p99_ms"] >= 0):
        return "budget.p99_ms must be a non-negative number"
    if budget["dominant"] not in STAGES + ("",):
        return (f"budget.dominant {budget['dominant']!r} is not a "
                "known stage")
    for key in ("stage_p99_ms", "worst_mean_ms"):
        block = budget[key]
        if not isinstance(block, dict):
            return f"budget.{key} must be an object"
        for stage in block:
            if stage not in STAGES:
                return f"budget.{key} names unknown stage {stage!r}"
    markers = obj["markers"]
    if not isinstance(markers, list):
        return "markers must be a list"
    for j, ev in enumerate(markers):
        err = _check_event(ev, f"markers[{j}]")
        if err:
            return err
        if ev.get("ph") != "i" or ev.get("name") not in MARKER_NAMES:
            return (f"markers[{j}]: must be an 'i' instant named one of "
                    f"{MARKER_NAMES}")
    exemplars = obj["exemplars"]
    if not isinstance(exemplars, list):
        return "exemplars must be a list"
    if totals["exemplars"] != len(exemplars):
        return (f"totals.exemplars {totals['exemplars']} != "
                f"{len(exemplars)} retained exemplars")
    seen: set = set()
    for i, ex in enumerate(exemplars):
        err = _check_exemplar(ex, i)
        if err:
            return err
        tid = ex["trace_id"]
        if tid in seen:
            return (f"duplicate trace_id {tid!r} — exemplar identity "
                    "must be unique within one artifact")
        seen.add(tid)
    return None


def qtrace_p99_consistency(obj: Dict[str, Any]) -> Optional[str]:
    """The exemplar set must AGREE with the aggregation it rode along
    with: the worst retained span tree bounds the logged window p99
    from above (the tail rule retains every ring maximum), within the
    artifact's own ring tolerance.  Error string or None; call after
    :func:`validate_qtrace_report`."""
    exemplars = obj.get("exemplars") or []
    budget = obj.get("budget") or {}
    p99 = budget.get("p99_ms") or 0.0
    if not exemplars or not _num(p99) or p99 <= 0:
        return None  # nothing to cross-check
    worst = max(float(ex["total_ms"]) for ex in exemplars)
    tol = float(obj.get("ring_tolerance") or 0.0)
    if p99 > worst * (1.0 + tol):
        return (f"logged window p99 {p99:.3f} ms exceeds the worst "
                f"exemplar span tree ({worst:.3f} ms) by more than the "
                f"ring tolerance ({tol:.2f}) — the exemplar set "
                "disagrees with the aggregation it shipped with")
    return None


def load_qtrace_report(path: str) -> Dict[str, Any]:
    """Parse a qtrace artifact; raises ``ValueError`` on non-JSON."""
    with open(path, "r", encoding="utf-8") as f:
        try:
            obj = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON ({e})") from None
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: qtrace artifact must be a JSON object")
    return obj
