"""The versioned ``npairloss-quality-v1`` contract: the quality log.

The shadow scorer (:mod:`npairloss_tpu.obs.quality.shadow`) appends one
JSONL stream per serving run — ``quality.jsonl`` in the telemetry dir —
recording what the online recall estimate actually observed:

  * one ``config`` record FIRST (shadow rate, seed, recall Ks, the
    declared recall floor when an SLO armed one, and the committed
    ``parity`` baseline from the served IVF index's commit manifest —
    the birth certificate the live gauges are compared against);
  * one ``window`` record per emitted shadow window (per-K recall,
    score-gap stats, the running sampled total);
  * at most one ``summary`` record LAST (drain time, last-sample wall
    time) — the evidence the stale-shadow gate reads.

``validate_quality_report`` IS the contract, exactly like
``validate_alert_log`` and ``validate_remediation_log``: consumers rely
on every key it checks, and ``scripts/bench_check.py --quality``
file-path-loads THIS module from a jax-free process — so it keeps ZERO
intra-package imports (stdlib only, self-contained).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

QUALITY_SCHEMA = "npairloss-quality-v1"
QUALITY_KINDS = ("config", "window", "summary")

# Keys every record of each kind carries (pinned by tests/test_quality.py).
CONFIG_KEYS = ("schema", "kind", "shadow_rate", "seed", "ks", "window",
               "wall_time")
WINDOW_KEYS = ("schema", "kind", "wall_time", "samples", "sampled_total",
               "score_gap_mean", "score_gap_max")
SUMMARY_KEYS = ("schema", "kind", "wall_time", "sampled_total", "windows",
                "dropped")

# A shadow scorer that went silent for this long before the drain
# "silently stopped sampling" — overridable per run via the config
# record's ``stale_after_s`` (the scorer stamps it from its own window
# cadence).
DEFAULT_STALE_AFTER_S = 60.0


def load_quality_report(path: str) -> List[Dict[str, Any]]:
    """Read one quality JSONL file; a torn final line (killed writer)
    is tolerated, any other unparseable line surfaces through the
    validator via a sentinel record (the alert-log loader's contract)."""
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail: the crash-durability contract
            records.append({"_bad_line": i + 1})
    return records


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_quality_report(records: Sequence[Any]) -> Optional[str]:
    """Schema + stream-shape check; returns an error string or None.

    The contract: every record carries the schema tag and a known
    ``kind``; the FIRST record is the one ``config`` (shadow_rate in
    (0, 1], ascending unique integer ``ks``, window >= 1; the optional
    ``recall_floor`` is in [0, 1] and names its ``floor_metric``);
    every ``window`` carries ``recall_at_<k>`` in [0, 1] for each
    declared k, a positive integer sample count, non-negative score
    gaps with ``max >= mean``, and ``sampled_total``/``wall_time``
    non-decreasing across the stream; at most one ``summary``, last.
    """
    if not records:
        return "empty quality report (not even a config record)"
    ks: List[int] = []
    prev_total = 0
    prev_t = None
    saw_summary = False
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            return f"record {i} is not an object"
        if "_bad_line" in rec:
            return f"unparseable JSON on line {rec['_bad_line']}"
        if rec.get("schema") != QUALITY_SCHEMA:
            return (f"record {i}: schema must be {QUALITY_SCHEMA!r}, "
                    f"got {rec.get('schema')!r}")
        kind = rec.get("kind")
        if kind not in QUALITY_KINDS:
            return f"record {i}: kind {kind!r} not in {QUALITY_KINDS}"
        if saw_summary:
            return (f"record {i}: {kind} record after the summary "
                    "(the summary is the stream's last word)")
        if i == 0:
            if kind != "config":
                return ("record 0 must be the config record, got "
                        f"kind {kind!r}")
        elif kind == "config":
            return f"record {i}: duplicate config record"
        if kind == "config":
            for key in CONFIG_KEYS:
                if key not in rec:
                    return f"record {i} (config) missing {key!r}"
            rate = rec["shadow_rate"]
            if not _num(rate) or not (0.0 < rate <= 1.0):
                return (f"record {i}: shadow_rate {rate!r} outside "
                        "(0, 1] — a zero-rate run writes no report")
            raw_ks = rec["ks"]
            if (not isinstance(raw_ks, list) or not raw_ks
                    or any(not isinstance(k, int) or isinstance(k, bool)
                           or k < 1 for k in raw_ks)
                    or raw_ks != sorted(set(raw_ks))):
                return (f"record {i}: ks must be ascending unique "
                        f"integers >= 1, got {raw_ks!r}")
            ks = list(raw_ks)
            if not isinstance(rec["window"], int) or rec["window"] < 1:
                return f"record {i}: window must be an integer >= 1"
            if not _num(rec["wall_time"]):
                return f"record {i}: wall_time is not numeric"
            floor = rec.get("recall_floor")
            if floor is not None:
                if not _num(floor) or not (0.0 <= floor <= 1.0):
                    return (f"record {i}: recall_floor {floor!r} "
                            "outside [0, 1]")
                metric = rec.get("floor_metric")
                if not isinstance(metric, str) or not metric:
                    return (f"record {i}: recall_floor declared without "
                            "its floor_metric (the alert cross-check "
                            "needs the metric name)")
            stale = rec.get("stale_after_s")
            if stale is not None and (not _num(stale) or stale <= 0):
                return f"record {i}: stale_after_s must be > 0"
            baseline = rec.get("baseline")
            if baseline is not None and not isinstance(baseline, dict):
                return f"record {i}: baseline is not an object"
            prev_t = float(rec["wall_time"])
        elif kind == "window":
            for key in WINDOW_KEYS:
                if key not in rec:
                    return f"record {i} (window) missing {key!r}"
            if not isinstance(rec["samples"], int) or rec["samples"] < 1:
                return f"record {i}: samples must be an integer >= 1"
            for k in ks:
                r = rec.get(f"recall_at_{k}")
                if not _num(r) or not (0.0 <= r <= 1.0):
                    return (f"record {i}: recall_at_{k} {r!r} missing "
                            "or outside [0, 1]")
            gm, gx = rec["score_gap_mean"], rec["score_gap_max"]
            if not _num(gm) or gm < 0 or not _num(gx) or gx < 0:
                return (f"record {i}: score gaps must be numeric >= 0 "
                        "(the exact score can never trail the served "
                        "one after clamping)")
            if gx < gm - 1e-9:
                return (f"record {i}: score_gap_max {gx} < "
                        f"score_gap_mean {gm}")
            total = rec["sampled_total"]
            if not isinstance(total, int) or total < prev_total:
                return (f"record {i}: sampled_total {total!r} regressed "
                        f"(previous {prev_total}) — the counter is "
                        "monotone")
            prev_total = total
            if not _num(rec["wall_time"]):
                return f"record {i}: wall_time is not numeric"
            t = float(rec["wall_time"])
            if prev_t is not None and t < prev_t - 1e-6:
                return (f"record {i}: wall_time {t} precedes the "
                        f"previous record's {prev_t}")
            prev_t = t
        else:  # summary
            for key in SUMMARY_KEYS:
                if key not in rec:
                    return f"record {i} (summary) missing {key!r}"
            if not _num(rec["wall_time"]):
                return f"record {i}: wall_time is not numeric"
            if not isinstance(rec["windows"], int) or rec["windows"] < 0:
                return f"record {i}: windows must be an integer >= 0"
            n_windows = sum(1 for r in records[:i]
                            if isinstance(r, dict)
                            and r.get("kind") == "window")
            if rec["windows"] != n_windows:
                return (f"record {i}: summary claims {rec['windows']} "
                        f"window(s), the stream holds {n_windows}")
            if rec["sampled_total"] != prev_total and n_windows:
                return (f"record {i}: summary sampled_total "
                        f"{rec['sampled_total']} != last window's "
                        f"{prev_total}")
            last = rec.get("last_sample_wall_time")
            if rec["sampled_total"] > 0 and not _num(last):
                return (f"record {i}: summary with samples but no "
                        "numeric last_sample_wall_time (the stale-"
                        "shadow gate needs it)")
            offered = rec.get("offered_total")
            if offered is not None and (
                    not isinstance(offered, int) or offered < 0):
                return (f"record {i}: offered_total must be an "
                        "integer >= 0")
            lo = rec.get("last_offer_wall_time")
            if lo is not None and not _num(lo):
                return f"record {i}: last_offer_wall_time not numeric"
            saw_summary = True
    return None


# -- gate helpers (scripts/bench_check.py --quality) --------------------------


def quality_breaches(records: Sequence[Dict[str, Any]]
                     ) -> List[Tuple[int, str, float, float]]:
    """(record index, metric, recall, floor) for every window whose
    floor-K recall fell below the config's declared ``recall_floor``.
    Empty when no floor was declared (no SLO armed one) or nothing
    breached.  Call only on a validated report."""
    cfg = records[0]
    floor = cfg.get("recall_floor")
    if floor is None:
        return []
    metric = str(cfg.get("floor_metric"))
    # floor_metric is "serve_recall_at_<k>"; the window key drops the
    # phase prefix (the row->gauge mapping adds it back).
    key = metric[len("serve_"):] if metric.startswith("serve_") else metric
    out: List[Tuple[int, str, float, float]] = []
    for i, rec in enumerate(records):
        if rec.get("kind") != "window":
            continue
        r = rec.get(key)
        if isinstance(r, (int, float)) and r < floor:
            out.append((i, metric, float(r), float(floor)))
    return out


def stale_shadow(records: Sequence[Dict[str, Any]]) -> Optional[str]:
    """An error string when the shadow scorer silently stopped SCORING
    while traffic kept arriving.  The summary's offer-side evidence
    (``offered_total``/``last_offer_wall_time`` — stamped by the
    dispatch, not the scorer thread) is what separates a stalled
    scorer from stopped traffic: offers outrunning the last scored
    sample by more than ``stale_after_s`` is a wedge; a drain minutes
    after the last QUERY is a healthy idle server.  Older logs without
    the offer keys fall back to the drain-time heuristic.  None when
    the stream looks live, or when no summary exists (a killed run is
    the alert gate's problem).  Call only on a validated report."""
    cfg = records[0]
    summary = next((r for r in records if r.get("kind") == "summary"),
                   None)
    if summary is None:
        return None
    stale_after = float(cfg.get("stale_after_s", DEFAULT_STALE_AFTER_S))
    offered = summary.get("offered_total")
    last_offer = summary.get("last_offer_wall_time")
    if summary["sampled_total"] == 0:
        if offered == 0:
            return None  # no traffic was ever sampled — not a wedge
        age = float(summary["wall_time"]) - float(cfg["wall_time"])
        if age > stale_after:
            return (f"shadow scorer sampled NOTHING in {age:.1f}s of "
                    "run"
                    + (f" ({offered} offer(s) arrived)"
                       if offered else
                       " (rate > 0 but zero samples reached the "
                       "oracle)"))
        return None
    last_sample = float(summary["last_sample_wall_time"])
    if last_offer is not None:
        age = float(last_offer) - last_sample
        if age > stale_after:
            return (f"shadow scorer went silent: offers kept arriving "
                    f"{age:.1f}s past the last scored sample "
                    f"(stale_after_s={stale_after:g}) — scoring "
                    "stalled mid-run")
        return None
    age = float(summary["wall_time"]) - last_sample
    if age > stale_after:
        return (f"shadow scorer went silent: last sample {age:.1f}s "
                f"before the drain (stale_after_s={stale_after:g}) — "
                "sampling stopped mid-run")
    return None


def quality_summary(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate view for ``watch``/``prof --quality``: per-K min/mean
    recall over every window, worst score gap, breach count vs the
    declared floor, and the committed baseline (when the config carried
    one) for side-by-side reading.  Call only on a validated report."""
    cfg = records[0]
    windows = [r for r in records if r.get("kind") == "window"]
    ks = list(cfg.get("ks", []))
    recall: Dict[str, Dict[str, float]] = {}
    for k in ks:
        vals = [float(w[f"recall_at_{k}"]) for w in windows]
        if vals:
            recall[f"at_{k}"] = {
                "min": round(min(vals), 4),
                "mean": round(sum(vals) / len(vals), 4),
                "last": round(vals[-1], 4),
            }
    out: Dict[str, Any] = {
        "windows": len(windows),
        "sampled_total": (windows[-1]["sampled_total"] if windows else 0),
        "shadow_rate": cfg.get("shadow_rate"),
        "recall": recall,
        "breaches": len(quality_breaches(records)),
    }
    gaps = [float(w["score_gap_max"]) for w in windows]
    if gaps:
        out["score_gap_max"] = round(max(gaps), 6)
    if cfg.get("recall_floor") is not None:
        out["recall_floor"] = cfg["recall_floor"]
        out["floor_metric"] = cfg.get("floor_metric")
    if isinstance(cfg.get("baseline"), dict):
        out["baseline"] = cfg["baseline"]
    return out
