"""ShadowScorer — online recall estimation by shadow-scoring live queries.

The reference monitored answer quality DURING training (in-training
Recall@{1,5,10}, reference cu:173-206); the serving tier must do the
same DURING serving: the PR-11 IVF index trades recall for latency, and
that trade was gated offline only (the ``topk_recall`` parity harness
runs at build/test time).  This module closes the loop against live
traffic (docs/OBSERVABILITY.md §Quality observatory):

  * the serving dispatch **offers** every answered query; a
    deterministic hash of ``(seed, query id)`` keeps a configurable
    fraction (``--shadow-rate``) — same seed ⇒ same shadow set, so a
    replayed query stream shadows identically;
  * sampled queries land in a bounded queue (full queue = counted drop,
    NEVER a block — the serving path's latency is untouched, pinned by
    tests/test_quality.py) and a background thread re-scores them
    against a **flat brute-force oracle** (the ``GalleryIndex``
    block-streamed exact scan at fp32 HIGHEST — the same math the
    offline parity harness trusts);
  * per window of samples it emits ONE serve-phase telemetry row
    (``recall_at_{1,5,10}``, ``shadow_score_gap``) through the existing
    ``RunTelemetry`` — the PR-10 ``RegistrySink`` then feeds the
    ``serve_recall_at_{k}`` gauges with zero new sink call sites, the
    row stream replays through ``watch``, and the recall-floor SLO
    reads the gauges like any other — plus one ``window`` record into
    the versioned ``npairloss-quality-v1`` log (``quality.jsonl``).

The oracle follows the SERVED index: ``index_fn`` is read per scoring
batch, and a hot-swap or ``add()`` republish (a new index object)
rebuilds the oracle before the next batch scores — shadow recall is
always measured against the gallery the answers came from.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from npairloss_tpu.obs.quality.report import QUALITY_SCHEMA

log = logging.getLogger("npairloss_tpu.obs.quality")

QUALITY_FILENAME = "quality.jsonl"

_HASH_SPACE = float(2 ** 32)


def shadow_sampled(query_id: Any, rate: float, seed: int = 0) -> bool:
    """Deterministic membership of one query id in the shadow set.

    A stable CRC-32 of ``(seed, repr(id))`` against ``rate`` — NOT
    Python's salted ``hash()``, so the same seed selects the same ids
    across processes and replays (the determinism pin)."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = zlib.crc32(f"{seed}:{query_id!r}".encode("utf-8"))
    return (h / _HASH_SPACE) < rate


def recall_against(served_rows: Sequence[int], exact_rows: Sequence[int],
                   k: int) -> float:
    """Per-query recall@K: |served top-K ∩ exact top-K| / K — the
    ``serve/ivf.topk_recall`` math for ONE query (kept jax-free so the
    window aggregation is testable against hand fixtures)."""
    s = set(int(r) for r in served_rows[:k])
    e = set(int(r) for r in exact_rows[:k])
    return len(s & e) / float(k)


@dataclasses.dataclass(frozen=True)
class ShadowConfig:
    """``rate`` is the sampled fraction of answered queries (0 disables
    — the scorer is then never constructed); ``ks`` the recall depths
    (clamped to the served ``top_k``); ``window`` the samples per
    emitted quality row; ``max_queue`` the bound on queued-but-unscored
    samples (beyond it, drops are counted, dispatches never wait);
    ``oracle_batch`` the padding bucket the oracle scores shadows in."""

    rate: float = 0.1
    ks: Tuple[int, ...] = (1, 5, 10)
    window: int = 32
    seed: int = 0
    max_queue: int = 512
    oracle_batch: int = 8
    stale_after_s: float = 60.0

    def __post_init__(self):
        if not (0.0 < self.rate <= 1.0):
            raise ValueError(
                f"shadow rate must be in (0, 1], got {self.rate} "
                "(0 means: do not build a scorer)")
        if not self.ks or list(self.ks) != sorted(set(self.ks)) \
                or min(self.ks) < 1:
            raise ValueError(
                f"ks must be ascending unique ints >= 1, got {self.ks}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")


class _Sample:
    __slots__ = ("qid", "embedding", "served_rows", "served_best")

    def __init__(self, qid, embedding, served_rows, served_best):
        self.qid = qid
        self.embedding = embedding
        self.served_rows = served_rows
        self.served_best = served_best


class ShadowScorer:
    """Sample, queue, oracle-score, emit — see the module docstring.

    ``index_fn`` returns the CURRENTLY served index (the server's
    ``lambda: server.engine.index``) so swaps re-anchor the oracle;
    ``telemetry`` routes the per-window row through the existing sink
    chain (None = registry-only mode for tests: pass ``registry`` and
    the gauges are set directly, the freshness-probe pattern);
    ``out_path`` lands ``quality.jsonl`` (None = in-memory history
    only).  ``baseline`` is the served IVF commit's ``parity`` manifest
    block; ``recall_floor``/``floor_metric`` the armed SLO's declared
    floor — both are stamped into the config record so the jax-free
    gate can judge the stream without the serving process."""

    def __init__(
        self,
        index_fn: Callable[[], Any],
        cfg: ShadowConfig = ShadowConfig(),
        telemetry=None,
        registry=None,
        out_path: Optional[str] = None,
        baseline: Optional[Dict[str, Any]] = None,
        recall_floor: Optional[float] = None,
        floor_metric: Optional[str] = None,
    ):
        self.index_fn = index_fn
        self.cfg = cfg
        self.telemetry = telemetry
        self.registry = registry
        self.baseline = baseline
        self.recall_floor = recall_floor
        self.floor_metric = floor_metric
        self._q: queue.Queue = queue.Queue(maxsize=cfg.max_queue)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.sampled_total = 0
        self.dropped = 0
        self.windows = 0
        # Offer-side evidence: how many queries the dispatch SAMPLED
        # (accepted or dropped) and when the last one arrived — what
        # lets the stale-shadow gate tell "scorer stalled" apart from
        # "traffic stopped" (a drain minutes after the last query is
        # healthy; offers outrunning samples is not).
        self.offered_total = 0
        self.last_offer_wall_time: Optional[float] = None
        self.last_sample_wall_time: Optional[float] = None
        self._last_window: Dict[str, Any] = {}
        self._acc: List[Dict[str, float]] = []
        self._oracle = None  # (index object, (size, created), engine)
        self.history: List[Dict[str, Any]] = []
        self.out_path = os.path.abspath(out_path) if out_path else None
        self._f = None
        if self.out_path:
            parent = os.path.dirname(self.out_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(self.out_path, "a", buffering=1)
        self._emit({
            "schema": QUALITY_SCHEMA,
            "kind": "config",
            "shadow_rate": cfg.rate,
            "seed": cfg.seed,
            "ks": list(cfg.ks),
            "window": cfg.window,
            "wall_time": time.time(),
            "stale_after_s": cfg.stale_after_s,
            **({"baseline": baseline} if baseline else {}),
            **({"recall_floor": recall_floor,
                "floor_metric": floor_metric}
               if recall_floor is not None else {}),
        })

    # -- the hot-path side (dispatch thread) -------------------------------

    def sampled(self, query_id: Any) -> bool:
        return shadow_sampled(query_id, self.cfg.rate, self.cfg.seed)

    def offer(self, query_id: Any, embedding: np.ndarray,
              served_rows: np.ndarray, served_scores: np.ndarray) -> bool:
        """Called by the serving dispatch per answered query: hash, and
        (when sampled) enqueue a COPY of the answer evidence.  Hash +
        ``put_nowait`` only — a full queue is a counted drop, never a
        wait; the serving path's latency is invariant to the scorer
        (the tests/test_quality.py pin)."""
        if not self.sampled(query_id):
            return False
        with self._lock:
            self.offered_total += 1
            self.last_offer_wall_time = time.time()
        sample = _Sample(
            query_id,
            np.array(embedding, np.float32, copy=True),
            np.array(served_rows, np.int32, copy=True),
            float(served_scores[0]) if len(served_scores) else 0.0,
        )
        try:
            self._q.put_nowait(sample)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            return False
        return True

    # -- the shadow side (background thread) -------------------------------

    def _oracle_engine(self):
        """The flat exact-scan oracle for the CURRENTLY served index,
        rebuilt when the served gallery changes — recall is always
        measured against the gallery the answers came from.  A
        hot-swap arrives as a NEW index object, but ``add()``
        republishes IN PLACE (same object, new rows), so the staleness
        token is (identity, size, created): ``add()`` bumps both size
        and the ``created`` freshness stamp, forcing the rebuild.
        Kept single-device and UNWARMED: its compiles count only in
        its own totals and can never trip the serving tier's strict
        compile guard."""
        from npairloss_tpu.serve.engine import EngineConfig, QueryEngine
        from npairloss_tpu.serve.index import GalleryIndex

        index = self.index_fn()
        token = (index.size, index.created)
        if self._oracle is not None and self._oracle[0] is index \
                and self._oracle[1] == token:
            return self._oracle[2]
        kmax = min(max(self.cfg.ks), index.size)
        flat = GalleryIndex.build(
            index._host_emb, index._host_labels, ids=index.ids,
            normalize=False)
        engine = QueryEngine(
            flat,
            EngineConfig(top_k=kmax,
                         buckets=(min(self.cfg.oracle_batch, flat.size),),
                         scoring="fp32"),
        )
        self._oracle = (index, token, engine)
        log.info("shadow oracle rebuilt for index of %d rows", flat.size)
        return engine

    def _score_batch(self, batch: List[_Sample]) -> None:
        engine = self._oracle_engine()
        out = engine.query(np.stack([s.embedding for s in batch]))
        now = time.time()
        for j, s in enumerate(batch):
            exact_rows = out["rows"][j]
            exact_best = float(out["scores"][j, 0])
            rec = {
                f"recall_at_{k}": recall_against(s.served_rows,
                                                 exact_rows, k)
                for k in self.cfg.ks
                if k <= len(s.served_rows) and k <= len(exact_rows)
            }
            # The exact top-1 can only trail a served score through
            # scoring-dtype noise (bf16/int8 overestimates); clamp so
            # the gap reads "similarity left on the table", never < 0.
            rec["gap"] = max(exact_best - s.served_best, 0.0)
            self._acc.append(rec)
            with self._lock:
                self.sampled_total += 1
                self.last_sample_wall_time = now
        while len(self._acc) >= self.cfg.window:
            window, self._acc = (self._acc[:self.cfg.window],
                                 self._acc[self.cfg.window:])
            self._emit_window(window, now)

    def _emit_window(self, window: List[Dict[str, float]],
                     now: float) -> None:
        n = len(window)
        gaps = [w["gap"] for w in window]
        row: Dict[str, Any] = {}
        for k in self.cfg.ks:
            vals = [w[f"recall_at_{k}"] for w in window
                    if f"recall_at_{k}" in w]
            if vals:
                row[f"recall_at_{k}"] = round(sum(vals) / len(vals), 4)
        row["shadow_score_gap"] = round(sum(gaps) / n, 6)
        row["shadow_score_gap_max"] = round(max(gaps), 6)
        row["shadow_samples"] = n
        with self._lock:
            total = self.sampled_total
            dropped = self.dropped
            self.windows += 1
            self._last_window = dict(row)
        if dropped:
            # The spans_dropped contract: present only when > 0, so
            # drop-free streams stay byte-identical.
            row["shadow_dropped"] = dropped
        if self.telemetry is not None and self.telemetry.metrics_enabled:
            try:
                # THE emission: one existing-telemetry serve row — the
                # RegistrySink turns recall_at_10 into the
                # serve_recall_at_10 gauge with zero new sink call
                # sites, and the row replays through `watch`.
                self.telemetry.log("serve", total, row)
            except Exception as e:  # noqa: BLE001 — observing must not kill serving
                log.error("shadow window emission failed: %s", e)
        if self.registry is not None and self.telemetry is None:
            # Registry-only mode (no telemetry stream to ride): set the
            # gauges directly, the freshness-probe pattern.
            for key, v in row.items():
                if isinstance(v, (int, float)):
                    self.registry.set(f"serve_{key}", float(v), now)
        self._emit({
            "schema": QUALITY_SCHEMA,
            "kind": "window",
            "wall_time": now,
            "samples": n,
            "sampled_total": total,
            **{k: v for k, v in row.items()
               if k.startswith("recall_at_")},
            "score_gap_mean": row["shadow_score_gap"],
            "score_gap_max": row["shadow_score_gap_max"],
        })

    def _loop(self) -> None:
        batch: List[_Sample] = []
        while True:
            try:
                item = self._q.get(timeout=0.05)
                batch.append(item)
            except queue.Empty:
                item = None
            if self._stop.is_set() and item is None and self._q.empty():
                break
            full = len(batch) >= self.cfg.oracle_batch
            drained = item is None and batch
            if full or drained:
                try:
                    self._score_batch(batch)
                except Exception as e:  # noqa: BLE001 — shadow must not die silently
                    log.error("shadow scoring failed (%d sample(s) "
                              "lost): %s", len(batch), e)
                    with self._lock:
                        self.dropped += len(batch)
                batch = []
        if batch:
            try:
                self._score_batch(batch)
            except Exception as e:  # noqa: BLE001
                log.error("shadow drain scoring failed: %s", e)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ShadowScorer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="shadow-scorer", daemon=True)
            self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain the queue (every accepted sample is scored), flush a
        final partial window, append the summary record, close the
        log."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._acc:
            self._emit_window(self._acc, time.time())
            self._acc = []
        with self._lock:
            summary = {
                "schema": QUALITY_SCHEMA,
                "kind": "summary",
                "wall_time": time.time(),
                "sampled_total": self.sampled_total,
                "windows": self.windows,
                "dropped": self.dropped,
                "offered_total": self.offered_total,
                **({"last_offer_wall_time": self.last_offer_wall_time}
                   if self.last_offer_wall_time is not None else {}),
                **({"last_sample_wall_time": self.last_sample_wall_time}
                   if self.last_sample_wall_time is not None else {}),
            }
        self._emit(summary)
        if self._f is not None and not self._f.closed:
            self._f.close()

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self._f is None:
            # In-memory mode only (tests, no out_path): with a log on
            # disk the stream lives there — an unbounded in-process
            # copy would be a slow leak on a multi-day serve.
            self.history.append(rec)
        elif not self._f.closed:
            self._f.write(json.dumps(rec) + "\n")

    # -- reads -------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The /healthz + drain-summary ``quality`` block: what the
        shadow estimate currently says.  ``last`` absent until the
        first window lands; ``baseline`` only when the served commit
        carried its parity birth certificate."""
        with self._lock:
            out: Dict[str, Any] = {
                "shadow_rate": self.cfg.rate,
                "sampled": self.sampled_total,
                "windows": self.windows,
                "dropped": self.dropped,
            }
            if self._last_window:
                out["last"] = dict(self._last_window)
        if self.baseline:
            out["baseline"] = self.baseline
        return out
