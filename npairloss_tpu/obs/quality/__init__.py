"""Quality observatory — online ANSWER-QUALITY observation for serving.

The live observatory (obs/live) watches speed and health; this package
watches whether the answers are still GOOD (docs/OBSERVABILITY.md
§Quality observatory):

  * :mod:`report`   — the versioned ``npairloss-quality-v1`` JSONL
    contract (``validate_quality_report`` IS the contract) plus the
    jax-free gate helpers ``scripts/bench_check.py --quality``
    file-path-loads — stdlib only, self-contained, the alerts.py
    pattern;
  * :mod:`shadow`   — the ShadowScorer: deterministic sampling of live
    queries, off-hot-path re-scoring against the flat brute-force
    oracle, per-window ``serve_recall_at_{1,5,10}``/score-gap rows
    through the EXISTING telemetry sink chain;
  * :mod:`escalate` — the ProbeEscalator remediation actuator: widen
    the IVF probe set under a burning recall floor, flat-fallback when
    the probe budget exhausts.

``shadow`` and ``escalate`` need jax (they build serve engines) and are
imported lazily by their consumers; this ``__init__`` re-exports only
the stdlib contract.  Truly jax-free processes (``bench_check``, the
``watch`` surfacing) file-path-load ``report.py`` directly — the parent
``obs`` package's ``__init__`` imports jax, so ``report.py`` keeps zero
intra-package imports (the alerts.py/remediate.py contract).
"""

from npairloss_tpu.obs.quality.report import (
    QUALITY_SCHEMA,
    load_quality_report,
    quality_breaches,
    quality_summary,
    stale_shadow,
    validate_quality_report,
)

__all__ = [
    "QUALITY_SCHEMA",
    "load_quality_report",
    "quality_breaches",
    "quality_summary",
    "stale_shadow",
    "validate_quality_report",
]
