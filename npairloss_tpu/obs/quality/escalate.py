"""ProbeEscalator — the recall-burn remediation actuator.

When the recall-floor SLO burns (the shadow scorer's live
``serve_recall_at_{k}`` gauge under the declared floor), the cheapest
knob that buys recall back is the IVF probe width: score more clusters
per query.  ``probes`` is baked into the engine's jitted program, so an
escalation is a HOT-SWAP, not a flag flip — build a fresh engine tier
with the widened ``EngineConfig``, warm every padding bucket OFF the
serving path (the old tier keeps answering through the compiles), then
publish atomically via :meth:`RetrievalServer.swap_engines` — zero
dropped queries, zero serving-path compiles, the hotswap contract.

The escalation ladder doubles probes per attempt up to the cluster
count; with the probe budget exhausted (probing every cluster IS the
exact scan, just a slower one) the next attempt **falls back to flat
scoring**: the tier republishes on a flat ``GalleryIndex`` built from
the same gallery rows — recall is 1.0 by construction, latency pays.
A further attempt on a flat tier raises (nothing left to escalate),
which the remediation engine records as an honest FAILED attempt — the
``NothingNewerError`` pattern.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional

log = logging.getLogger("npairloss_tpu.obs.quality")


class EscalationExhaustedError(RuntimeError):
    """The tier already serves flat exact answers — no knob remains."""


class ProbeEscalator:
    """Escalate the served IVF probe width; flat-fallback past it.

    ``factor`` multiplies ``probes`` per attempt (clamped to the
    cluster count).  The CURRENT tier is read from the server at each
    call, so escalations chain correctly across interleaved hot-swaps
    (a snapshot swap preserves the escalated config — hotswap reuses
    ``old.cfg``).  ``escalate(alert=None)`` is the remediation-action
    signature; the returned detail dict lands on the audit record.
    """

    def __init__(self, server, telemetry=None, factor: int = 2):
        if factor < 2:
            raise ValueError(f"factor must be >= 2, got {factor}")
        self.server = server
        self.telemetry = telemetry
        self.factor = factor

    def escalate(self, alert: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        from npairloss_tpu.serve.engine import QueryEngine
        from npairloss_tpu.serve.index import GalleryIndex
        from npairloss_tpu.serve.ivf import IVFIndex

        server = self.server
        old = server.engine
        index = old.index
        if not isinstance(index, IVFIndex):
            raise EscalationExhaustedError(
                "serving tier is already flat (exact scan) — probe "
                "escalation has nothing left to widen"
                + (f" (alert {alert.get('alert_id')})" if alert else ""))
        kc = index.n_clusters
        effective = min(old.cfg.probes, kc)
        if effective < kc:
            new_probes = min(effective * self.factor, kc)
            cfg = dataclasses.replace(old.cfg, probes=new_probes)
            new_index = index
            detail: Dict[str, Any] = {"probes": new_probes,
                                      "probes_before": effective}
            log.warning("recall remediation: escalating IVF probes "
                        "%d -> %d (of %d clusters)",
                        effective, new_probes, kc)
        else:
            # Probe budget exhausted: probing every cluster already IS
            # the exact answer set — the remaining recall knob is the
            # flat oracle itself.  int8 has no flat equivalent (the
            # per-cluster scale), so the fallback scores fp32.
            cfg = dataclasses.replace(
                old.cfg,
                scoring=("fp32" if old.cfg.scoring == "int8"
                         else old.cfg.scoring))
            new_index = GalleryIndex.build(
                index._host_emb, index._host_labels, ids=index.ids,
                mesh=index.mesh, axis=index.axis, normalize=False)
            new_index.created = index.created  # same content, same age
            detail = {"fallback": "flat", "probes_before": effective}
            log.warning("recall remediation: probe budget exhausted "
                        "(%d/%d) — falling back to the flat exact scan",
                        effective, kc)
        primary = QueryEngine(
            new_index, cfg, model=old.model, state=old.state,
            telemetry=self.telemetry,
        )
        warmup_s = primary.warmup(
            server.input_shape if old.model is not None else None)
        engines = [primary] + [
            QueryEngine(new_index, cfg, model=old.model, state=old.state,
                        telemetry=self.telemetry,
                        share_compiled_with=primary)
            for _ in range(len(server.engines) - 1)
        ]
        for e in engines[1:]:
            e.warmed = True
        # Same gallery content, same freshness identity: pass None so
        # swap_engines keeps the served ages — a recall remediation is
        # not a freshness event.
        server.swap_engines(engines, None)
        detail["warmup_s"] = round(warmup_s, 3)
        if self.telemetry is not None:
            self.telemetry.instant("serve/probe_escalation", **detail)
        return detail
