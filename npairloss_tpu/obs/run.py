"""RunTelemetry — one object tying a run directory to the telemetry parts.

A run directory is the on-disk unit of diagnosability:

    <run_dir>/manifest.json   provenance (obs.manifest.RunManifest)
    <run_dir>/metrics.jsonl   structured metric records (obs.sinks)
    <run_dir>/trace.json      host span timeline (obs.tracing, Perfetto)

``RunTelemetry`` owns the run_id, stamps every record with the required
``{run_id, step, wall_time, phase}`` envelope, multiplexes records to a
JSONL file + in-memory ring buffer (plus any extra sinks), and holds the
span tracer.  The Solver and the CLI emit through this one pipeline
instead of bespoke callbacks and hand-rolled JSON.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Iterator, Optional, Sequence

from npairloss_tpu.obs.manifest import RunManifest
from npairloss_tpu.obs.sinks import (
    JsonlSink,
    MetricLogger,
    MultiSink,
    RingBufferSink,
)
from npairloss_tpu.obs.tracing import SpanTracer

METRICS_FILENAME = "metrics.jsonl"
MANIFEST_FILENAME = "manifest.json"
TRACE_FILENAME = "trace.json"


def _default_run_id() -> str:
    """Sortable, collision-resistant without coordination: UTC timestamp
    + pid + 2 random bytes (concurrent processes on one host share the
    second)."""
    rand = os.urandom(2).hex()
    return time.strftime("%Y%m%d-%H%M%S", time.gmtime()) + \
        f"-{os.getpid()}-{rand}"


class RunTelemetry:
    """Lifecycle: construct (creates the run dir and opens sinks) ->
    ``write_manifest`` -> ``log``/``span`` during the run -> ``close``
    (flushes sinks, writes trace.json).  Usable as a context manager.

    ``metrics=False`` gives a trace-only instance (the CLI's
    ``--trace-dir``); ``trace=False`` a metrics-only one.  ``ring``
    records stay readable via ``.ring.records()`` for live
    introspection either way.

    ``fleet`` (docs/OBSERVABILITY.md §Fleet observatory) opts into
    rank-stamped multi-process telemetry: ``True`` resolves the ambient
    rank identity (jax process topology or the harness override), an
    explicit :class:`obs.fleet.FleetStamp` passes through.  With a
    stamp, every metric row gains ``{process_index, process_count,
    local_device_ids}`` and the on-disk files switch to the rank-aware
    scheme (``telemetry.r<k>.jsonl`` / ``trace.r<k>.json`` /
    ``manifest.r<k>.json``) so N concurrent ranks sharing one run dir
    never interleave a stream.  With ``fleet=None`` (default) behavior
    — file names AND stream bytes — is identical to the pre-fleet
    layer; the parity is pinned by test.
    """

    def __init__(
        self,
        run_dir: str,
        run_id: Optional[str] = None,
        metrics: bool = True,
        trace: bool = True,
        ring_capacity: int = 1024,
        extra_sinks: Sequence[MetricLogger] = (),
        fleet=None,
    ):
        from npairloss_tpu.obs.fleet.stamp import resolve_fleet

        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.run_id = run_id or _default_run_id()
        self.fleet = resolve_fleet(fleet)
        self._stamp = self.fleet.to_dict() if self.fleet else None
        # Consumers (Solver.train) gate their per-step emission on this:
        # a trace-only instance must not pay the per-step host sync that
        # materializing metric scalars costs — it would distort the very
        # host timeline the tracer exists to capture.
        self.metrics_enabled = bool(metrics)
        self.ring = RingBufferSink(ring_capacity)
        children: list = [self.ring]
        if metrics:
            children.insert(
                0, JsonlSink(os.path.join(self.run_dir,
                                          self._metrics_filename()))
            )
        children.extend(extra_sinks)
        self.sink: MetricLogger = MultiSink(children)
        self.tracer: Optional[SpanTracer] = SpanTracer() if trace else None
        if self.tracer is not None and self._stamp is not None:
            self.tracer.stamp = dict(self._stamp)
        self.manifest: Optional[RunManifest] = None
        self._closed = False

    # -- rank-aware path scheme -------------------------------------------

    def _metrics_filename(self) -> str:
        if self.fleet is None:
            return METRICS_FILENAME
        from npairloss_tpu.obs.fleet.stamp import rank_metrics_name

        return rank_metrics_name(self.fleet.process_index)

    def _trace_filename(self) -> str:
        if self.fleet is None:
            return TRACE_FILENAME
        from npairloss_tpu.obs.fleet.stamp import rank_trace_name

        return rank_trace_name(self.fleet.process_index)

    def _manifest_filename(self) -> str:
        if self.fleet is None:
            return MANIFEST_FILENAME
        from npairloss_tpu.obs.fleet.stamp import rank_manifest_name

        return rank_manifest_name(self.fleet.process_index)

    # -- manifest ---------------------------------------------------------

    def write_manifest(
        self,
        config: Optional[Dict[str, Any]] = None,
        mesh: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Collect + write ``manifest.json`` (``manifest.r<k>.json``
        under a fleet stamp); call once at run start."""
        self.manifest = RunManifest.collect(
            self.run_id, config=config, mesh=mesh, fleet=self._stamp,
            extra=extra,
        )
        return self.manifest.write(
            os.path.join(self.run_dir, self._manifest_filename())
        )

    # -- metric records ---------------------------------------------------

    def log(
        self,
        phase: str,
        step: int,
        metrics: Optional[Dict[str, Any]] = None,
        **extra: Any,
    ) -> Dict[str, Any]:
        """Emit one record with the required envelope stamped.  The
        caller's metric keys must not collide with the envelope (the
        envelope wins — a metric named "step" would corrupt every
        downstream consumer)."""
        record: Dict[str, Any] = {}
        if metrics:
            record.update(metrics)
        record.update(extra)
        record.update(
            run_id=self.run_id,
            step=int(step),
            wall_time=time.time(),
            phase=phase,
        )
        if self._stamp is not None:
            # Fleet identity on EVERY row: offline aggregation must be
            # able to attribute a row found anywhere (a copied stream, a
            # fan-out sink) without trusting its file name.
            record.update(self._stamp)
        self.sink.log(record)
        return record

    # -- spans ------------------------------------------------------------

    def span(self, name: str, **args: Any):
        """Tracer span, or a no-op context when tracing is disabled —
        call sites never need to branch."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **args)

    def instant(self, name: str, **args: Any) -> None:
        if self.tracer is not None:
            self.tracer.instant(name, **args)

    # -- lifecycle --------------------------------------------------------

    def flush(self) -> None:
        self.sink.flush()
        if self.tracer is not None:
            self.tracer.write(
                os.path.join(self.run_dir, self._trace_filename()))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.flush()
        finally:
            # Even when a flush/trace write fails (disk full), every
            # sink still gets its close call (MultiSink isolates
            # per-child) before the error propagates.
            self.sink.close()

    def __enter__(self) -> "RunTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
