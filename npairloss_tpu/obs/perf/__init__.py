"""Perf observatory (docs/OBSERVABILITY.md §Perf observatory).

The device-trace-free performance attribution layer — built because
``jax.profiler`` device traces wedge the tunneled backend
(``scripts/profile_flagship.py``), so *where the step time goes* must
be recoverable from artifacts the host already has:

  * ``perf.costs`` — THE shared cost-analysis/MFU helper (every
    ``mfu`` number in the repo routes through here);
  * ``perf.hlo`` — per-``jax.named_scope``-region FLOPs / bytes /
    collective-bytes attribution parsed from compiled HLO text;
  * ``perf.roofline`` — chip peak specs + compute/memory/collective
    bound classification with arithmetic intensity;
  * ``perf.decompose`` — step-time and serve-latency decomposition
    from the obs.tracing span streams, wall-reconciled;
  * ``perf.report`` — the versioned ``prof`` report artifact
    (schema, validator, renderers).

All modules are stdlib-only; jax-free processes (bench.py's parent,
the profile orchestrator) load the ones they need by file path.
Entry points: ``python -m npairloss_tpu prof --step train|serve`` and
``scripts/bench_check.py``.
"""

from npairloss_tpu.obs.perf.costs import (
    PEAK_FLOPS,
    cost_analysis_dict,
    cost_bytes,
    cost_flops,
    mfu_from_timing,
    peak_flops,
)
from npairloss_tpu.obs.perf.decompose import (
    SERVE_CATEGORIES,
    STEP_CATEGORIES,
    decompose_step_time,
    serve_latency_decomposition,
)
from npairloss_tpu.obs.perf.hlo import (
    UNSCOPED,
    attribute_regions,
    region_of,
    stage_hlo_text,
)
from npairloss_tpu.obs.perf.report import (
    REPORT_SCHEMA,
    ablation_markdown,
    build_report,
    render_table,
    validate_report,
    write_report,
)
from npairloss_tpu.obs.perf.roofline import (
    BOUND_CLASSES,
    ChipSpec,
    chip_peaks,
    classify,
)

__all__ = [
    "PEAK_FLOPS",
    "cost_analysis_dict",
    "cost_bytes",
    "cost_flops",
    "mfu_from_timing",
    "peak_flops",
    "STEP_CATEGORIES",
    "SERVE_CATEGORIES",
    "decompose_step_time",
    "serve_latency_decomposition",
    "UNSCOPED",
    "attribute_regions",
    "region_of",
    "stage_hlo_text",
    "REPORT_SCHEMA",
    "ablation_markdown",
    "build_report",
    "render_table",
    "validate_report",
    "write_report",
    "BOUND_CLASSES",
    "ChipSpec",
    "chip_peaks",
    "classify",
]
