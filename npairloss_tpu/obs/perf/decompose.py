"""Step-time decomposition from the host span streams (obs.tracing).

The span tracer already records where the loop thread's wall clock goes
(``data/next_batch``, ``step/dispatch``, ``step/compile``, ``eval``,
``snapshot``, ``step/window_sync``, the ``serve/*`` request path).  This
module turns one run's Chrome-trace events into the per-category
breakdown the reports publish, with two hard rules:

  * **self-time attribution** — a nested span's time belongs to the
    DEEPEST span covering it (``eval`` containing ``eval/compile``
    must not double-count), computed per thread by timestamp
    containment, the same convention Perfetto renders;
  * **explicit reconciliation** — categorized time never silently
    absorbs the remainder: ``unattributed_ms`` is defined as
    ``wall_ms - sum(parts)`` so the invariant
    ``sum(parts) + unattributed == wall`` holds EXACTLY by
    construction, and a large unattributed share is itself a finding
    (host work between spans), not a rounding artifact.

Stdlib-only.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# span-name (longest prefix wins) -> step-time category.  The category
# vocabulary is part of the report schema (tests pin it).
SPAN_CATEGORIES = [
    ("data/next_batch", "data_wait"),
    ("pipeline/stage", "h2d"),
    ("comm/", "comms"),
    # comm/price is the fleet observatory's AOT collective-pricing
    # compile — obs overhead, NOT interconnect time; the longer prefix
    # outranks the comm/ rule above so seconds of XLA compile can't
    # masquerade as a comms share.
    ("comm/price", "compile"),
    ("step/compile", "compile"),
    ("eval/compile", "compile"),
    ("step/recompile", "compile"),
    ("step/dispatch", "dispatch"),
    ("step/device_wait", "device_compute"),
    ("step/window_sync", "window_sync"),
    ("eval", "eval"),
    ("snapshot", "snapshot"),
    ("serve/admit", "admit"),
    ("serve/batch", "batch"),
    ("serve/dispatch", "dispatch"),
    ("serve/encode", "encode"),
    ("serve/topk", "topk"),
    ("serve/warmup", "warmup"),
]

STEP_CATEGORIES = (
    "data_wait", "h2d", "comms", "compile", "dispatch", "device_compute",
    "window_sync", "eval", "snapshot", "other_span",
)

SERVE_CATEGORIES = ("admit", "batch", "dispatch", "encode", "topk")


def category_of(name: str) -> Optional[str]:
    """Longest-prefix category for a span name; None = unmapped (its
    time lands in ``other_span``, never dropped silently)."""
    best, best_len = None, -1
    for prefix, cat in SPAN_CATEGORIES:
        if name.startswith(prefix) and len(prefix) > best_len:
            best, best_len = cat, len(prefix)
    return best


def _complete_events(
    events: Sequence[Dict[str, Any]], tid: Optional[int]
) -> List[Dict[str, Any]]:
    out = [e for e in events
           if e.get("ph") == "X" and isinstance(e.get("dur"), (int, float))]
    if tid is not None:
        out = [e for e in out if e.get("tid") == tid]
    return out


def loop_thread(events: Sequence[Dict[str, Any]]) -> Optional[int]:
    """The tid owning the most step/data spans — the train-loop thread
    (staging/reader threads emit other names)."""
    counts: Dict[int, int] = {}
    for e in _complete_events(events, None):
        if str(e.get("name", "")).startswith(("step/", "data/")):
            counts[e.get("tid")] = counts.get(e.get("tid"), 0) + 1
    if not counts:
        return None
    return max(counts, key=counts.get)


def self_times(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-event self time (dur minus directly-nested children) for ONE
    thread's complete events, by timestamp containment."""
    evs = sorted(events, key=lambda e: (e["ts"], -e["dur"]))
    out = []
    stack: List[Dict[str, Any]] = []
    for e in evs:
        rec = {"name": e["name"], "ts": e["ts"], "dur": e["dur"],
               "self": float(e["dur"])}
        while stack and stack[-1]["ts"] + stack[-1]["dur"] <= e["ts"]:
            stack.pop()
        if stack and e["ts"] + e["dur"] <= stack[-1]["ts"] + stack[-1]["dur"]:
            stack[-1]["self"] -= float(e["dur"])
        stack.append(rec)
        out.append(rec)
    return out


def decompose_step_time(
    events: Sequence[Dict[str, Any]],
    wall_ms: float,
    tid: Optional[int] = None,
    serve: bool = False,
) -> Dict[str, Any]:
    """Span events + the measured wall interval -> the step-time
    breakdown ``{"parts": {category: ms}, "unattributed_ms", "wall_ms"}``
    with the exact reconciliation invariant.  ``tid`` defaults to the
    detected loop thread (other threads' spans OVERLAP the loop wall
    clock and must not be summed into it).  ``serve=True`` admits the
    serving stage categories (encode/batch/dispatch/topk/admit) as
    first-class parts — a serve-step decomposition that other_span'ed
    them would bury the entire measured loop in one opaque bucket."""
    if tid is None:
        tid = loop_thread(events)
    evs = _complete_events(events, tid)
    parts: Dict[str, float] = {}
    for rec in self_times(evs):
        cat = category_of(str(rec["name"])) or "other_span"
        if not serve and cat in SERVE_CATEGORIES \
                and cat not in STEP_CATEGORIES:
            cat = "other_span"
        parts[cat] = parts.get(cat, 0.0) + max(rec["self"], 0.0) / 1e3
    rounded = {k: round(v, 3) for k, v in sorted(parts.items())}
    wall_r = round(wall_ms, 3)
    return {
        "parts": rounded,
        # Defined as the remainder, so sum(parts) + unattributed ==
        # wall holds (to fp/rounding noise) by construction; a NEGATIVE
        # value means spans overran the measured wall interval.
        "unattributed_ms": round(wall_r - sum(rounded.values()), 3),
        "attributed_ms": round(sum(rounded.values()), 3),
        "wall_ms": wall_r,
    }


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (no numpy — this
    module stays stdlib-only)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(round(q / 100.0 * (len(sorted_vals) - 1))),
              len(sorted_vals) - 1)
    return sorted_vals[idx]


def serve_latency_decomposition(
    events: Sequence[Dict[str, Any]],
    since_us: float = 0.0,
) -> Dict[str, Dict[str, float]]:
    """p50/p99/count per serving stage (encode / batch / dispatch /
    topk / admit) from the ``serve/*`` spans — the per-request latency
    split the Gemma-serving comparison (PAPERS.md) uses to justify
    precision/layout work.  ``since_us`` restricts to spans that
    *ended* at or after the cursor (tracer-relative timestamps): a span
    straddling the window boundary counts in the window it finished in
    — filtering on start time would drop exactly the longest (tail)
    spans and bias p99 low."""
    durs: Dict[str, List[float]] = {}
    for e in _complete_events(events, None):
        if e["ts"] + e["dur"] < since_us:
            continue
        name = str(e.get("name", ""))
        if not name.startswith("serve/"):
            # A step/dispatch span also maps to "dispatch" — only the
            # serving path's own spans belong in this split.
            continue
        cat = category_of(name)
        if cat in SERVE_CATEGORIES:
            durs.setdefault(cat, []).append(float(e["dur"]) / 1e3)
    out = {}
    for cat, vals in sorted(durs.items()):
        vals.sort()
        out[cat] = {
            "p50_ms": round(_percentile(vals, 50), 3),
            "p99_ms": round(_percentile(vals, 99), 3),
            "count": len(vals),
        }
    return out
