"""Roofline model: chip peak specs + bound-class classification.

The TPU-v4 paper framing (PAPERS.md): every region of a step is limited
by whichever peak it saturates first — the MXU (compute), HBM
(memory), or the interconnect (collective).  Given a region's analytic
FLOPs / bytes-accessed / collective bytes (``obs.perf.hlo``), the
classification is mechanical:

    t_compute    = flops            / peak_flops
    t_memory     = bytes            / peak_hbm_bytes_per_s
    t_collective = collective_bytes / peak_ici_bytes_per_s
    bound        = argmax(t_*)
    est_s        = max(t_*)          # the roofline-optimal time

``arithmetic_intensity = flops / bytes`` against the ridge point
``peak_flops / peak_hbm`` tells the same story as a ratio: regions left
of the ridge cannot be fixed by more MXU utilization — only by moving
fewer bytes (fusion, bf16, layout).

Peak numbers are public per-chip specs.  HBM/ICI figures are
coarse (generation-level, not SKU-exact) — the CLASSIFICATION is the
product here, not a promise of achievable GB/s; ``known=False`` specs
(CPU, unknown kinds) fall back to the v4 reference roofline so reports
stay deterministic everywhere, with the fallback flagged in the
report.  Stdlib-only.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from npairloss_tpu.obs.perf.costs import PEAK_FLOPS

# Bound classes a region can carry (pinned by tests/test_perf.py; the
# report schema promises exactly these values).
BOUND_CLASSES = ("compute", "memory", "collective", "unknown")


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip peaks: dense bf16 FLOP/s, HBM bytes/s, interconnect
    bytes/s (aggregate per chip, coarse).  ``ici`` is the intra-slice
    chip fabric; ``dcn`` the per-host data-center network crossed by
    multi-host (multi-process) collectives — the TPU-v4 paper's point
    is that the two differ by ~an order of magnitude, so a fleet
    bandwidth check against the wrong one is off by that factor."""

    device_kind: str
    flops: float
    hbm_bytes_per_s: float
    ici_bytes_per_s: float
    dcn_bytes_per_s: float = 25.0e9
    known: bool = True

    @property
    def ridge_ai(self) -> float:
        """FLOPs/byte at which compute and memory time are equal."""
        return self.flops / self.hbm_bytes_per_s


# Interconnect link kinds a collective can ride (fleet comms rows carry
# one of these; pinned by tests).
LINK_KINDS = ("ici", "dcn")

# (device_kind substring, HBM GB/s, ICI GB/s, DCN GB/s per host) — peak
# FLOP/s rides costs.PEAK_FLOPS so the two tables can never disagree on
# a kind.  DCN figures are generation-coarse (~200 Gb/s-class NICs for
# v4+, less for earlier): like the HBM/ICI columns, the CLASSIFICATION
# is the product, not a promise of achievable GB/s.
_BW_SPECS = [
    ("v6", 1640.0, 448.0, 50.0),
    ("v5p", 2765.0, 450.0, 50.0),
    ("v5 lite", 819.0, 160.0, 25.0),
    ("v5e", 819.0, 160.0, 25.0),
    ("v4", 1228.0, 300.0, 25.0),
    ("v3", 900.0, 280.0, 12.5),
    ("v2", 700.0, 62.0, 12.5),
]

# Unknown kinds (CPU, test doubles) classify against the v4 reference
# roofline — deterministic output everywhere, flagged via known=False.
DEFAULT_SPEC = ChipSpec("unknown (v4 reference roofline)", 275e12,
                        1228e9, 300e9, 25e9, known=False)


def chip_peaks(device_kind: str) -> ChipSpec:
    """Resolve a device kind to its peak spec (first substring match),
    or the flagged v4-reference fallback."""
    kind = (device_kind or "").lower()
    flops = {k: f for k, f in PEAK_FLOPS}
    for key, hbm, ici, dcn in _BW_SPECS:
        if key in kind and key in flops:
            return ChipSpec(device_kind, flops[key], hbm * 1e9,
                            ici * 1e9, dcn * 1e9)
    return DEFAULT_SPEC


def interconnect_peak(spec: ChipSpec, link: str) -> float:
    """Peak bytes/s of the named link kind — the reference a fleet
    comms row's effective bandwidth is checked against."""
    if link not in LINK_KINDS:
        raise ValueError(f"link must be one of {LINK_KINDS}, got {link!r}")
    return spec.dcn_bytes_per_s if link == "dcn" else spec.ici_bytes_per_s


def classify(
    flops: float,
    bytes_accessed: float,
    collective_bytes: float = 0.0,
    spec: Optional[ChipSpec] = None,
) -> Dict[str, object]:
    """Roofline classification of one region; returns a dict with
    ``ai`` (flops/byte, None when bytes==0), ``bound`` (one of
    :data:`BOUND_CLASSES`), ``est_ms_at_roofline`` and the three time
    components (ms) behind the argmax.  A region with no cost at all
    classifies ``unknown``."""
    spec = spec if spec is not None else DEFAULT_SPEC
    t_c = max(flops, 0.0) / spec.flops
    t_m = max(bytes_accessed, 0.0) / spec.hbm_bytes_per_s
    t_i = max(collective_bytes, 0.0) / spec.ici_bytes_per_s
    times = {"compute": t_c, "memory": t_m, "collective": t_i}
    if t_c == t_m == t_i == 0.0:
        bound = "unknown"
    else:
        # Deterministic tie-break in BOUND_CLASSES order (compute wins
        # an exact compute/memory tie — it sits ON the ridge).
        bound = max(BOUND_CLASSES[:3], key=lambda k: times[k])
    ai = (flops / bytes_accessed) if bytes_accessed > 0 else None
    return {
        "ai": ai,
        "bound": bound,
        "est_ms_at_roofline": max(t_c, t_m, t_i) * 1e3,
        "compute_ms": t_c * 1e3,
        "memory_ms": t_m * 1e3,
        "collective_ms": t_i * 1e3,
    }
