"""The ONE cost-analysis / MFU helper (docs/OBSERVABILITY.md §Perf).

Before this module, four call sites computed XLA ``cost_analysis`` ->
FLOPs -> MFU independently (bench.py's headline and batch-scaling rows,
``cli.py cmd_time``, ``utils/profiling.cost_flops``), each handling the
list-vs-dict return shape and missing keys slightly differently.  This
is the single home now; ``utils.profiling`` re-exports the names so old
import paths keep working, and every producer of an ``mfu`` number in
this repo goes through :func:`mfu_from_timing`.

Stdlib-only: the "stage" arguments are duck-typed
``jax.stages.Lowered``/``Compiled`` objects (anything with a
``cost_analysis()`` method), so jax-free processes can load this module
by file path like ``obs.sinks``.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

log = logging.getLogger("npairloss_tpu.perf")

# Peak dense bf16 FLOP/s per chip by device_kind substring (public
# specs); used only for MFU / roofline estimates.  Ordered: first match
# wins, so the more specific keys come first.
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOP/s for a device kind, or None if unknown."""
    kind = device_kind.lower()
    for key, peak in PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def cost_analysis_dict(stage) -> Optional[Dict[str, float]]:
    """``stage.cost_analysis()`` normalized to one flat float dict.

    Accepts a ``jax.stages.Lowered`` (client-side analysis, no device
    compile — what the CLI ``time`` command uses so a tunneled backend
    is never asked to compile a second program) or a ``Compiled``.
    Handles the cross-version return shapes in ONE place: older jax
    returns ``[dict]`` from Compiled and ``dict`` from Lowered; missing
    keys and non-numeric values are dropped; any failure (backends
    without analysis, empty modules) degrades to None, never raises.
    """
    try:
        cost = stage.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: [dict]
            cost = cost[0] if cost else {}
        out = {}
        for k, v in dict(cost).items():
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                continue
        return out
    except Exception as e:  # noqa: BLE001 — analysis is best-effort
        log.debug("cost_analysis failed: %s", e)
        return None


def cost_flops(stage) -> Optional[float]:
    """XLA's analytic FLOPs for a lowered or compiled program, or None."""
    cost = cost_analysis_dict(stage)
    if cost is None:
        return None
    f = cost.get("flops", 0.0)
    return f if f > 0 else None


def cost_bytes(stage) -> Optional[float]:
    """XLA's analytic bytes-accessed estimate, or None."""
    cost = cost_analysis_dict(stage)
    if cost is None:
        return None
    b = cost.get("bytes accessed", 0.0)
    return b if b > 0 else None


def mfu_from_timing(
    stage=None,
    *,
    seconds: float,
    steps: int = 1,
    device_kind: str = "",
    flops: Optional[float] = None,
) -> Dict[str, Any]:
    """The one MFU computation: ``flops_per_step * steps / seconds``
    against the chip's peak.

    ``stage`` (lowered/compiled) supplies the per-step FLOPs unless
    ``flops`` is given explicitly; ``seconds`` is the wall time of
    ``steps`` steps.  Returns ``{"step_flops": float|None,
    "mfu": float|None}`` — keys are always present, values None when
    the estimate is unavailable (no cost analysis / unknown chip /
    non-positive timing), so call sites stay branch-free.
    """
    if flops is None and stage is not None:
        flops = cost_flops(stage)
    mfu = None
    peak = peak_flops(device_kind) if device_kind else None
    if flops and peak and seconds > 0 and steps > 0:
        mfu = (flops * steps / seconds) / peak
    return {"step_flops": flops, "mfu": mfu}
