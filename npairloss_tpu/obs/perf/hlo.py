"""Per-region cost attribution from HLO text (docs/OBSERVABILITY.md §Perf).

XLA's ``cost_analysis()`` prices a WHOLE program — one flops number, one
bytes number — which is how the repo got an MFU headline but no map of
where the 27.85 ms step goes.  The missing per-region view is
recoverable from the compiled module's own text: every HLO instruction
carries ``metadata={op_name="jit(step)/jit(main)/<scopes...>/<prim>"}``
where ``<scopes...>`` is the ``jax.named_scope`` / flax-module-path
stack (``utils/profiling.py`` annotates the loss stages; flax names the
trunk's blocks for free).  This module parses that text, prices each
instruction with an analytic cost model (the same flavor of estimate
``cost_analysis`` itself makes), and aggregates FLOPs / bytes-accessed /
collective bytes per region.

Honesty notes, also stamped into every report:

  * FLOPs are analytic (2MNK gemms, window*out convs, 1/elem
    elementwise) — the region SHARES are the product; absolute numbers
    reconcile against XLA's own total in the report (``coverage``).
  * bytes are operand+result sizes per instruction; instructions INSIDE
    a fusion contribute flops only, while the fusion call site
    contributes its operand/result bytes — i.e. bytes approximate
    post-fusion HBM traffic, not materialized intermediates.
  * ``while`` bodies (lax.scan) multiply by a best-effort trip count
    read off the loop condition; when that fails the body counts once
    and the region is flagged ``trip_count_unknown``.

Stdlib-only (text in, dicts out) — usable from jax-free processes.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# Region key for ops outside any named scope / module path.
UNSCOPED = "(unscoped)"

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1,
    "f8e5m2": 1, "f8e4m3b11fnuz": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "token": 0, "tuple": 0,
}

# Pure data movement / bookkeeping: no FLOPs (bytes still count).
_ZERO_FLOP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "broadcast", "reshape", "transpose", "copy",
    "copy-start", "copy-done", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "pad", "gather", "scatter",
    "iota", "convert", "reverse", "after-all", "rng-bit-generator",
    "rng", "partition-id", "replica-id", "custom-call", "infeed",
    "outfeed", "send", "recv", "send-done", "recv-done", "domain",
    "opt-barrier", "add-dependency",
})

# Bookkeeping ops that contribute NOTHING (not even bytes): they have
# no runtime cost of their own.
_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "after-all",
    "domain", "opt-barrier", "add-dependency",
})

_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-gather-start", "collective-permute-start",
})

_INSTR_RE = re.compile(
    # The type charset includes parens: TPU-optimized HLO stamps tiled
    # layouts like f32[8,16]{1,0:T(8,128)(2,1)} on non-tuple results,
    # and a charset without ( ) fails to match every such instruction —
    # invisible on CPU (no tiling), empty region tables on the chip.
    # Tuple types match LAZILY up to the ` opcode(` anchor (not
    # ``[^=]*?``): XLA comments element indices past 4 as /*index=5*/,
    # and an =-excluding charset fails on every 6+-element tuple — so
    # a ``while`` with a large carry (the ring engine's scan) never
    # parsed and its whole body went unwalked.
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\(.*?\)|[\w\[\]{},:#*\.()]+)\s+"
    r"(?P<opcode>[\w\-]+)\(",
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_METADATA_RE = re.compile(r'metadata=\{[^{}]*?op_name="([^"]*)"')
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)")
_COMP_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*)?\{\s*$")


@dataclasses.dataclass
class Instr:
    name: str
    opcode: str
    out_shapes: List[Tuple[str, Tuple[int, ...]]]
    operand_shapes: List[Tuple[str, Tuple[int, ...]]]
    operands_raw: str   # raw operand-list text (constant values live here)
    attrs: str          # raw text after the operand list
    op_name: str        # metadata op_name ("" when absent)
    called: List[str]   # computations referenced via calls/to_apply/...


def _shapes_in(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype not in _DTYPE_BYTES:
            # Layout/tiling artifacts like T(8,128) match the shape
            # regex; a real shape always leads with a known dtype.
            continue
        out.append(
            (dtype, tuple(int(d) for d in dims.split(",") if d != ""))
        )
    return out


def _elems(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _shape_bytes(shapes: List[Tuple[str, Tuple[int, ...]]]) -> float:
    return float(sum(
        _elems(dims) * _DTYPE_BYTES.get(dtype, 4) for dtype, dims in shapes
    ))


def _operand_section(line: str, start: int) -> Tuple[str, int]:
    """The operand list between the opcode's parens; paren matching
    ignores parens nested in layout braces (``{1,0:T(8,128)}``)."""
    depth, brace, i = 0, 0, start
    for i in range(start, len(line)):
        c = line[i]
        if c == "{":
            brace += 1
        elif c == "}":
            brace -= 1
        elif brace == 0 and c == "(":
            depth += 1
        elif brace == 0 and c == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i], i + 1
    return line[start + 1:], len(line)


def parse_hlo_computations(text: str) -> Tuple[str, Dict[str, List[Instr]]]:
    """HLO module text -> (entry_name, {computation: [Instr, ...]})."""
    comps: Dict[str, List[Instr]] = {}
    entry = ""
    current: Optional[str] = None
    for line in text.splitlines():
        stripped = line.strip()
        if current is None:
            m = _COMP_HEADER_RE.match(stripped)
            if m and not stripped.startswith("HloModule"):
                current = m.group(2)
                comps[current] = []
                if m.group(1):
                    entry = current
            continue
        if stripped == "}":
            current = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        opcode = m.group("opcode")
        operands, rest = _operand_section(line, line.find("(", m.end() - 1))
        attrs = line[rest:]
        meta = _METADATA_RE.search(attrs)
        comps[current].append(Instr(
            name=m.group("name"),
            opcode=opcode,
            out_shapes=_shapes_in(m.group("type")),
            operand_shapes=_shapes_in(operands),
            operands_raw=operands,
            attrs=attrs,
            op_name=meta.group(1) if meta else "",
            called=_CALLED_RE.findall(attrs),
        ))
    if not entry and comps:
        entry = next(iter(comps))
    return entry, comps


# -- op_name -> region --------------------------------------------------------

def _split_scopes(op_name: str) -> List[str]:
    """Split an op_name path on depth-0 slashes (scope names like
    ``npair/sim`` appear INSIDE ``jvp(...)`` wrappers, where the slash
    must not split the wrapper)."""
    parts, depth, cur = [], 0, []
    for c in op_name:
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        if c == "/" and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    if cur:
        parts.append("".join(cur))
    return parts


_WRAPPER_RE = re.compile(r"^(jit|jvp|vjp|transpose|vmap|pmap|remat|"
                         r"custom_jvp|custom_vjp|checkpoint)\((.*)\)$")


def _unwrap(segment: str) -> str:
    """Peel tracer wrappers: ``transpose(jvp(GoogLeNet))`` ->
    ``GoogLeNet`` (forward and backward of a scope attribute to the
    same region — the roofline doesn't care which direction moved the
    bytes)."""
    while True:
        m = _WRAPPER_RE.match(segment)
        if not m:
            return segment
        segment = m.group(2)


def region_of(op_name: str, depth: int = 2) -> str:
    """``jit(step)/jit(main)/jvp(npair/sim)/dot_general`` ->
    ``npair/sim``; the trailing primitive name drops, wrappers unwrap,
    ``jit(main)``/outer-jit segments and empty leftovers vanish, and
    the result truncates to ``depth`` path segments (0 = unlimited)."""
    raw = _split_scopes(op_name)
    if not raw:
        return UNSCOPED
    segs: List[str] = []
    # Control-flow structure segments (lax.scan/while/cond lowering)
    # carry no attribution information — without this filter every
    # scan body collapses into one "while/body" region and the REAL
    # scopes inside it vanish past the depth cut.
    structural = ("main", "while", "body", "cond", "branch")
    for seg in raw[:-1]:  # the last segment is the primitive name
        seg = _unwrap(seg)
        if not seg or seg in structural or seg.startswith("_"):
            continue
        segs.extend(s for s in seg.split("/") if s)
    # The outermost segment is the jitted function's own name (step,
    # train_step, f) — every op shares it, so it carries no contrast.
    if len(segs) > 1:
        segs = segs[1:]
    elif segs and raw[0].startswith("jit("):
        segs = []
    if not segs:
        return UNSCOPED
    if depth and depth > 0:
        segs = segs[:depth]
    return "/".join(segs)


# -- per-instruction cost model ----------------------------------------------

def _dims_attr(attrs: str, key: str) -> List[int]:
    m = re.search(key + r"=\{([\d,]*)\}", attrs)
    if not m:
        return []
    return [int(d) for d in m.group(1).split(",") if d != ""]


def _instr_flops(instr: Instr) -> float:
    op = instr.opcode
    out_elems = sum(_elems(dims) for _, dims in instr.out_shapes)
    if op in _ZERO_FLOP_OPS:
        return 0.0
    if op == "dot":
        # 2 * output elems * contracted extent (batch dims are part of
        # the output, so this is the full 2MNK including batching).
        if not instr.operand_shapes:
            return 0.0
        lhs = instr.operand_shapes[0][1]
        contract = 1
        for d in _dims_attr(instr.attrs, "lhs_contracting_dims"):
            if d < len(lhs):
                contract *= lhs[d]
        return 2.0 * out_elems * contract
    if op == "convolution":
        # 2 * output elems * (kernel elems / output features): each
        # output element is a dot over spatial-window x input-features.
        if len(instr.operand_shapes) < 2:
            return 0.0
        kshape = instr.operand_shapes[1][1]
        kelems = _elems(kshape)
        m = re.search(r"dim_labels=\w+_(\w+)->", instr.attrs)
        out_feat = 1
        if m and "o" in m.group(1):
            o_idx = m.group(1).index("o")
            if o_idx < len(kshape):
                out_feat = kshape[o_idx]
        return 2.0 * out_elems * (kelems / max(out_feat, 1))
    if op in ("reduce", "reduce-precision"):
        return float(sum(
            _elems(dims) for _, dims in instr.operand_shapes[:1]))
    if op in ("reduce-window", "select-and-scatter"):
        m = re.search(r"size=([\dx]+)", instr.attrs)
        window = 1
        if m:
            for d in m.group(1).split("x"):
                window *= int(d)
        return float(out_elems * window)
    if op in ("sort", "top-k"):
        # O(n log n)-ish; count the comparisons linearly — sort cost is
        # dwarfed by gemms in every program this repo builds.
        return float(sum(_elems(dims) for _, dims in instr.operand_shapes))
    # Elementwise / everything else: one op per output element.
    return float(out_elems)


def _instr_bytes(instr: Instr) -> float:
    return _shape_bytes(instr.operand_shapes) + _shape_bytes(
        instr.out_shapes)


_KNOWN_TRIP_RE = re.compile(r'"known_trip_count":\s*\{"n":"(\d+)"\}')
_CONDITION_RE = re.compile(r"condition=%?([\w.\-]+)")


def _while_trip_count(
    instr: Instr, comps: Dict[str, List[Instr]]
) -> Optional[int]:
    """Trip count of a ``while`` op: XLA's own
    ``backend_config={"known_trip_count":{"n":...}}`` when present,
    else the condition-compare heuristic.  The condition computation is
    found by its ``condition=`` attribute, NOT by position — HLO prints
    ``condition=`` before ``body=``, so ``called[0]`` is the condition
    (assuming body-first silently killed every trip count and scan
    regions undercounted by the trip factor)."""
    m = _KNOWN_TRIP_RE.search(instr.attrs)
    if m:
        n = int(m.group(1))
        return n if n > 0 else None
    m = _CONDITION_RE.search(instr.attrs)
    cond = comps.get(m.group(1), []) if m else []
    return _trip_count(cond)


def _trip_count(cond: List[Instr]) -> Optional[int]:
    """Best-effort lax.scan/while trip count off the loop condition:
    a ``compare(iv, constant(N)), direction=LT`` pattern."""
    consts = {}
    for ins in cond:
        if ins.opcode == "constant":
            m = re.fullmatch(r"\s*(-?\d+)\s*", ins.operands_raw)
            if m:
                consts[ins.name] = int(m.group(1))
    for ins in cond:
        if ins.opcode == "compare" and "direction=LT" in ins.attrs:
            if consts:
                n = max(consts.values())
                return n if n > 0 else None
    return None


# -- aggregation --------------------------------------------------------------

def attribute_regions(
    hlo_text: str, depth: int = 2
) -> Dict[str, Dict[str, float]]:
    """HLO module text -> ``{region: {"flops", "bytes",
    "collective_bytes", "ops"}}`` plus a ``"_notes"`` key listing
    attribution caveats hit (unknown trip counts etc.)."""
    entry, comps = parse_hlo_computations(hlo_text)
    regions: Dict[str, Dict[str, float]] = {}
    notes: List[str] = []
    unknown_trips: Dict[str, int] = {}

    def bucket(region: str) -> Dict[str, float]:
        return regions.setdefault(region, {
            "flops": 0.0, "bytes": 0.0, "collective_bytes": 0.0,
            "ops": 0.0,
        })

    def walk(comp_name: str, mult: float, count_bytes: bool,
             seen: Tuple[str, ...]) -> None:
        if comp_name not in comps or comp_name in seen:
            return
        for instr in comps[comp_name]:
            if instr.opcode in _SKIP_OPS:
                continue
            region = region_of(instr.op_name, depth)
            if instr.opcode == "fusion":
                # The fusion call site IS the memory traffic (operands
                # + result); the fused instructions carry the flops.
                if count_bytes:
                    bucket(region)["bytes"] += _instr_bytes(instr) * mult
                for callee in instr.called:
                    walk(callee, mult, False, seen + (comp_name,))
                continue
            if instr.opcode == "call":
                for callee in instr.called:
                    walk(callee, mult, count_bytes, seen + (comp_name,))
                continue
            if instr.opcode == "while":
                trip = _while_trip_count(instr, comps)
                if trip is None:
                    trip = 1
                    unknown_trips.setdefault(region, 0)
                    unknown_trips[region] += 1
                for callee in instr.called:
                    walk(callee, mult * trip, count_bytes,
                         seen + (comp_name,))
                continue
            if instr.opcode == "conditional":
                for callee in instr.called:
                    walk(callee, mult, count_bytes, seen + (comp_name,))
                continue
            b = bucket(region)
            b["ops"] += mult
            b["flops"] += _instr_flops(instr) * mult
            if count_bytes:
                b["bytes"] += _instr_bytes(instr) * mult
            if instr.opcode in _COLLECTIVE_OPS:
                b["collective_bytes"] += _shape_bytes(
                    instr.out_shapes) * mult

    walk(entry, 1.0, True, ())
    if unknown_trips:
        detail = ", ".join(f"{reg} x{n}" for reg, n
                           in sorted(unknown_trips.items()))
        notes.append(
            f"trip_count_unknown: {sum(unknown_trips.values())} while "
            f"body(ies) counted once ({detail}) — their regions' flops "
            "are lower bounds")
    if notes:
        regions["_notes"] = notes  # type: ignore[assignment]
    return regions


def collective_bytes_by_opcode(
    hlo_text: str,
) -> Dict[str, Dict[str, object]]:
    """Per-collective-opcode wire accounting for the fleet comms join
    (obs.fleet.comms): ``{opcode: {"bytes", "count", "regions":
    {full_scope_path: bytes}}}`` with ``while`` bodies multiplied by
    their trip count exactly like :func:`attribute_regions`.

    Bytes are the OUTPUT shape of each collective (the convention
    ``attribute_regions`` prices ``collective_bytes`` with), so the two
    views reconcile by construction.  Regions here are FULL scope paths
    (``region_of(..., depth=0)``): the comm attribution needs to see
    the ``comm/<kind>`` scope markers wherever they sit in the stack,
    which a report-depth truncation would cut off.
    """
    entry, comps = parse_hlo_computations(hlo_text)
    out: Dict[str, Dict[str, object]] = {}

    def account(instr: Instr, mult: float) -> None:
        b = _shape_bytes(instr.out_shapes) * mult
        row = out.setdefault(instr.opcode, {
            "bytes": 0.0, "count": 0.0, "regions": {},
        })
        row["bytes"] += b
        row["count"] += mult
        region = region_of(instr.op_name, depth=0)
        row["regions"][region] = row["regions"].get(region, 0.0) + b

    def walk(comp_name: str, mult: float, seen: Tuple[str, ...]) -> None:
        if comp_name not in comps or comp_name in seen:
            return
        for instr in comps[comp_name]:
            if instr.opcode in _COLLECTIVE_OPS:
                account(instr, mult)
                continue
            if instr.opcode == "while":
                trip = _while_trip_count(instr, comps) or 1
                for callee in instr.called:
                    walk(callee, mult * trip, seen + (comp_name,))
                continue
            if instr.called:
                # fusion/call/conditional/map bodies can all contain
                # collectives after SPMD partitioning; count each body
                # once at the caller's multiplicity.
                for callee in instr.called:
                    walk(callee, mult, seen + (comp_name,))
    walk(entry, 1.0, ())
    return out


def stage_hlo_text(stage) -> str:
    """Optimized HLO text (with op_name metadata) for a jax Lowered or
    Compiled stage.  A Lowered's ``as_text()`` is StableHLO (no HLO
    metadata), so it compiles first — callers on tunneled backends
    should pass an already-Compiled stage."""
    txt = None
    if hasattr(stage, "as_text"):
        try:
            txt = stage.as_text()
        except Exception:  # noqa: BLE001 — fall through to compile
            txt = None
    if txt and txt.lstrip().startswith("HloModule"):
        return txt
    if hasattr(stage, "compile"):
        return stage.compile().as_text()
    raise TypeError(
        f"cannot extract HLO text from {type(stage).__name__}"
    )
