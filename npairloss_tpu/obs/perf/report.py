"""Perf-report artifact: schema, builder, validator, renderers.

One on-disk artifact per ``prof`` run (JSON + human table): per-region
FLOPs / bytes / arithmetic intensity / bound class / share-of-step /
est-ms-at-roofline from the static HLO attribution, plus the dynamic
step-time decomposition reconciled against wall time.  The JSON schema
is versioned and pinned by tests — downstream tooling (bench gates,
the next perf PR's before/after diffs) may rely on every key listed in
:func:`validate_report`.

Intra-package imports are lazy where jax-free file-path loaders need a
function (``scripts/profile_flagship.py`` loads this module standalone
for :func:`ablation_markdown`, the same trick bench.py uses on
``obs.sinks``).  Stdlib-only either way.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

REPORT_SCHEMA = "npairloss-perf-report-v1"

# Keys every region row carries (pinned by tests/test_perf.py).
REGION_KEYS = (
    "region", "flops", "bytes", "collective_bytes", "ai", "bound",
    "pct_flops", "est_ms_at_roofline",
)


def build_report(
    *,
    step: str,
    device_kind: str,
    batch: Optional[int] = None,
    hlo_text: Optional[str] = None,
    stage=None,
    span_events: Optional[Sequence[Dict[str, Any]]] = None,
    wall_ms: Optional[float] = None,
    steps: Optional[int] = None,
    ms_per_step: Optional[float] = None,
    serve_spans: bool = False,
    region_depth: int = 2,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble one report dict from whatever layers are available:
    static attribution (``hlo_text`` or a lowered/compiled ``stage``),
    dynamic decomposition (``span_events`` + ``wall_ms``), and timing
    (``ms_per_step`` for the MFU line).  Layers degrade independently —
    a report with only one layer is still schema-valid."""
    from npairloss_tpu.obs.perf import costs, decompose, hlo, roofline

    spec = roofline.chip_peaks(device_kind)
    report: Dict[str, Any] = {
        "schema": REPORT_SCHEMA,
        "step": step,
        "device_kind": device_kind,
        "batch": batch,
        "peaks": {
            "device": spec.device_kind,
            "flops": spec.flops,
            "hbm_bytes_per_s": spec.hbm_bytes_per_s,
            "ici_bytes_per_s": spec.ici_bytes_per_s,
            "ridge_ai": round(spec.ridge_ai, 2),
            "known": spec.known,
        },
        "regions": [],
        "totals": {},
        "notes": [],
    }
    if extra:
        report.update(extra)

    if stage is not None and hlo_text is None:
        hlo_text = hlo.stage_hlo_text(stage)
    if stage is not None:
        cost = costs.cost_analysis_dict(stage)
        if cost:
            report["totals"]["flops_xla"] = cost.get("flops")
            report["totals"]["bytes_xla"] = cost.get("bytes accessed")

    if hlo_text is not None:
        regions = hlo.attribute_regions(hlo_text, depth=region_depth)
        notes = regions.pop("_notes", [])
        report["notes"].extend(notes)
        total_flops = sum(r["flops"] for r in regions.values()) or 1.0
        total_bytes = sum(r["bytes"] for r in regions.values())
        total_coll = sum(r["collective_bytes"] for r in regions.values())
        rows: List[Dict[str, Any]] = []
        for name, r in regions.items():
            cls = roofline.classify(
                r["flops"], r["bytes"], r["collective_bytes"], spec)
            rows.append({
                "region": name,
                "flops": r["flops"],
                "bytes": r["bytes"],
                "collective_bytes": r["collective_bytes"],
                "ops": int(r["ops"]),
                "ai": (round(cls["ai"], 3)
                       if cls["ai"] is not None else None),
                "bound": cls["bound"],
                "pct_flops": round(100.0 * r["flops"] / total_flops, 2),
                "est_ms_at_roofline": round(cls["est_ms_at_roofline"], 4),
            })
        rows.sort(key=lambda r: -r["flops"])
        report["regions"] = rows
        report["totals"].update(
            flops_attributed=sum(r["flops"] for r in rows),
            bytes_attributed=total_bytes,
            collective_bytes_attributed=total_coll,
        )
        fx = report["totals"].get("flops_xla")
        if fx:
            report["totals"]["flops_coverage"] = round(
                report["totals"]["flops_attributed"] / fx, 4)

    if ms_per_step is not None:
        report["timing"] = {
            "ms_per_step": round(ms_per_step, 4),
            "steps": steps,
        }
        est = costs.mfu_from_timing(
            seconds=ms_per_step * 1e-3, steps=1, device_kind=device_kind,
            flops=report["totals"].get("flops_xla")
            or report["totals"].get("flops_attributed"),
        )
        if est["mfu"] is not None:
            report["timing"]["mfu"] = round(est["mfu"], 4)
        if batch:
            report["timing"]["emb_per_sec"] = round(
                batch / (ms_per_step * 1e-3), 1)

    if span_events is not None and wall_ms is not None:
        report["decomposition"] = decompose.decompose_step_time(
            span_events, wall_ms, serve=(step == "serve"))
    if span_events is not None and serve_spans:
        report["serve_latency"] = decompose.serve_latency_decomposition(
            span_events)
    return report


def validate_report(obj: Any) -> Optional[str]:
    """Schema check; returns an error string or None.  This IS the
    schema contract: tests and the ci.sh prof smoke call exactly this."""
    from npairloss_tpu.obs.perf.roofline import BOUND_CLASSES

    if not isinstance(obj, dict):
        return "report must be a JSON object"
    if obj.get("schema") != REPORT_SCHEMA:
        return f"schema must be {REPORT_SCHEMA!r}, got {obj.get('schema')!r}"
    if obj.get("step") not in ("train", "serve"):
        return f"step must be train|serve, got {obj.get('step')!r}"
    if not isinstance(obj.get("regions"), list):
        return "missing regions list"
    for i, row in enumerate(obj["regions"]):
        for key in REGION_KEYS:
            if key not in row:
                return f"region {i} missing {key!r}"
        if row["bound"] not in BOUND_CLASSES:
            return (f"region {i} bound {row['bound']!r} not in "
                    f"{BOUND_CLASSES}")
        if row["ai"] is not None and not isinstance(
                row["ai"], (int, float)):
            return f"region {i} ai is not numeric"
    dec = obj.get("decomposition")
    if dec is not None:
        for key in ("parts", "unattributed_ms", "wall_ms"):
            if key not in dec:
                return f"decomposition missing {key!r}"
        gap = (sum(dec["parts"].values()) + dec["unattributed_ms"]
               - dec["wall_ms"])
        if abs(gap) > 0.01:
            return (f"decomposition does not reconcile: parts + "
                    f"unattributed - wall = {gap:.4f} ms")
    return None


def render_table(report: Dict[str, Any]) -> str:
    """The human-readable counterpart of the JSON: region table +
    decomposition + timing, plain text."""
    lines = [
        f"perf report [{report['step']}] on {report['device_kind']!r}"
        + (f" batch={report['batch']}" if report.get("batch") else ""),
    ]
    peaks = report.get("peaks", {})
    if peaks:
        lines.append(
            f"roofline: peak {peaks['flops'] / 1e12:.0f} TF/s, HBM "
            f"{peaks['hbm_bytes_per_s'] / 1e9:.0f} GB/s, ridge AI "
            f"{peaks['ridge_ai']}"
            + ("" if peaks.get("known") else "  [fallback spec]"))
    t = report.get("timing")
    if t:
        lines.append(
            "timing: "
            + " ".join(f"{k}={v}" for k, v in sorted(t.items())))
    if report.get("regions"):
        lines.append("")
        hdr = (f"{'region':34s} {'flops':>12s} {'bytes':>12s} "
               f"{'AI':>8s} {'bound':>10s} {'%flops':>7s} "
               f"{'roofline_ms':>11s}")
        lines += [hdr, "-" * len(hdr)]
        for r in report["regions"]:
            ai = f"{r['ai']:.1f}" if r["ai"] is not None else "-"
            lines.append(
                f"{r['region'][:34]:34s} {r['flops']:12.3e} "
                f"{r['bytes']:12.3e} {ai:>8s} {r['bound']:>10s} "
                f"{r['pct_flops']:7.2f} {r['est_ms_at_roofline']:11.4f}")
    dec = report.get("decomposition")
    if dec:
        lines += ["", f"step-time decomposition (wall "
                  f"{dec['wall_ms']:.1f} ms):"]
        for cat, ms in dec["parts"].items():
            lines.append(f"  {cat:16s} {ms:10.3f} ms")
        lines.append(f"  {'unattributed':16s} "
                     f"{dec['unattributed_ms']:10.3f} ms")
    sl = report.get("serve_latency")
    if sl:
        lines += ["", "serve latency split (per span):"]
        for cat, row in sl.items():
            lines.append(
                f"  {cat:10s} p50={row['p50_ms']:8.3f} ms  "
                f"p99={row['p99_ms']:8.3f} ms  n={row['count']}")
    for note in report.get("notes", []):
        lines.append(f"note: {note}")
    return "\n".join(lines) + "\n"


def write_json_txt(report: Dict[str, Any], out_dir: str, name: str,
                   renderer) -> Dict[str, str]:
    """The one report-artifact writer: ``<out_dir>/<name>.json`` +
    ``.txt`` (atomic tmp+rename), the ``.txt`` rendered by
    ``renderer(report)``.  Shared by the perf report and the fleet
    report (obs.fleet.aggregate) so every versioned artifact lands the
    same way; returns the paths."""
    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    for ext, payload in (
        ("json", json.dumps(report, indent=1, default=str) + "\n"),
        ("txt", renderer(report)),
    ):
        path = os.path.join(out_dir, f"{name}.{ext}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
        paths[ext] = path
    return paths


def write_report(report: Dict[str, Any], out_dir: str,
                 name: str = "perf_report") -> Dict[str, str]:
    """Write ``<out_dir>/<name>.json`` + ``.txt`` (atomic tmp+rename);
    returns the paths."""
    return write_json_txt(report, out_dir, name, render_table)


# -- differential-ablation rendering (scripts/profile_flagship.py) -----------

def ablation_markdown(payload: Dict[str, Any]) -> str:
    """profile/flagship.md from the ablation artifact
    (profile/flagship.json) — the renderer scripts/profile_flagship.py
    used to hand-roll, now shared so the ablation view and the prof
    reports evolve together.  Self-contained (no intra-package
    imports): the orchestrator parent loads this module by file path
    from a jax-free process."""
    r = {k: v["ms_per_step"] for k, v in payload["results"].items()
         if "ms_per_step" in v}
    full = r.get("full", 0.0)

    def pct(ms):
        return (f"{ms:.1f} ms ({100 * ms / full:.0f}%)" if full
                else f"{ms:.1f} ms")

    def _table_lines(results):
        out = ["| variant | ms/step | emb/s |", "|---|---|---|"]
        for k, v in results.items():
            if "ms_per_step" in v:
                out.append(
                    f"| {k} | {v['ms_per_step']} | {v['emb_per_sec']} |")
            else:
                out.append(f"| {k} | ERROR: {v.get('error', '?')} | — |")
        if len(out) == 2:
            out.append("| (no measurements yet — re-run pending) | — | — |")
        return out

    lines = [
        "# Flagship step profile (differential)",
        "",
        f"Device: `{payload['device']}` — GoogLeNet bf16 + mined N-pair "
        f"loss (def.prototxt config) + analytic VJP + Caffe-SGD, batch "
        f"{payload['batch']} @ {payload['image']}x{payload['image']}.",
        "",
        "`jax.profiler` traces wedge the tunneled backend, so attribution",
        "is by ablation (scripts/profile_flagship.py): each variant is",
        f"{payload['steps_per_timing']} perturbed steps inside one jitted",
        "lax.scan, host-fetch synced, dispatch floor",
        f"({payload['fetch_floor_ms']} ms) subtracted.  The STATIC "
        "counterpart",
        "(per-region FLOPs/bytes/roofline, no timing needed) is",
        "`python -m npairloss_tpu prof --step train` — "
        "docs/OBSERVABILITY.md.",
        "",
    ]
    lines += _table_lines(payload["results"])
    lines += ["", "## Attribution", ""]
    if all(k in r for k in ("full", "fwd_only", "fwd_bwd", "npair_only")):
        lines += [
            f"- model forward: {pct(r['fwd_only'])}",
            f"- model backward + update: "
            f"{pct(max(r['fwd_bwd'] - r['fwd_only'], 0.0))}",
            f"- N-pair loss machinery (mining + custom VJP): "
            f"{pct(r['npair_only'])} standalone; in-graph cost "
            f"{pct(max(r['full'] - r['fwd_bwd'], 0.0))}",
        ]
    if "no_lrn" in r and full:
        lines.append(
            f"- LRN (both layers): {pct(max(full - r['no_lrn'], 0.0))} — "
            "VPU-bound across-channel window"
        )
    if "fp32" in r and full:
        lines.append(
            f"- bf16 vs fp32 activations: fp32 costs "
            f"{pct(max(r['fp32'] - full, 0.0))} extra"
        )
    if "bn" in r and full:
        lines.append(
            f"- Inception-BN trunk (BN instead of LRN): {pct(r['bn'])} "
            "total"
        )
    for run in payload.get("prior_runs", []):
        lines += [
            "",
            f"## Prior measurements ({run.get('date', '?')})",
            "",
            run.get("note", ""),
            "",
        ]
        lines += _table_lines(run.get("results", {}))
    lines.append("")
    return "\n".join(lines)
