"""LiveObservatory — the one object the CLI wires in.

Bundles the four moving parts (registry + sink adapter, SLO evaluator,
alert engine, probes) behind two entry points:

  * ``sink`` goes into ``RunTelemetry(extra_sinks=...)`` — the existing
    Solver / RetrievalServer rows then feed the registry with zero new
    call sites;
  * ``tick()`` evaluates every SLO and advances the alert lifecycle —
    called by the background thread (``start()``/``stop()``) in live
    processes, or directly with an injected ``now`` by the offline
    ``watch`` feed and by tests (deterministic by construction).

``probes`` cover the few signals that are not metric rows (freshness
ages, snapshot age): each probe is a callable run at the top of every
tick that sets gauges directly — polling state the process already
holds, not new instrumentation.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from npairloss_tpu.obs.live.alerts import AlertEngine
from npairloss_tpu.obs.live.registry import MetricRegistry, RegistrySink
from npairloss_tpu.obs.live.slo import SLOEvaluator, SLOSpec

log = logging.getLogger("npairloss_tpu.obs.live")

ALERTS_FILENAME = "alerts.jsonl"


class LiveObservatory:
    """Registry + sink + SLO evaluator + alert engine + probe loop.

    ``out_dir`` lands ``alerts.jsonl`` there (None = in-memory only);
    ``min_ticks`` is the alert engine's debounce.  Start the background
    evaluator with ``start(period_s)``; ``stop()`` runs one final tick
    first so an alert state that changed right before shutdown still
    reaches the log (the drain contract), then closes the log file.
    """

    def __init__(
        self,
        specs: Sequence[SLOSpec],
        out_dir: Optional[str] = None,
        min_ticks: int = 1,
        clock=time.time,
    ):
        self.registry = MetricRegistry()
        self.sink = RegistrySink(self.registry)
        self.evaluator = SLOEvaluator(specs, self.registry)
        self.alerts_path = (
            os.path.join(os.path.abspath(out_dir), ALERTS_FILENAME)
            if out_dir else None)
        self.alerts = AlertEngine(self.alerts_path, min_ticks=min_ticks,
                                  clock=clock)
        self.probes: List[Callable[[], None]] = []
        self.listeners: List[Callable[[List[Any]], None]] = []
        # Optional RemediationEngine (resilience/remediate.py): ticked
        # AFTER the alert update with the SAME now, so actuation and
        # the pager can never disagree about the alert state.  Duck-
        # typed on purpose — this package stays stdlib-only/jax-free.
        self.remediation = None
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_probe(self, fn: Callable[[], None]) -> None:
        """Register a per-tick gauge setter (freshness ages etc.); a
        probe raising is logged once per tick, never fatal."""
        self.probes.append(fn)

    def add_listener(self, fn: Callable[[List[Any]], None]) -> None:
        """Register a per-tick consumer of the COMMITTED SLO statuses —
        the actuation hook (serve admission control sheds load on burn
        through exactly this stream, so actuators and the pager can
        never disagree about the burn state).  A listener raising is
        logged, never fatal."""
        self.listeners.append(fn)

    def set_remediation(self, engine) -> None:
        """Attach the alert→actuation engine: its ``tick(active, now)``
        runs after every alert update (actions run on the evaluator
        thread — a slow action pauses evaluation, bounded by the action
        itself), and ``stop()`` closes its audit log."""
        self.remediation = engine

    # -- evaluation --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Probes -> SLO evaluation -> alert lifecycle; returns the
        alert events this tick emitted."""
        for probe in self.probes:
            try:
                probe()
            except Exception as e:  # noqa: BLE001 — probes are best-effort
                log.warning("live-obs probe failed: %s", e)
        now = self._clock() if now is None else float(now)
        statuses = self.evaluator.evaluate(now)
        events = self.alerts.update(statuses, now)
        for ev in events:
            log.warning("ALERT %s: %s", ev["state"], ev["message"])
        for fn in self.listeners:
            try:
                fn(statuses)
            except Exception as e:  # noqa: BLE001 — actuation best-effort
                log.error("live-obs listener failed: %s", e)
        if self.remediation is not None:
            try:
                self.remediation.tick(self.alerts.active(), now)
            except Exception as e:  # noqa: BLE001 — must not kill the tick
                log.error("remediation tick failed: %s", e)
        return events

    def health(self) -> Dict[str, Any]:
        """The /healthz enrichment: per-SLO status + active alerts."""
        active = self.alerts.active()
        return {
            "slo": self.evaluator.status_dict(self._clock()),
            "alerts_active": len(active),
            "alerts": active,
        }

    # -- background loop ---------------------------------------------------

    def start(self, period_s: float = 1.0) -> "LiveObservatory":
        if self._thread is None:
            self._stop.clear()

            def loop():
                while not self._stop.wait(period_s):
                    try:
                        self.tick()
                    except Exception as e:  # noqa: BLE001 — keep ticking
                        log.error("live-obs tick failed: %s", e)

            self._thread = threading.Thread(
                target=loop, name="live-obs-evaluator", daemon=True)
            self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if final_tick:
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001
                log.error("live-obs final tick failed: %s", e)
        self.alerts.close()
        if self.remediation is not None:
            try:
                self.remediation.close()
            except Exception as e:  # noqa: BLE001
                log.error("remediation close failed: %s", e)
