"""Surfaces: Prometheus text exposition + the localhost HTTP exporter.

``prometheus_text`` renders a registry snapshot in the Prometheus
text-based exposition format (version 0.0.4 — the format every scraper
accepts): counters as ``<name>_total``, gauges plain, histograms as
cumulative ``_bucket{le=...}`` series with ``_sum``/``_count``.  The
serve front end mounts it at ``GET /metrics`` on its EXISTING HTTP
server (serve/server.py); the train side gets its own opt-in localhost
port via :func:`start_http_exporter` (CLI ``--metrics-port``) because
training has no HTTP surface otherwise.

Stdlib-only, like the whole package.
"""

from __future__ import annotations

import json
import logging
import math
import threading
from typing import Any, Callable, Dict, Optional

log = logging.getLogger("npairloss_tpu.obs.live")

PROM_PREFIX = "npairloss_"


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_"
                  for ch in name)
    if out and out[0].isdigit():
        out = "_" + out
    return PROM_PREFIX + out


def _fmt(v: float) -> str:
    """Prometheus sample values: shortest exact-ish float repr; +Inf
    spelled the Prometheus way."""
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def prometheus_text(registry) -> str:
    """Render every metric in the exposition format, sorted by name so
    scrapes (and tests) are deterministic.

    Labeled registry keys (``serve_rows{tenant="a"}`` — see
    ``registry.labeled_name``) render as real Prometheus labels, with
    the family's ``# TYPE`` header emitted once across all label sets.
    A registry with no labeled series renders byte-identically to the
    pre-label format."""
    from npairloss_tpu.obs.live.registry import split_labels

    lines = []
    snap = registry.snapshot()
    entries = sorted(
        (split_labels(key) + (key,)) for key in snap)
    typed = set()
    for base, labels, key in entries:
        m = snap[key]
        pname = _prom_name(base)
        kind = m["kind"]
        lab = "{" + labels + "}" if labels else ""
        if kind == "counter":
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total{lab} {_fmt(m['value'])}")
        elif kind == "gauge":
            if m["value"] is None:
                continue  # a gauge never set exposes nothing
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname}{lab} {_fmt(m['value'])}")
        else:
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} histogram")
            # ``le`` composes with (goes after) the series labels.
            pre = labels + "," if labels else ""
            cum = m["cumulative_counts"]
            for bound, count in zip(m["bounds"], cum):
                lines.append(
                    f'{pname}_bucket{{{pre}le="{_fmt(bound)}"}} {count}')
            lines.append(f'{pname}_bucket{{{pre}le="+Inf"}} {cum[-1]}')
            lines.append(f"{pname}_sum{lab} {_fmt(m['sum'])}")
            lines.append(f"{pname}_count{lab} {m['count']}")
    return "\n".join(lines) + "\n"


def start_http_exporter(
    registry,
    port: int,
    host: str = "127.0.0.1",
    health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
):
    """Serve ``GET /metrics`` (+ ``/healthz`` when ``health_fn`` is
    given) on a localhost port from a daemon thread — the train-side
    surface (CLI ``--metrics-port``).  Returns the ``HTTPServer``;
    call ``.shutdown()`` then ``.server_close()`` to stop.  Localhost
    by default on purpose: this exposes run internals, a reverse proxy
    decides what leaves the box."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route through logging
            log.debug("exporter: " + fmt, *args)

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                self._send(200, prometheus_text(registry).encode(),
                           "text/plain; version=0.0.4")
            elif self.path == "/healthz" and health_fn is not None:
                try:
                    payload = health_fn()
                except Exception as e:  # noqa: BLE001 — health must answer
                    payload = {"ok": False, "error": str(e)}
                self._send(200, (json.dumps(payload) + "\n").encode(),
                           "application/json")
            else:
                self._send(404, b'{"error": "unknown path"}\n',
                           "application/json")

    httpd = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=httpd.serve_forever, name="live-obs-exporter", daemon=True)
    thread.start()
    log.info("live-obs exporter on http://%s:%d/metrics",
             host, httpd.server_address[1])
    return httpd
