"""In-process metric registry + the telemetry sink adapter that feeds it.

The registry is the live observatory's state: named counters, gauges,
and fixed-bound histograms behind ONE lock, cheap enough to update on
every telemetry row and safe to read from any thread (the SLO evaluator
tick, the ``/metrics`` HTTP handler, a probe).  Gauges and histograms
additionally keep a bounded rolling sample window ``(wall_time, value)``
— that window is what the SLO engine's burn-rate math reads
(:mod:`npairloss_tpu.obs.live.slo`).

``RegistrySink`` is the zero-new-call-sites bridge: it implements the
``MetricLogger`` protocol (obs.sinks), so attaching it as an
``extra_sinks`` entry on ``RunTelemetry`` routes every EXISTING Solver
and RetrievalServer metric row into the registry.  It never mutates the
record and never raises out of ``log`` (a live-obs bug must not abort
training or serving; MultiSink would re-raise) — and with no sink
attached, the telemetry streams on disk are byte-identical to a
pre-live-obs build (pinned by tests/test_live.py).

Stdlib-only: no jax, no numpy — the watch feed and the bench_check
alert gate run backend-free.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default fixed histogram bounds: latency-shaped (ms).  Fixed at
# construction — a histogram never grows buckets, so exposition stays
# O(bounds) and two processes observing the same metric agree on shape.
DEFAULT_BOUNDS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                  250.0, 500.0, 1000.0, 2500.0, 5000.0)

# Rolling samples kept per gauge/histogram for SLO window evaluation.
SAMPLE_WINDOW = 4096

_NUMERIC = (int, float)


class Counter:
    """Monotone counter (``inc``); exported as ``<name>_total``."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        self.value += amount


class Gauge:
    """Last-value metric with a rolling ``(t, v)`` sample window."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 window: int = SAMPLE_WINDOW):
        self.name = name
        self.help = help
        self.value: Optional[float] = None
        self.samples: collections.deque = collections.deque(maxlen=window)

    def set(self, value: float, t: Optional[float] = None) -> None:
        self.value = float(value)
        self.samples.append((time.time() if t is None else float(t),
                             self.value))


class Histogram:
    """Fixed-bound histogram: cumulative-style bucket counts + sum +
    count, plus the same rolling sample window gauges keep (so an SLO
    can target raw observations, not just pre-aggregated gauges)."""

    kind = "histogram"

    def __init__(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS,
                 help: str = "", window: int = SAMPLE_WINDOW):
        bs = [float(b) for b in bounds]
        if not bs or bs != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(
                f"histogram {name}: bounds must be ascending and unique, "
                f"got {bounds}")
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(bs)
        # counts[i] = observations <= bounds[i] is derived at exposition;
        # internally we keep per-bucket (non-cumulative) counts, last
        # slot = the +Inf overflow bucket.
        self.bucket_counts = [0] * (len(bs) + 1)
        self.sum = 0.0
        self.count = 0
        self.samples: collections.deque = collections.deque(maxlen=window)

    def observe(self, value: float, t: Optional[float] = None) -> None:
        v = float(value)
        i = 0
        while i < len(self.bounds) and v > self.bounds[i]:
            i += 1
        self.bucket_counts[i] += 1
        self.sum += v
        self.count += 1
        self.samples.append((time.time() if t is None else float(t), v))

    def cumulative_counts(self) -> List[int]:
        out, acc = [], 0
        for c in self.bucket_counts:
            acc += c
            out.append(acc)
        return out


class MetricRegistry:
    """Name -> metric, one lock for registration AND updates.

    Registration is get-or-create (``counter``/``gauge``/``histogram``);
    asking for an existing name with a different kind (or different
    histogram bounds) is a programming error and raises.  ``snapshot``
    and ``samples_since`` are the read APIs the exporter and the SLO
    evaluator consume — both return copies, so readers never hold the
    lock while rendering or doing math.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Any] = {}  # guarded-by: _lock

    def _get(self, name: str, kind, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = kind(name, **kw)
                self._metrics[name] = m
                return m
            if not isinstance(m, kind):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind.kind}")
            if kind is Histogram and "bounds" in kw and \
                    tuple(float(b) for b in kw["bounds"]) != m.bounds:
                raise ValueError(
                    f"histogram {name!r} already registered with bounds "
                    f"{m.bounds}, requested {kw['bounds']}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_BOUNDS,
                  help: str = "") -> Histogram:
        return self._get(name, Histogram, bounds=bounds, help=help)

    # -- thread-safe update shorthands ------------------------------------

    def inc(self, name: str, amount: float = 1.0) -> None:
        with self._lock:
            self.counter(name).inc(amount)

    def set(self, name: str, value: float, t: Optional[float] = None) -> None:
        with self._lock:
            self.gauge(name).set(value, t)

    def observe(self, name: str, value: float,
                t: Optional[float] = None,
                bounds: Sequence[float] = DEFAULT_BOUNDS) -> None:
        with self._lock:
            self.histogram(name, bounds=bounds).observe(value, t)

    # -- read APIs ---------------------------------------------------------

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Point-in-time copy of every metric's exported state."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, m in self._metrics.items():
                if isinstance(m, Counter):
                    out[name] = {"kind": "counter", "value": m.value}
                elif isinstance(m, Gauge):
                    out[name] = {"kind": "gauge", "value": m.value}
                else:
                    out[name] = {
                        "kind": "histogram",
                        "bounds": list(m.bounds),
                        "cumulative_counts": m.cumulative_counts(),
                        "sum": m.sum,
                        "count": m.count,
                    }
        return out

    def samples_since(self, name: str, since: float) -> List[Tuple[float, float]]:
        """Rolling-window samples of a gauge/histogram with
        ``t >= since`` (oldest first); [] for counters/unknown names —
        the SLO evaluator's one read primitive."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or not hasattr(m, "samples"):
                return []
            return [(t, v) for t, v in m.samples if t >= since]

    def view(self, **labels: str) -> "LabeledRegistry":
        """A label-scoped view of this registry (``view(tenant="a")``)
        — see :class:`LabeledRegistry`."""
        return LabeledRegistry(self, labels)


def _sanitize(key: str) -> str:
    """Telemetry keys to metric-name atoms ([a-zA-Z0-9_])."""
    return "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in key)


# -- label dimension ----------------------------------------------------------
#
# A labeled metric lives in the registry under the canonical key
# ``name{k="v",...}`` (labels sorted by key).  The registry itself stays
# label-oblivious — every read/write API keys on the full string — which
# is exactly what lets label-scoped series flow through ``samples_since``
# and hence SLO specs unchanged: an SLO targeting
# ``serve_p99_ms{tenant="acme"}`` needs zero evaluator changes.  The
# exporter (obs/live/export.py) splits the key back apart to render
# Prometheus label syntax.

_LABEL_KEY_RE_CHARS = "label keys must match [a-zA-Z_][a-zA-Z0-9_]*"


def labeled_name(name: str, labels: Dict[str, str]) -> str:
    """Canonical registry key for ``name`` under a fixed label set.
    Loud on malformed labels — a typo'd label must fail at wiring, not
    render broken exposition."""
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        if not k or not (k[0].isalpha() or k[0] == "_") or \
                not all(ch.isalnum() or ch == "_" for ch in k):
            raise ValueError(f"bad metric label key {k!r}: "
                             + _LABEL_KEY_RE_CHARS)
        if any(ch in v for ch in ('"', "\\", "\n")):
            raise ValueError(
                f"bad metric label value {v!r} for {k!r}: quotes, "
                "backslashes and newlines are not representable")
        parts.append(f'{k}="{v}"')
    return f"{name}{{{','.join(parts)}}}"


def split_labels(key: str) -> Tuple[str, str]:
    """Inverse of :func:`labeled_name` for the exporter: registry key ->
    ``(base name, rendered label body)`` — ``("serve_rows", 'tenant="a"')``
    for a labeled key, ``(key, "")`` for a flat one."""
    if key.endswith("}") and "{" in key:
        base, _, rest = key.partition("{")
        return base, rest[:-1]
    return key, ""


class LabeledRegistry:
    """A label-scoped view over a :class:`MetricRegistry`: every metric
    name is rewritten through :func:`labeled_name` with a fixed label
    set.  This is how per-tenant serving reuses tenant-agnostic
    components (AdmissionController, ShadowScorer, freshness probes)
    unchanged — hand them the view and their ``serve_shedding`` becomes
    ``serve_shedding{tenant="acme"}``."""

    def __init__(self, registry: "MetricRegistry", labels: Dict[str, str]):
        if not labels:
            raise ValueError("LabeledRegistry needs >= 1 label")
        self.base = registry
        self.labels = dict(labels)
        labeled_name("_probe", self.labels)  # validate loudly at wiring

    def _n(self, name: str) -> str:
        return labeled_name(name, self.labels)

    def counter(self, name: str, help: str = ""):
        return self.base.counter(self._n(name), help=help)

    def gauge(self, name: str, help: str = ""):
        return self.base.gauge(self._n(name), help=help)

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_BOUNDS,
                  help: str = ""):
        return self.base.histogram(self._n(name), bounds=bounds, help=help)

    def inc(self, name: str, amount: float = 1.0) -> None:
        self.base.inc(self._n(name), amount)

    def set(self, name: str, value: float,
            t: Optional[float] = None) -> None:
        self.base.set(self._n(name), value, t)

    def observe(self, name: str, value: float,
                t: Optional[float] = None,
                bounds: Sequence[float] = DEFAULT_BOUNDS) -> None:
        self.base.observe(self._n(name), value, t, bounds=bounds)

    def get(self, name: str):
        return self.base.get(self._n(name))

    def samples_since(self, name: str, since: float
                      ) -> List[Tuple[float, float]]:
        return self.base.samples_since(self._n(name), since)


class RegistrySink:
    """``MetricLogger`` adapter: telemetry records in, registry updates out.

    Mapping (docs/OBSERVABILITY.md §Live observatory):

      * every record increments counter ``<phase>_rows`` (exported
      with Prometheus's ``_total`` suffix);
      * every numeric top-level key becomes gauge ``<phase>_<key>``
        sampled at the record's ``wall_time`` (so offline replay through
        ``watch`` sees the same timeline the live process saw);
      * ``phase="train"``: finite ``loss`` feeds the ``train_loss``
        histogram; a non-finite loss bumps counter ``train_nonfinite_loss``
        and the consecutive-streak gauge ``train_nonfinite_streak``
        (the divergence guard's pre-rollback early warning);
        ``emb_mag_mean``/``emb_mag_max`` additionally derive
        ``train_emb_mag_spread`` (max/mean — the norm-spread collapse
        signal); rank-stamped rows (obs.fleet) track per-rank max step
        and publish ``fleet_step_lag`` = max-over-ranks minus
        min-over-ranks (live straggler persistence);
      * ``phase="serve"``: ``p99_ms``/``p50_ms`` feed the
        ``serve_latency_ms`` histogram too.

    Non-finite values never reach a gauge (an SLO comparison against
    NaN would silently never fire).  The record dict is NEVER mutated,
    and ``log`` never raises — live obs must not alter or abort the
    stream it observes.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self._nonfinite_streak = 0
        self._rank_steps: Dict[int, int] = {}

    # The envelope + identity keys that are not metric material.
    _SKIP = frozenset(
        ("step", "wall_time", "process_index", "process_count"))

    def log(self, record: Dict[str, Any]) -> None:
        try:
            self._ingest(record)
        except Exception:  # noqa: BLE001 — observing must not abort the run
            pass

    def _ingest(self, record: Dict[str, Any]) -> None:
        reg = self.registry
        phase = str(record.get("phase", "unknown"))
        t = record.get("wall_time")
        t = float(t) if isinstance(t, _NUMERIC) else None
        p = _sanitize(phase)
        # Tenant-stamped rows (multi-tenant serving) land on labeled
        # series — ``serve_p99_ms{tenant="a"}`` — so one tenant's signal
        # cannot hide in the aggregate.  Rows without the stamp map to
        # the same flat names as always.
        tenant = record.get("tenant")
        lab = {"tenant": tenant} if isinstance(tenant, str) and tenant \
            else {}
        reg.inc(labeled_name(f"{p}_rows", lab))
        event = record.get("event")
        if isinstance(event, str):
            # Lifecycle/event rows (resilience retry/rollback/preempt,
            # the serve_drain summary) are markers, not samples: the
            # drain summary carries WHOLE-RUN percentiles whose keys
            # collide with the window rows' — ingesting them as gauge
            # samples would re-fire a long-resolved p99 alert at the
            # final tick.  Count them; never gauge them.
            reg.inc(labeled_name(f"{p}_event_{_sanitize(event)}", lab))
            return
        step = record.get("step")
        if isinstance(step, _NUMERIC):
            reg.set(labeled_name(f"{p}_step", lab), float(step), t)
        for key, value in record.items():
            if key in self._SKIP or key in ("phase", "tenant") or \
                    not isinstance(value, _NUMERIC) or \
                    isinstance(value, bool):
                continue
            if not math.isfinite(value):
                continue
            reg.set(labeled_name(f"{p}_{_sanitize(key)}", lab),
                    float(value), t)
        if phase == "train":
            self._train_extras(record, t)
        elif phase == "serve":
            self._serve_extras(record, t, lab)

    def _train_extras(self, record: Dict[str, Any], t) -> None:
        reg = self.registry
        loss = record.get("loss")
        if isinstance(loss, _NUMERIC) and not isinstance(loss, bool):
            if math.isfinite(loss):
                self._nonfinite_streak = 0
                # _hist suffix: the generic mapping above already owns
                # the ``train_loss`` GAUGE name for this key.
                reg.observe("train_loss_hist", float(loss), t,
                            bounds=(0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 50.0))
            else:
                self._nonfinite_streak += 1
                reg.inc("train_nonfinite_loss")
            reg.set("train_nonfinite_streak",
                    float(self._nonfinite_streak), t)
        mean = record.get("emb_mag_mean")
        mx = record.get("emb_mag_max")
        if isinstance(mean, _NUMERIC) and isinstance(mx, _NUMERIC) \
                and mean and math.isfinite(mean) and math.isfinite(mx):
            reg.set("train_emb_mag_spread", float(mx) / float(mean), t)
        rank = record.get("process_index")
        step = record.get("step")
        if isinstance(rank, int) and isinstance(step, _NUMERIC):
            self._rank_steps[rank] = max(
                self._rank_steps.get(rank, 0), int(step))
            if len(self._rank_steps) > 1:
                vals = self._rank_steps.values()
                reg.set("fleet_step_lag", float(max(vals) - min(vals)), t)

    def _serve_extras(self, record: Dict[str, Any], t,
                      lab: Optional[Dict[str, str]] = None) -> None:
        name = labeled_name("serve_latency_ms", lab or {})
        for key in ("p50_ms", "p99_ms"):
            v = record.get(key)
            if isinstance(v, _NUMERIC) and math.isfinite(v):
                self.registry.observe(name, float(v), t)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass
