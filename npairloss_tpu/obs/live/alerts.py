"""Alert engine + the versioned ``npairloss-alerts-v1`` JSONL contract.

The engine sits between the SLO evaluator and the on-disk alert log:
each evaluation tick hands it the current :class:`slo.SLOStatus` list;
it owns the firing→resolved lifecycle:

  * a spec that starts burning opens ONE alert (dedup: at most one
    active alert per SLO name — a spec burning for an hour is one
    incident, not 3600);
  * flap suppression is two-layered: the evaluator's
    burn/clear-threshold hysteresis (slo.py) plus this engine's
    ``min_ticks`` debounce — the burn state must hold for N consecutive
    ticks before the transition is believed;
  * every transition appends one JSONL record, so the log is an
    event-sourced history a jax-free gate can audit
    (``scripts/bench_check.py --alerts``).

``validate_alert_log`` IS the contract, exactly like
``obs.perf.report.validate_report`` and the fleet validator: consumers
rely on every key it checks, and bench_check file-path-loads THIS
module from a jax-free process — so it must keep ZERO intra-package
imports (stdlib only, self-contained).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

ALERTS_SCHEMA = "npairloss-alerts-v1"
ALERT_STATES = ("firing", "resolved")
# Twin of slo.SEVERITIES — spelled out here (not imported) because this
# module is the one jax-free processes load by file path; the twin is
# pinned equal by tests/test_live.py.
ALERT_SEVERITIES = ("info", "warning", "critical")

# Record keys every alert event carries (pinned by tests/test_live.py).
EVENT_KEYS = (
    "schema", "alert_id", "slo", "metric", "severity", "state", "ts",
    "fired_at", "bad_fraction", "samples", "target", "op", "message",
)


class Alert:
    """One open (or closed) incident for one SLO."""

    def __init__(self, alert_id: str, status, fired_at: float):
        self.alert_id = alert_id
        self.spec = status.spec
        self.fired_at = fired_at
        self.resolved_at: Optional[float] = None
        self.last_status = status

    @property
    def active(self) -> bool:
        return self.resolved_at is None


class AlertEngine:
    """Consume SLO statuses, emit lifecycle events, persist JSONL.

    ``log_path=None`` keeps the history in memory only (tests, the
    /healthz payload); with a path every event is appended
    line-buffered, so a killed process loses at most the current line
    (the telemetry-sink durability contract).  ``min_ticks`` is the
    debounce: a state transition must be observed on N CONSECUTIVE
    ticks before it is believed (1 = trust the evaluator's hysteresis
    alone).  Thread-safe: the serve HTTP handler reads ``active()``
    while the evaluator thread ticks.
    """

    def __init__(self, log_path: Optional[str] = None, min_ticks: int = 1,
                 clock=time.time):
        if min_ticks < 1:
            raise ValueError(f"min_ticks must be >= 1, got {min_ticks}")
        self.log_path = os.path.abspath(log_path) if log_path else None
        self.min_ticks = int(min_ticks)
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Dict[str, Alert] = {}
        self._streaks: Dict[str, int] = {}  # consecutive ticks in new state
        self._seq = 0
        # Alerts a PREVIOUS process segment left open in the log we are
        # appending to: {slo: (alert_id, fired_at, severity)}.  The
        # resumed engine adopts them — still-burning SLOs keep the old
        # incident's id (no duplicate firing event), recovered ones get
        # their resolve under the original id — so a preempt-and-resume
        # run (the supported resilience flow) still writes ONE
        # validator-clean lifecycle per incident.
        self._inherited: Dict[str, Tuple[str, float, str]] = {}
        self.history: List[Dict[str, Any]] = []
        self._f = None
        if self.log_path:
            parent = os.path.dirname(self.log_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._resume_from_log(self.log_path)
            self._f = open(self.log_path, "a", buffering=1)

    def _resume_from_log(self, path: str) -> None:
        """Seed ``_seq`` past every id a previous segment used and
        collect its still-open alerts for adoption.  Best-effort: an
        unreadable or foreign log just starts fresh (the validator
        will say so downstream)."""
        try:
            records = load_alert_log(path)
        except OSError:
            return
        for rec in records:
            if not isinstance(rec, dict) or "alert_id" not in rec:
                continue
            _, _, tail = str(rec["alert_id"]).rpartition("-")
            if tail.isdigit():
                self._seq = max(self._seq, int(tail))
            if rec.get("state") == "firing":
                self._inherited[rec.get("slo")] = (
                    rec["alert_id"], float(rec.get("fired_at", 0.0)),
                    rec.get("severity", "warning"))
            elif rec.get("state") == "resolved":
                self._inherited.pop(rec.get("slo"), None)

    # -- lifecycle ---------------------------------------------------------

    def update(self, statuses: Sequence, now: Optional[float] = None
               ) -> List[Dict[str, Any]]:
        """One evaluation tick; returns the events it emitted."""
        now = self._clock() if now is None else float(now)
        events: List[Dict[str, Any]] = []
        with self._lock:
            for status in statuses:
                name = status.spec.name
                if name in self._inherited:
                    # First sight of an SLO a previous segment left
                    # firing: adopt the open incident (original id and
                    # fired_at) instead of opening a duplicate.
                    aid, fired_at, _sev = self._inherited.pop(name)
                    adopted = Alert(aid, status, fired_at)
                    self._active[name] = adopted
                    self._streaks[name] = 0
                    if not status.burning:
                        events.append(self._close(adopted, status, now))
                    continue
                alert = self._active.get(name)
                if status.burning and alert is None:
                    streak = self._streaks.get(name, 0) + 1
                    self._streaks[name] = streak
                    if streak >= self.min_ticks:
                        self._streaks[name] = 0
                        events.append(self._open(status, now))
                elif not status.burning and alert is not None:
                    streak = self._streaks.get(name, 0) + 1
                    self._streaks[name] = streak
                    if streak >= self.min_ticks:
                        self._streaks[name] = 0
                        events.append(self._close(alert, status, now))
                else:
                    # State agrees with the ledger: reset the debounce
                    # (the transition evidence was not consecutive).
                    self._streaks[name] = 0
                    if alert is not None:
                        alert.last_status = status
        return events

    def _open(self, status, now: float) -> Dict[str, Any]:
        self._seq += 1
        alert = Alert(f"{status.spec.name}-{self._seq}", status, now)
        self._active[status.spec.name] = alert
        return self._emit(alert, status, "firing", now)

    def _close(self, alert: Alert, status, now: float) -> Dict[str, Any]:
        alert.resolved_at = now
        del self._active[alert.spec.name]
        return self._emit(alert, status, "resolved", now)

    def _emit(self, alert: Alert, status, state: str, now: float
              ) -> Dict[str, Any]:
        spec = alert.spec
        verb = "burning" if state == "firing" else "recovered"
        event: Dict[str, Any] = {
            "schema": ALERTS_SCHEMA,
            "alert_id": alert.alert_id,
            "slo": spec.name,
            "metric": spec.metric,
            "severity": spec.severity,
            "state": state,
            "ts": now,
            "fired_at": alert.fired_at,
            "bad_fraction": round(status.bad_fraction, 4),
            "samples": status.samples,
            "target": spec.target,
            "op": spec.op,
            "message": (
                f"{spec.name}: {spec.metric} {verb} — "
                f"{status.bad_fraction:.0%} of {status.samples} sample(s) "
                f"in {spec.window_s:g}s violate {spec.op} {spec.target:g}"
                + (f" (worst {status.worst:g})"
                   if status.worst is not None else "")
            ),
        }
        if state == "resolved":
            event["resolved_at"] = alert.resolved_at
            event["duration_s"] = round(alert.resolved_at - alert.fired_at, 3)
        self.history.append(event)
        if self._f is not None and not self._f.closed:
            self._f.write(json.dumps(event) + "\n")
        return event

    # -- reads -------------------------------------------------------------

    def active(self) -> Dict[str, Dict[str, Any]]:
        """{slo name: summary} of currently-firing alerts (the /healthz
        payload)."""
        with self._lock:
            return {
                name: {
                    "alert_id": a.alert_id,
                    "severity": a.spec.severity,
                    "fired_at": a.fired_at,
                    "bad_fraction": round(
                        a.last_status.bad_fraction, 4),
                }
                for name, a in self._active.items()
            }

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.close()


# -- the npairloss-alerts-v1 contract ----------------------------------------


def load_alert_log(path: str) -> List[Dict[str, Any]]:
    """Read one alert JSONL file; a torn final line (killed writer) is
    tolerated, any OTHER unparseable line is a contract violation
    surfaced by :func:`validate_alert_log` via a sentinel record."""
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail: the crash-durability contract
            records.append({"_bad_line": i + 1})
    return records


def validate_alert_log(records: Sequence[Any]) -> Optional[str]:
    """Schema + lifecycle check; returns an error string or None.

    The contract: every record carries :data:`EVENT_KEYS` with the
    schema tag, a known state/severity, numeric timestamps; per
    alert_id the lifecycle is firing then (optionally) resolved —
    never a resolve without its firing, never two firings, and
    ``fired_at <= resolved_at``; at most one ACTIVE (unresolved) alert
    per SLO name at any point in the stream (the dedup promise).
    """
    open_by_slo: Dict[str, str] = {}
    seen_states: Dict[str, List[str]] = {}
    fired_at: Dict[str, float] = {}
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            return f"record {i} is not an object"
        if "_bad_line" in rec:
            return f"unparseable JSON on line {rec['_bad_line']}"
        if rec.get("schema") != ALERTS_SCHEMA:
            return (f"record {i}: schema must be {ALERTS_SCHEMA!r}, "
                    f"got {rec.get('schema')!r}")
        for key in EVENT_KEYS:
            if key not in rec:
                return f"record {i} missing {key!r}"
        if rec["state"] not in ALERT_STATES:
            return (f"record {i}: state {rec['state']!r} not in "
                    f"{ALERT_STATES}")
        if rec["severity"] not in ALERT_SEVERITIES:
            return (f"record {i}: severity {rec['severity']!r} not in "
                    f"{ALERT_SEVERITIES}")
        for key in ("ts", "fired_at", "bad_fraction"):
            if not isinstance(rec[key], (int, float)):
                return f"record {i}: {key} is not numeric"
        aid, slo, state = rec["alert_id"], rec["slo"], rec["state"]
        states = seen_states.setdefault(aid, [])
        if state == "firing":
            if states:
                return f"record {i}: duplicate firing for alert {aid!r}"
            if slo in open_by_slo:
                return (f"record {i}: alert {aid!r} fired while "
                        f"{open_by_slo[slo]!r} is still active for SLO "
                        f"{slo!r} (dedup violated)")
            open_by_slo[slo] = aid
            fired_at[aid] = float(rec["fired_at"])
        else:
            if states != ["firing"]:
                # covers both a resolve with no firing and a SECOND
                # resolve for one incident — the lifecycle is exactly
                # firing then at most one resolved per alert_id
                return (f"record {i}: resolved alert {aid!r} has "
                        f"lifecycle {states + [state]}, expected "
                        "['firing', 'resolved']")
            if "resolved_at" not in rec or not isinstance(
                    rec["resolved_at"], (int, float)):
                return f"record {i}: resolved event missing resolved_at"
            if rec["resolved_at"] < fired_at.get(aid, float("inf")):
                return (f"record {i}: alert {aid!r} resolved_at "
                        f"{rec['resolved_at']} precedes fired_at")
            if open_by_slo.get(slo) == aid:
                del open_by_slo[slo]
        states.append(state)
    return None


def unresolved_alerts(records: Sequence[Dict[str, Any]]
                      ) -> List[Tuple[str, str, str]]:
    """(alert_id, slo, severity) of alerts still firing at end of log
    — what the bench_check gate refuses when any severity is
    ``critical``.  Call only on a log :func:`validate_alert_log`
    accepted."""
    open_alerts: Dict[str, Tuple[str, str, str]] = {}
    for rec in records:
        if rec["state"] == "firing":
            open_alerts[rec["alert_id"]] = (
                rec["alert_id"], rec["slo"], rec["severity"])
        else:
            open_alerts.pop(rec["alert_id"], None)
    return list(open_alerts.values())
