"""Declarative SLOs: spec, config loader, incremental burn-rate evaluator.

An SLO here is the Gemma-serving-paper shape (PAPERS.md): an operating
target on a live metric — "serve p99 <= 150 ms", "queue depth <= 80% of
the bound" — evaluated over a ROLLING window with a burn-rate
threshold: the SLO is burning when more than ``burn_threshold`` of the
window's samples violate the target.  Burn fraction (not a single
sample) is what separates an incident from boundary noise; the
hysteresis pair ``burn_threshold``/``clear_threshold`` is what keeps an
alert from flapping when the burn fraction dances on the line
(:mod:`npairloss_tpu.obs.live.alerts` owns the firing→resolved
lifecycle).

Config is a JSON file (TOML accepted when the interpreter ships
``tomllib``); every entry maps 1:1 onto :class:`SLOSpec`, and the named
:mod:`watchdogs` can be pulled in by reference so a config composes
"the standard serve watchdogs plus my custom p99 bar" without
restating them.

Stdlib-only (the jax-free package contract — see ``obs/live/__init__``).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Optional, Sequence

SEVERITIES = ("info", "warning", "critical")
OPS = ("<=", ">=")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``metric`` names a registry gauge/histogram sample stream; a sample
    ``v`` is GOOD when ``v <op> target`` holds.  Over the trailing
    ``window_s`` seconds: bad_fraction >= ``burn_threshold`` starts the
    SLO burning; it stops only when bad_fraction <= ``clear_threshold``
    (default: half the burn threshold) — the hysteresis band.  Windows
    with fewer than ``min_samples`` samples keep the PREVIOUS state: a
    healthy SLO stays ok (no evidence is not an incident) and a
    burning one stays burning (silence is not recovery — a wedged
    server emitting nothing must not stand the pager down; resolution
    requires good samples).
    """

    name: str
    metric: str
    op: str
    target: float
    window_s: float = 60.0
    burn_threshold: float = 0.5
    clear_threshold: Optional[float] = None
    min_samples: int = 1
    severity: str = "warning"
    description: str = ""

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(
                f"slo {self.name!r}: op must be one of {OPS}, "
                f"got {self.op!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"slo {self.name!r}: severity must be one of "
                f"{SEVERITIES}, got {self.severity!r}")
        if not (0.0 < self.burn_threshold <= 1.0):
            raise ValueError(
                f"slo {self.name!r}: burn_threshold must be in (0, 1], "
                f"got {self.burn_threshold}")
        if self.window_s <= 0:
            raise ValueError(
                f"slo {self.name!r}: window_s must be > 0, "
                f"got {self.window_s}")
        if self.min_samples < 1:
            raise ValueError(
                f"slo {self.name!r}: min_samples must be >= 1, "
                f"got {self.min_samples}")
        clear = self.resolved_clear_threshold()
        if not (0.0 <= clear <= self.burn_threshold):
            raise ValueError(
                f"slo {self.name!r}: clear_threshold {clear} must sit in "
                f"[0, burn_threshold {self.burn_threshold}] — hysteresis "
                "clears BELOW where it fires")

    def resolved_clear_threshold(self) -> float:
        if self.clear_threshold is not None:
            return self.clear_threshold
        return self.burn_threshold / 2.0

    def good(self, value: float) -> bool:
        return value <= self.target if self.op == "<=" \
            else value >= self.target


@dataclasses.dataclass
class SLOStatus:
    """One spec's evaluation at one instant."""

    spec: SLOSpec
    burning: bool
    bad_fraction: float
    samples: int
    worst: Optional[float] = None  # most-violating sample in the window

    def to_dict(self) -> Dict[str, Any]:
        return {
            "slo": self.spec.name,
            "metric": self.spec.metric,
            "burning": self.burning,
            "bad_fraction": round(self.bad_fraction, 4),
            "samples": self.samples,
            "worst": self.worst,
            "severity": self.spec.severity,
        }


class SLOEvaluator:
    """Evaluate specs over a registry's rolling sample windows.

    Stateful only for hysteresis: each spec's previous burning state
    decides which threshold applies (burn to START, clear to STOP), so
    a bad_fraction wobbling between the two cannot flap.  The evaluator
    itself holds no samples — the registry's windows are the one store,
    which is exactly what lets the in-process feed and the offline
    ``watch`` feed share this class unchanged.
    """

    def __init__(self, specs: Sequence[SLOSpec], registry):
        import threading

        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.specs = list(specs)
        self.registry = registry
        self._burning: Dict[str, bool] = \
            {s.name: False for s in self.specs}  # guarded-by: _lock
        # Hysteresis state is written only by committed evaluations;
        # the lock serializes the tick thread against /healthz scrapes
        # (which evaluate read-only — a monitoring poll must never
        # advance alerting state, see ``commit``).  The guarded-by
        # annotation is enforced by `staticcheck` (docs/STATICCHECK.md).
        self._lock = threading.Lock()

    def evaluate(self, now: Optional[float] = None,
                 commit: bool = True) -> List[SLOStatus]:
        """One evaluation.  ``commit=False`` is the scrape mode
        (/healthz, watch summaries): the hysteresis decision is made
        against the CURRENT state but never written back, so an
        off-tick poll landing on a transient burn cannot open or close
        an alert the tick-driven engine alone would not have."""
        now = time.time() if now is None else float(now)
        out: List[SLOStatus] = []
        with self._lock:
            for spec in self.specs:
                samples = self.registry.samples_since(
                    spec.metric, now - spec.window_s)
                # Clamp to the window's leading edge too: offline
                # replay hands ``now`` mid-stream and must not see the
                # future.
                vals = [v for t, v in samples if t <= now]
                n = len(vals)
                was = self._burning[spec.name]
                if n < spec.min_samples:
                    # No evidence is not an incident — but it is not
                    # RECOVERY either: a burning SLO holds through an
                    # empty window (a wedged server emitting nothing is
                    # the worst version of the incident; standing the
                    # pager down on silence would be exactly wrong).
                    # Resolution requires good samples.
                    out.append(SLOStatus(spec, was, 0.0, n))
                    continue
                bad = [v for v in vals if not spec.good(v)]
                frac = len(bad) / n
                if was:
                    burning = frac > spec.resolved_clear_threshold()
                else:
                    burning = frac >= spec.burn_threshold
                if commit:
                    self._burning[spec.name] = burning
                worst = None
                if bad:
                    worst = max(bad) if spec.op == "<=" else min(bad)
                out.append(SLOStatus(spec, burning, frac, n, worst))
        return out

    def status_dict(self, now: Optional[float] = None) -> Dict[str, Any]:
        """{slo name: status} — the /healthz enrichment payload.
        Read-only: scraping health never advances hysteresis."""
        return {s.spec.name: s.to_dict()
                for s in self.evaluate(now, commit=False)}


# -- config loading -----------------------------------------------------------

_SPEC_KEYS = {f.name for f in dataclasses.fields(SLOSpec)}


def _spec_from_dict(d: Dict[str, Any], source: str) -> SLOSpec:
    unknown = set(d) - _SPEC_KEYS
    if unknown:
        raise ValueError(
            f"{source}: unknown SLO keys {sorted(unknown)} "
            f"(known: {sorted(_SPEC_KEYS)})")
    missing = {"name", "metric", "op", "target"} - set(d)
    if missing:
        raise ValueError(f"{source}: SLO entry missing {sorted(missing)}")
    return SLOSpec(**d)


def load_slo_config(path: str) -> List[SLOSpec]:
    """Parse an SLO config file into specs.

    JSON shape (TOML is isomorphic when ``tomllib`` is available)::

        {
          "watchdogs": ["serve"],            # named presets (optional)
          "slos": [
            {"name": "p99", "metric": "serve_p99_ms", "op": "<=",
             "target": 150.0, "window_s": 30, "burn_threshold": 0.5,
             "severity": "critical"}
          ]
        }

    ``watchdogs`` pulls in :func:`watchdogs.default_watchdogs` presets
    by kind; explicit ``slos`` entries with the same ``name`` override
    the preset of that name.  Validation is loud — a typo'd threshold
    must fail at load, not silently never fire.
    """
    raw = None
    if path.endswith(".toml"):
        try:
            import tomllib  # Python >= 3.11
        except ImportError as e:
            raise ValueError(
                f"{path}: TOML config needs a tomllib-equipped "
                "interpreter; use the JSON form"
            ) from e
        with open(path, "rb") as f:
            raw = tomllib.load(f)
    else:
        with open(path) as f:
            raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: SLO config must be an object")
    unknown = set(raw) - {"watchdogs", "slos"}
    if unknown:
        raise ValueError(
            f"{path}: unknown top-level keys {sorted(unknown)}")
    specs: Dict[str, SLOSpec] = {}
    kinds = raw.get("watchdogs", [])
    if kinds:
        from npairloss_tpu.obs.live.watchdogs import default_watchdogs

        if not isinstance(kinds, list):
            raise ValueError(f"{path}: 'watchdogs' must be a list of kinds")
        for kind in kinds:
            for spec in default_watchdogs(kind):
                specs[spec.name] = spec
    entries = raw.get("slos", [])
    if not isinstance(entries, list):
        raise ValueError(f"{path}: 'slos' must be a list")
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise ValueError(f"{path}: slos[{i}] is not an object")
        spec = _spec_from_dict(entry, f"{path}: slos[{i}]")
        specs[spec.name] = spec
    if not specs:
        raise ValueError(f"{path}: config defines no SLOs")
    return list(specs.values())
