"""Live observatory — the ONLINE half of the observability stack.

Everything under ``obs/`` so far (sinks/traces, perf reports, fleet
reports) is post-hoc: artifacts on disk, analyzed after the fact.  This
package closes the loop while the process is still running
(docs/OBSERVABILITY.md §Live observatory):

  * :mod:`registry`  — lock-guarded in-process metric registry
    (counters / gauges / fixed-bound histograms) fed by a
    ``MetricLogger``-protocol sink adapter, so the EXISTING telemetry
    streams flow in with zero new call sites;
  * :mod:`slo`       — declarative SLO specs (metric, target, rolling
    window, burn-rate threshold) loaded from JSON/TOML, evaluated
    incrementally over the registry's sample windows;
  * :mod:`alerts`    — severities, hysteresis/dedup, a firing→resolved
    lifecycle persisted as the versioned ``npairloss-alerts-v1`` JSONL
    contract (``validate_alert_log`` IS the contract, like the perf and
    fleet report validators);
  * :mod:`watchdogs` — domain SLOs wired to signals the repo already
    computes (serve p99 / queue saturation, post-warmup compiles, train
    throughput vs the committed BENCH bar, non-finite-loss streaks,
    fleet straggler lag, snapshot/index staleness, embedding collapse);
  * :mod:`export`    — Prometheus text exposition (``/metrics``) and
    the localhost HTTP exporter the train side mounts;
  * :mod:`watch`     — the OFFLINE feed: tail a run directory's
    telemetry JSONL (per-rank files included) through the SAME
    evaluator — one engine, two feeds.

IMPORTANT: this whole package must stay importable WITHOUT jax (stdlib
only) — ``watch`` runs backend-free, and ``scripts/bench_check.py
--alerts`` file-path-loads the alert validator from a jax-free process
(the bench-parent contract).
"""

from npairloss_tpu.obs.live.alerts import (
    ALERTS_SCHEMA,
    Alert,
    AlertEngine,
    load_alert_log,
    unresolved_alerts,
    validate_alert_log,
)
from npairloss_tpu.obs.live.live import LiveObservatory
from npairloss_tpu.obs.live.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    RegistrySink,
)
from npairloss_tpu.obs.live.slo import (
    SLOSpec,
    SLOStatus,
    SLOEvaluator,
    load_slo_config,
)
from npairloss_tpu.obs.live.watchdogs import bench_floor_emb_per_sec, default_watchdogs
from npairloss_tpu.obs.live.export import prometheus_text, start_http_exporter
from npairloss_tpu.obs.live.watch import (
    reconcile_remediation,
    replay_records,
    watch_run_dir,
)

__all__ = [
    "ALERTS_SCHEMA",
    "Alert",
    "AlertEngine",
    "Counter",
    "Gauge",
    "Histogram",
    "LiveObservatory",
    "MetricRegistry",
    "RegistrySink",
    "SLOEvaluator",
    "SLOSpec",
    "SLOStatus",
    "bench_floor_emb_per_sec",
    "default_watchdogs",
    "load_alert_log",
    "load_slo_config",
    "prometheus_text",
    "reconcile_remediation",
    "replay_records",
    "start_http_exporter",
    "unresolved_alerts",
    "validate_alert_log",
    "watch_run_dir",
]
