"""``watch RUNDIR`` — the offline feed of the ONE SLO engine.

A live process evaluates SLOs over rows as they are emitted; ``watch``
evaluates the SAME specs over the rows a run directory already holds
(and, with ``follow=True``, keeps tailing as ranks append) — one
evaluator, two feeds.  Replay is deterministic: each record's own
``wall_time`` drives the evaluation clock, so re-running watch over the
same stream produces the same alert sequence the in-process engine
would have produced from those rows (pinned by tests/test_live.py).

Reads both telemetry layouts: the legacy ``metrics.jsonl`` and the
fleet observatory's rank-suffixed ``telemetry.r<k>.jsonl`` files —
per-rank streams merge by ``wall_time`` so the fleet straggler watchdog
sees the interleaved frontier.  Torn tail lines (a rank mid-write) are
skipped, never fatal — the fleet aggregator's contract.

Stdlib-only and backend-free: watch must run on the box where the
artifacts are, whether or not jax can even initialize there.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from npairloss_tpu.obs.live.live import LiveObservatory
from npairloss_tpu.obs.live.slo import SLOSpec

WATCH_ALERTS_FILENAME = "alerts.watch.jsonl"
REMEDIATION_FILENAME = "remediation.jsonl"
QUALITY_FILENAME = "quality.jsonl"


def _load_quality():
    """File-path-load ``obs.quality.report`` (self-contained, stdlib
    only) WITHOUT importing its package — whose siblings pull jax, and
    watch must stay backend-free (the remediate loader's pattern)."""
    import importlib.util
    import sys

    name = "npairloss_tpu.obs.quality.report"
    if name not in sys.modules:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "quality", "report.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def _load_remediate():
    """File-path-load ``resilience.remediate`` (self-contained, stdlib
    only) WITHOUT importing the resilience package — whose ``__init__``
    pulls the jax-needing snapshot module, and watch must stay
    backend-free (the bench_check loader pattern)."""
    import importlib.util
    import sys

    name = "npairloss_tpu.resilience.remediate"
    if name not in sys.modules:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "resilience", "remediate.py")
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def reconcile_remediation(
    rem_records: Sequence[Dict[str, Any]],
    alert_events: Sequence[Dict[str, Any]],
) -> Dict[str, Any]:
    """Join one run's remediation audit against an alert-event stream
    (the watch replay's, or the live log's): every resolved alert of an
    SLO some policy ACTS ON should have an action, and every action's
    alert should eventually resolve.  Both mismatch directions are
    reported — ``alert_resolved_no_action`` (the alert healed on its
    own, or the actuator missed it) and ``action_no_resolution`` (the
    action ran but the incident never stood down) — as evidence for the
    operator, not a gate (bench_check --remediation owns the gating)."""
    # Dry-run attempts are rehearsals, not actions: they still mark
    # their SLO as policy-covered (so resolved-with-no-action reporting
    # works in a dry run) but must never read as "the actuator resolved
    # this incident".
    acted = {str(r.get("alert_id")) for r in rem_records
             if isinstance(r, dict) and not r.get("dry_run")}
    policy_slos = {r.get("slo") for r in rem_records
                   if isinstance(r, dict)}
    fired = {e["alert_id"]: e["slo"] for e in alert_events
             if e.get("state") == "firing"}
    resolved = {e["alert_id"] for e in alert_events
                if e.get("state") == "resolved"}
    return {
        "records": len(rem_records),
        "matched": sorted(acted & resolved),
        "alert_resolved_no_action": sorted(
            aid for aid, slo in fired.items()
            if aid in resolved and slo in policy_slos
            and aid not in acted),
        "action_no_resolution": sorted(acted - resolved),
    }


def telemetry_paths(run_dir: str) -> List[str]:
    """The run dir's metric streams: legacy + rank-suffixed layouts."""
    paths = []
    legacy = os.path.join(run_dir, "metrics.jsonl")
    if os.path.exists(legacy):
        paths.append(legacy)
    paths.extend(sorted(glob.glob(
        os.path.join(run_dir, "telemetry.r*.jsonl"))))
    return paths


class _Tail:
    """Byte-offset tailer for one JSONL stream: each poll returns the
    newly-completed lines; a torn final line stays buffered until its
    newline arrives (counted, never parsed half-written)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.torn = 0

    def poll(self) -> List[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        # Only consume up to the last newline: the tail beyond it is a
        # line still being written.
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return []
        self.offset += cut + 1
        records = []
        for line in chunk[:cut + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line.decode("utf-8", "replace")))
            except ValueError:
                self.torn += 1
        return records


def replay_records(
    records: Sequence[Dict[str, Any]],
    specs: Sequence[SLOSpec],
    out_path: Optional[str] = None,
    min_ticks: int = 1,
) -> Tuple[LiveObservatory, List[Dict[str, Any]]]:
    """Deterministic offline evaluation: feed ``records`` (already
    merged, ``wall_time``-ascending) through a fresh observatory,
    ticking at every record's own wall_time.  Returns the observatory
    and the full alert-event list — the function BOTH ``watch`` and the
    in-process-agreement test call, so the two feeds cannot drift."""
    obs = LiveObservatory(specs, out_dir=None, min_ticks=min_ticks)
    if out_path:
        from npairloss_tpu.obs.live.alerts import AlertEngine

        obs.alerts = AlertEngine(out_path, min_ticks=min_ticks)
    events: List[Dict[str, Any]] = []
    for rec in records:
        obs.sink.log(rec)
        t = rec.get("wall_time")
        if isinstance(t, (int, float)):
            events.extend(obs.tick(now=float(t)))
    return obs, events


def watch_run_dir(
    run_dir: str,
    specs: Sequence[SLOSpec],
    follow: bool = False,
    poll_s: float = 1.0,
    out_path: Optional[str] = None,
    emit=None,
    stop_after_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Evaluate ``specs`` over a run directory's telemetry.

    One-shot (``follow=False``): replay everything on disk, return the
    summary.  Follow mode: keep tailing all streams, ticking each new
    record at its wall_time, until ``stop_after_s`` (None = until
    interrupted).  ``emit`` (callable) receives each alert event as it
    happens — the CLI prints them.  Alert events land in ``out_path``
    (default ``<run_dir>/alerts.watch.jsonl`` — NOT alerts.jsonl, so
    watching a live run never interleaves with the in-process engine's
    own log).
    """
    run_dir = os.path.abspath(run_dir)
    paths = telemetry_paths(run_dir)
    if not paths:
        raise FileNotFoundError(
            f"{run_dir}: no metrics.jsonl or telemetry.r*.jsonl stream")
    if out_path is None:
        out_path = os.path.join(run_dir, WATCH_ALERTS_FILENAME)
    obs = LiveObservatory(specs, out_dir=None)
    from npairloss_tpu.obs.live.alerts import AlertEngine

    obs.alerts = AlertEngine(out_path)
    tails = [_Tail(p) for p in paths]
    rows = 0
    last_t: List[Optional[float]] = [None]
    events: List[Dict[str, Any]] = []

    def drain_once() -> int:
        nonlocal rows
        fresh: List[Dict[str, Any]] = []
        for tail in tails:
            fresh.extend(tail.poll())
        fresh.sort(key=lambda r: r.get("wall_time", 0))
        for rec in fresh:
            obs.sink.log(rec)
            t = rec.get("wall_time")
            if isinstance(t, (int, float)):
                last_t[0] = float(t)
                for ev in obs.tick(now=float(t)):
                    events.append(ev)
                    if emit is not None:
                        emit(ev)
        rows += len(fresh)
        return len(fresh)

    t0 = time.time()
    drain_once()
    while follow:
        if stop_after_s is not None and time.time() - t0 >= stop_after_s:
            break
        time.sleep(poll_s)
        drain_once()
    obs.alerts.close()
    active = obs.alerts.active()
    remediation: Optional[Dict[str, Any]] = None
    rem_path = os.path.join(run_dir, REMEDIATION_FILENAME)
    if os.path.exists(rem_path):
        # The run remediated: validate its audit log and reconcile it
        # against the alert lifecycle the replay just reproduced — a
        # resolved alert with no action and an action with no
        # resolution are both reported.
        rem = _load_remediate()
        rem_records = rem.load_remediation_log(rem_path)
        err = rem.validate_remediation_log(rem_records)
        remediation = {
            "log": rem_path,
            "valid": err is None,
            **({"error": err} if err else {}),
            **reconcile_remediation(rem_records, events),
        }
    quality: Optional[Dict[str, Any]] = None
    q_path = os.path.join(run_dir, QUALITY_FILENAME)
    if os.path.exists(q_path):
        # The run shadow-scored: validate the npairloss-quality-v1 log
        # and surface the aggregate recall view next to the replayed
        # alert lifecycle — the recall-floor firing the replay just
        # reproduced and the windows that caused it read side by side.
        qmod = _load_quality()
        q_records = qmod.load_quality_report(q_path)
        qerr = qmod.validate_quality_report(q_records)
        quality = {
            "log": q_path,
            "valid": qerr is None,
            **({"error": qerr} if qerr
               else qmod.quality_summary(q_records)),
        }
    return {
        "run_dir": run_dir,
        "streams": paths,
        "rows": rows,
        "torn_lines": sum(t.torn for t in tails),
        "alerts_log": out_path,
        "events": len(events),
        "alerts_active": len(active),
        "active": active,
        # Status as of the LAST ingested record's wall time — a replay
        # of a long-finished run evaluated at real now would see an
        # empty window and print every SLO as ok right next to an
        # active alert in the same summary.
        "slo": obs.evaluator.status_dict(last_t[0]),
        # Remediation reconciliation only when the run remediated, and
        # the quality view only when it shadow-scored (the absent-key
        # contract: no log, no block).
        **({"remediation": remediation}
           if remediation is not None else {}),
        **({"quality": quality} if quality is not None else {}),
    }
