"""Domain watchdogs — named SLO presets wired to signals the repo
already computes.

Each builder returns an :class:`slo.SLOSpec` targeting a metric the
:class:`registry.RegistrySink` (or a freshness probe) already
publishes from the EXISTING telemetry streams — no new instrumentation
call sites.  ``default_watchdogs(kind)`` bundles the standard set per
run kind; an SLO config pulls them in by name (``"watchdogs":
["serve"]``) and can override any of them by restating the name
(docs/OBSERVABILITY.md §Live observatory has the runbook: which
watchdog means what, and what to do when it fires).

Stdlib-only, like the whole package.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from npairloss_tpu.obs.live.slo import SLOSpec

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
LAST_GOOD = os.path.join(REPO, "bench_cache", "last_good.json")


# -- serve watchdogs ----------------------------------------------------------


def serve_p99(target_ms: float = 250.0, window_s: float = 30.0,
              severity: str = "critical") -> SLOSpec:
    """Tail latency: the serve window rows' p99 (the Gemma-serving
    operating target).  Fires when half the recent windows blow the
    bar — one slow window is noise, a burning half-minute is an
    incident."""
    return SLOSpec(
        name="serve_p99", metric="serve_p99_ms", op="<=",
        target=target_ms, window_s=window_s, burn_threshold=0.5,
        min_samples=2, severity=severity,
        description="serve p99 latency over the rolling window",
    )


def serve_queue_saturation(max_queue: int = 256,
                           fraction: float = 0.8,
                           window_s: float = 30.0) -> SLOSpec:
    """Admission-queue depth approaching the backpressure bound: the
    engine is falling behind offered load.  Past the bound, submits
    reject — this fires BEFORE clients start seeing QueueFullError."""
    return SLOSpec(
        name="serve_queue_saturation", metric="serve_queue_depth",
        op="<=", target=float(max_queue) * fraction, window_s=window_s,
        burn_threshold=0.5, min_samples=2, severity="warning",
        description="admission queue depth vs the backpressure bound",
    )


def post_warmup_compile(window_s: float = 3600.0) -> SLOSpec:
    """The strict serve compile guard's counting twin, non-fatal: ANY
    post-warmup XLA compile in the serving hot path is an SLO burn
    (the window row carries ``compiles_after_warmup`` only when > 0).
    Where ``NPAIRLOSS_SERVE_COMPILE_GUARD=strict`` would kill the
    server, this pages instead — the production posture."""
    return SLOSpec(
        name="serve_post_warmup_compile",
        metric="serve_compiles_after_warmup", op="<=", target=0.0,
        window_s=window_s, burn_threshold=0.01, min_samples=1,
        severity="warning",
        description="post-warmup XLA compiles in the serving hot path",
    )


def serve_recall_floor(k: int = 10, floor: float = 0.95,
                       window_s: float = 120.0,
                       severity: str = "critical") -> SLOSpec:
    """Online answer quality (docs/OBSERVABILITY.md §Quality
    observatory): the shadow scorer's live recall@K estimate vs the
    flat brute-force oracle.  An approximate index silently trading
    recall for speed is the regression the offline parity gate catches
    a build too late — this fires while it happens.  No shadow rows
    (``--shadow-rate 0``) = no samples = stays ok."""
    return SLOSpec(
        name="serve_recall_floor", metric=f"serve_recall_at_{k}",
        op=">=", target=floor, window_s=window_s, burn_threshold=0.5,
        min_samples=1, severity=severity,
        description=f"shadow-estimated recall@{k} vs the exact oracle",
    )


def serve_score_gap(max_gap: float = 0.05,
                    window_s: float = 120.0) -> SLOSpec:
    """The shadow scorer's companion signal: how much top-1 similarity
    the served answer leaves on the table vs the exact scan.  Recall
    can hold while scores quietly degrade (quantization drift) — the
    gap catches that earlier, at warning severity."""
    return SLOSpec(
        name="serve_score_gap", metric="serve_shadow_score_gap",
        op="<=", target=max_gap, window_s=window_s, burn_threshold=0.5,
        min_samples=1, severity="warning",
        description="shadow top-1 score gap vs the exact oracle",
    )


def index_staleness(max_age_s: float = 3600.0,
                    severity: str = "warning") -> SLOSpec:
    """Gallery freshness (ROADMAP item 4): the served index's commit
    age.  A retrieval tier answering from an hour-old gallery is the
    recommendation-system failure mode (Tensor Casting, PAPERS.md)."""
    return SLOSpec(
        name="index_staleness", metric="serve_index_age_s", op="<=",
        target=max_age_s, window_s=max(max_age_s / 4, 60.0),
        burn_threshold=0.5, min_samples=1, severity=severity,
        description="age of the served gallery index commit",
    )


def model_staleness(max_age_s: float = 4 * 3600.0,
                    severity: str = "warning") -> SLOSpec:
    """Model freshness: wall age of the restored snapshot behind the
    encode path (absent-metric = ok for embedding-only serving)."""
    return SLOSpec(
        name="model_staleness", metric="serve_model_age_s", op="<=",
        target=max_age_s, window_s=max(max_age_s / 4, 60.0),
        burn_threshold=0.5, min_samples=1, severity=severity,
        description="wall age of the restored model snapshot",
    )


# -- train watchdogs ----------------------------------------------------------


def nonfinite_loss_streak(window_s: float = 120.0) -> SLOSpec:
    """Consecutive non-finite losses — the divergence guard's
    pre-rollback early warning: the guard acts at ``patience``; this
    pages at the FIRST streak so a human sees the run destabilizing
    before params are rolled back."""
    return SLOSpec(
        name="train_nonfinite_streak", metric="train_nonfinite_streak",
        op="<=", target=0.0, window_s=window_s, burn_threshold=0.25,
        min_samples=1, severity="critical",
        description="consecutive non-finite training losses",
    )


def train_throughput_floor(floor_emb_per_sec: float,
                           window_s: float = 600.0) -> SLOSpec:
    """Throughput vs the committed BENCH bar (needs ``--perf-metrics``
    rows): a multi-day run silently degrading to half its benched
    emb/s is exactly the regression the post-hoc gate catches a round
    too late.  Pass :func:`bench_floor_emb_per_sec` (with margin) as
    the floor — on hardware that never benched, don't arm this."""
    return SLOSpec(
        name="train_throughput_floor", metric="perf_emb_per_sec",
        op=">=", target=floor_emb_per_sec, window_s=window_s,
        burn_threshold=0.5, min_samples=2, severity="warning",
        description="training emb/s vs the committed bench floor",
    )


def snapshot_staleness(max_age_s: float = 1800.0) -> SLOSpec:
    """Time since the newest committed snapshot (fed by the snapshot
    probe): a stalled snapshot cadence silently converts the next
    preemption from a resume into lost hours."""
    return SLOSpec(
        name="snapshot_staleness", metric="train_snapshot_age_s",
        op="<=", target=max_age_s, window_s=max(max_age_s / 4, 60.0),
        burn_threshold=0.5, min_samples=1, severity="warning",
        description="age of the newest committed training snapshot",
    )


def embedding_collapse(threshold: float = 0.98,
                       window_s: float = 600.0) -> SLOSpec:
    """Embedding-space collapse from the PR 2 health signals (needs
    ``--health-metrics`` rows): the mean negative-mining threshold
    (mean pairwise cosine of the mined frontier) trending to ~1 means
    every pair looks alike — the space is degenerating.  The
    companion norm-spread signal is ``train_emb_mag_spread`` (max/mean
    row norm, derived by the sink)."""
    return SLOSpec(
        name="embedding_collapse", metric="train_an_threshold_mean",
        op="<=", target=threshold, window_s=window_s,
        burn_threshold=0.5, min_samples=3, severity="warning",
        description="mean pairwise cosine of mined negatives "
                    "trending degenerate",
    )


def mining_margin_floor(floor: float = 0.05,
                        window_s: float = 600.0) -> SLOSpec:
    """Mining-health early warning (needs ``--health-metrics
    --mining-health`` rows): the mean AP−AN threshold margin — how far
    the mined positive frontier sits above the mined negative frontier.
    A margin collapsing to ~0 means every pair looks alike: the
    embedding-space collapse signature, visible as a quality TREND
    before ``an_threshold_mean`` crosses the collapse guard's bar."""
    return SLOSpec(
        name="mining_margin_floor", metric="train_ap_an_margin_mean",
        op=">=", target=floor, window_s=window_s, burn_threshold=0.5,
        min_samples=3, severity="warning",
        description="mean AP-AN mining-threshold margin (collapse trend)",
    )


def fleet_straggler(max_step_lag: float = 2.0,
                    window_s: float = 300.0) -> SLOSpec:
    """Persistent straggler lag across rank-stamped streams (the fleet
    observatory's offline skew report, live): max-minus-min of the
    per-rank step frontier.  Transient jitter self-heals; a rank
    persistently N steps behind is a sick host."""
    return SLOSpec(
        name="fleet_straggler", metric="fleet_step_lag", op="<=",
        target=max_step_lag, window_s=window_s, burn_threshold=0.5,
        min_samples=3, severity="warning",
        description="per-rank step-frontier lag (straggler persistence)",
    )


# -- presets ------------------------------------------------------------------


def bench_floor_emb_per_sec(margin: float = 0.5,
                            last_good_path: str = LAST_GOOD
                            ) -> Optional[float]:
    """The committed bench headline (bench_cache/last_good.json) scaled
    by ``margin`` — the default train-throughput floor.  None when no
    committed measurement exists (fresh checkout, new hardware):
    DON'T arm the throughput watchdog on a floor you never measured."""
    try:
        with open(last_good_path) as f:
            payload = json.load(f).get("payload") or {}
    except (OSError, ValueError):
        return None
    value = payload.get("value")
    if isinstance(value, (int, float)) and value > 0:
        return float(value) * float(margin)
    return None


def default_watchdogs(kind: str, max_queue: int = 256,
                      bench_floor: Optional[float] = None
                      ) -> List[SLOSpec]:
    """The standard watchdog set for a run kind.

    ``serve``: p99, queue saturation, post-warmup compiles, index +
    model staleness, shadow recall floor + score gap (quality SLOs —
    without shadow rows they simply never see a sample and stay ok).
    ``train``: non-finite streak, snapshot staleness, embedding
    collapse, mining-margin floor, fleet straggler lag, plus the
    throughput floor when ``bench_floor`` is given (see
    :func:`bench_floor_emb_per_sec` — never armed implicitly, a CPU box
    must not page against a TPU bar).
    """
    if kind == "serve":
        return [
            serve_p99(),
            serve_queue_saturation(max_queue=max_queue),
            post_warmup_compile(),
            index_staleness(),
            model_staleness(),
            serve_recall_floor(),
            serve_score_gap(),
        ]
    if kind == "train":
        specs = [
            nonfinite_loss_streak(),
            snapshot_staleness(),
            embedding_collapse(),
            mining_margin_floor(),
            fleet_straggler(),
        ]
        if bench_floor is not None:
            specs.append(train_throughput_floor(bench_floor))
        return specs
    raise ValueError(
        f"unknown watchdog kind {kind!r} (expected 'train' or 'serve')")
