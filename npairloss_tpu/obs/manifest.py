"""Run manifests — the "what exactly ran?" snapshot written at run start.

TensorFlow's event pipeline and the TPU-v4 scaling analyses both lean on
one discipline: every run directory carries enough provenance to
re-derive its numbers (config, topology, code version).  ``RunManifest``
captures that here: config snapshot, device/mesh topology, package
version, git sha, host info — written as ``manifest.json`` before the
first step so even a crashed run is diagnosable from disk.

Stdlib only at import time; jax and the package itself are consulted
lazily (and only if already imported) so this module stays usable from
jax-free processes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import subprocess
import sys
import time
from typing import Any, Dict, Optional


def git_sha(repo_dir: Optional[str] = None) -> Optional[str]:
    """HEAD sha of the repo containing ``repo_dir`` (default: this
    package's checkout), or None outside a git checkout / without git.

    With no ``repo_dir``, the sha is recorded only when THIS file is
    actually tracked by the enclosing repo — a pip-installed package
    whose site-packages merely sits inside some unrelated git checkout
    (a dotfiles repo, a project venv) must record None, not that repo's
    HEAD as bogus code provenance.
    """
    anchor = None
    if repo_dir is None:
        # Anchor on the package root __init__.py (tracked since the
        # seed commit) rather than this file, which may be newer than
        # the checkout's HEAD in mid-development trees.
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        anchor = os.path.join(pkg_dir, "__init__.py")
        repo_dir = pkg_dir
    try:
        if anchor is not None:
            tracked = subprocess.run(
                ["git", "-C", repo_dir, "ls-files", "--error-unmatch",
                 anchor],
                capture_output=True, timeout=10,
            )
            if tracked.returncode != 0:
                return None
        out = subprocess.run(
            ["git", "-C", repo_dir, "rev-parse", "HEAD"],
            capture_output=True, timeout=10,
        )
        if out.returncode == 0:
            return out.stdout.decode().strip() or None
    except Exception:
        pass
    return None


def package_version() -> Optional[str]:
    """npairloss_tpu.__version__ if the package is importable."""
    try:
        import npairloss_tpu

        return npairloss_tpu.__version__
    except Exception:
        return None


def device_topology() -> Optional[Dict[str, Any]]:
    """Mesh-relevant device/process topology from jax — but only if jax
    is ALREADY imported (never force a backend init from telemetry; a
    hung plugin discovery must not be observability's fault)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return {
            "default_backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": [
                {
                    "id": d.id,
                    "platform": d.platform,
                    "device_kind": d.device_kind,
                    "process_index": d.process_index,
                }
                for d in jax.devices()
            ],
        }
    except Exception:
        return None


@dataclasses.dataclass
class RunManifest:
    """One run's provenance record.  ``config`` is the caller's config
    snapshot (solver/loss/model/net — anything JSON-able; non-JSON
    leaves are stringified on write)."""

    run_id: str
    created: float = dataclasses.field(default_factory=time.time)
    config: Optional[Dict[str, Any]] = None
    topology: Optional[Dict[str, Any]] = None
    mesh: Optional[Dict[str, Any]] = None
    package_version: Optional[str] = None
    git_sha: Optional[str] = None
    argv: Optional[list] = None
    host: Optional[Dict[str, Any]] = None
    # Fleet identity of the WRITING process (obs.fleet):
    # {process_index, process_count, local_device_ids}.  ``topology``
    # above records what jax sees; this records what the telemetry
    # layer stamped — in a harness-declared fleet (no jax.distributed
    # cluster) the two legitimately differ, and the aggregator trusts
    # this one.
    fleet: Optional[Dict[str, Any]] = None
    extra: Optional[Dict[str, Any]] = None

    @classmethod
    def collect(
        cls,
        run_id: str,
        config: Optional[Dict[str, Any]] = None,
        mesh: Optional[Dict[str, Any]] = None,
        fleet: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> "RunManifest":
        """Gather the ambient provenance (version/sha/topology/host)
        around the caller's config snapshot."""
        return cls(
            run_id=run_id,
            config=config,
            topology=device_topology(),
            mesh=mesh,
            package_version=package_version(),
            git_sha=git_sha(),
            argv=list(sys.argv),
            host={
                "platform": platform.platform(),
                "python": platform.python_version(),
                "pid": os.getpid(),
            },
            fleet=fleet,
            extra=extra,
        )

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def write(self, path: str) -> str:
        """Write ``manifest.json`` atomically; returns the path."""
        path = os.path.abspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path
